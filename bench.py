"""Driver benchmark: serving throughput of the TPU engine on one chip.

Workload models the reference's multi-round-QA harness
(benchmarks/multi-round-qa.py: closed-loop users, prompt + growing
history, fixed output length): N requests with ~512-token prompts and
64-token outputs run through the full engine (chunked prefill,
continuous batching, paged attention, decode bursts, sampling).
Weights are random — a 1B-class Llama architecture is used because no
checkpoints can be downloaded in this environment and throughput does
not depend on weight values.

Robustness: all engine work runs in WORKER SUBPROCESSES with hard
timeouts. A Mosaic miscompile can hang (not just error) and wedge the
device — observed in round 3 — and the one run that matters must
always print its JSON line: pallas attention is attempted first; on
error OR hang the xla-attention worker runs; if even that cannot
complete, a diagnostic line is printed instead of hanging the driver.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = requests/second. vs_baseline divides by BASELINE.json's
``published.req_per_s`` once a measured baseline is recorded there
(1.0 until then).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_PROBE_LOG: dict = {}


def _tpu_available() -> bool:
    """Probe TPU init in a subprocess so a wedged tunnel can't hang us.

    The tunnel can take minutes to come up; ``jax.devices()`` on it has
    been observed to block >10 min. So: generous per-attempt budget
    (default 600 s, env-overridable), two attempts, and a loud report
    either way — a CPU fallback must never masquerade as the TPU
    number (round-1 failure mode).
    """
    budget = int(os.environ.get("BENCH_TPU_PROBE_TIMEOUT_S", "600"))
    attempts = int(os.environ.get("BENCH_TPU_PROBE_ATTEMPTS", "2"))
    t0 = time.time()
    for i in range(attempts):
        sys.stderr.write(
            f"[bench] TPU probe attempt {i + 1}/{attempts} "
            f"(budget {budget}s)...\n")
        sys.stderr.flush()
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); "
                 "assert d[0].platform != 'cpu'; "
                 "print(d[0].device_kind)"],
                timeout=budget, capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[bench] TPU probe attempt {i + 1} timed out after "
                f"{budget}s\n")
            continue
        if probe.returncode == 0:
            kind = probe.stdout.strip().splitlines()[-1]
            _PROBE_LOG.update(
                device_kind=kind,
                probe_seconds=round(time.time() - t0, 1))
            sys.stderr.write(
                f"[bench] TPU up: {kind} "
                f"({_PROBE_LOG['probe_seconds']}s)\n")
            return True
        sys.stderr.write(
            f"[bench] TPU probe attempt {i + 1} failed "
            f"(rc={probe.returncode}): {probe.stderr.strip()[-400:]}\n")
    _PROBE_LOG.update(
        probe_seconds=round(time.time() - t0, 1),
        probe_error=f"no TPU after {attempts} attempts x {budget}s")
    sys.stderr.write(
        "[bench] " + "=" * 60 + "\n"
        "[bench] WARNING: NO TPU REACHABLE — falling back to CPU.\n"
        "[bench] This number is NOT the TPU benchmark. "
        f"({_PROBE_LOG['probe_error']})\n"
        "[bench] " + "=" * 60 + "\n")
    return False


# Peak bf16 matmul FLOP/s per chip, for the MFU estimate.
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _peak_flops(device_kind: str) -> float:
    for k, v in _PEAK_FLOPS.items():
        if device_kind.lower().startswith(k.lower()):
            return v
    return 197e12  # assume v5e-class if unknown


def _param_count(model) -> int:
    h, ffn, L, v = (model.hidden_size, model.intermediate_size,
                    model.num_hidden_layers, model.vocab_size)
    nh, nkv, d = (model.num_attention_heads,
                  model.num_key_value_heads, model.head_dim)
    attn = h * nh * d + 2 * h * nkv * d + nh * d * h
    mlp = 3 * h * ffn
    return L * (attn + mlp) + 2 * v * h


def _bench_config(tpu: bool):
    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ModelConfig,
        SchedulerConfig,
    )
    if tpu and os.environ.get("BENCH_MODEL") == "8b":
        # North-star config (BASELINE.json config 2, BASELINE.md
        # "p50 TTFT within 1.2x of H100"): Llama-3-8B geometry on one
        # 16 GB v5e chip — int8 weight-only (~8 GB) + bf16 KV cache.
        # Random weights: serving throughput/TTFT are weight-value
        # independent, and the image has no egress for checkpoints.
        model = ModelConfig(
            name="llama-3-8b-class",
            architecture="llama",
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_hidden_layers=32,
            num_attention_heads=32,
            num_key_value_heads=8,
            head_dim=128,
            max_position_embeddings=8192,
            dtype="bfloat16",
            quantization="int8",
        )
        # KV per page: 2*32L*8kv*128d*128ps*2B = 16 MB -> 192 pages
        # ~= 3 GB cache alongside ~8 GB weights.
        cache = CacheConfig(page_size=128, num_pages=192)
        # deferred_kv_writes: round-5 on-chip +8% at this config
        # (3.30 vs 3.05 req/s — results/round5_notes.md).
        sched = SchedulerConfig(max_num_seqs=16, max_model_len=1024,
                                prefill_chunk_size=512,
                                prefill_batch_size=4,
                                decode_steps=32,
                                deferred_kv_writes=True)
        n_requests, prompt_len, out_len = 24, 512, 64
    elif tpu:
        from production_stack_tpu.engine.config import (
            bench_1b_model_config,
        )
        model = bench_1b_model_config()
        # page_size 128 = one lane tile per page: the Pallas kernels
        # DMA whole tile-aligned pages (ops/paged_attention_pallas.py).
        cache = CacheConfig(page_size=128, num_pages=512)
        # Fat device programs, few host syncs: 32-wide decode with
        # 32-step on-device bursts (per-row budgets/stops evaluated in
        # the compiled program), 8-prompt batched prefill chunks.
        # deferred_kv_writes: round-5 on-chip +15% at this config
        # (12.76 vs 11.07 req/s — results/round5_notes.md).
        sched = SchedulerConfig(max_num_seqs=32, max_model_len=1024,
                                prefill_chunk_size=512,
                                prefill_batch_size=8,
                                decode_steps=32,
                                deferred_kv_writes=True)
        n_requests, prompt_len, out_len = 48, 512, 64
    else:  # CPU fallback: tiny model, same code path
        from production_stack_tpu.engine.config import tiny_model_config
        model = tiny_model_config("llama")
        cache = CacheConfig(page_size=16, num_pages=256)
        sched = SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                prefill_chunk_size=128,
                                prefill_batch_size=4,
                                decode_steps=4)
        n_requests, prompt_len, out_len = 8, 128, 16
    # Experiment knobs (batch-scaling studies on a live chip window
    # without code churn between runs; defaults above are the served
    # configuration the driver measures).
    if os.environ.get("BENCH_MAX_SEQS"):
        sched.max_num_seqs = int(os.environ["BENCH_MAX_SEQS"])
    if os.environ.get("BENCH_NUM_PAGES"):
        cache.num_pages = int(os.environ["BENCH_NUM_PAGES"])
    if os.environ.get("BENCH_PAGE_SIZE"):
        cache.page_size = int(os.environ["BENCH_PAGE_SIZE"])
    if os.environ.get("BENCH_N_REQUESTS"):
        n_requests = int(os.environ["BENCH_N_REQUESTS"])
    if os.environ.get("BENCH_OUT_LEN"):
        out_len = int(os.environ["BENCH_OUT_LEN"])
    if os.environ.get("BENCH_DEFERRED"):
        sched.deferred_kv_writes = bool(int(os.environ["BENCH_DEFERRED"]))
    if os.environ.get("BENCH_QUANT"):
        model.quantization = os.environ["BENCH_QUANT"]
    if os.environ.get("BENCH_KV_DTYPE"):
        # KV page storage dtype A/B (docs/kv_quantization.md). Both
        # sides of the comparison get the same num_pages INPUT (= the
        # same HBM byte budget); EngineConfig expands the int8 side's
        # page count ~2x at those bytes.
        cache.kv_cache_dtype = os.environ["BENCH_KV_DTYPE"]
    if os.environ.get("BENCH_SPEC_K"):
        # Draft-free speculative decoding (docs/speculative.md).
        # Hybrid with the decode burst: drafting steps run the verify
        # program, draft-less steps keep the decode_steps burst.
        # Deferred KV is incompatible (verify writes draft KV
        # eagerly).
        k = int(os.environ["BENCH_SPEC_K"])
        sched.speculative_k = k
        if k > 0:
            sched.deferred_kv_writes = False
            sched.speculative_min_match = int(
                os.environ.get("BENCH_SPEC_MIN_MATCH", "2"))
    if os.environ.get("BENCH_DECODE_STEPS"):
        sched.decode_steps = int(os.environ["BENCH_DECODE_STEPS"])
    if os.environ.get("BENCH_ASYNC"):
        # Overlapped async pipeline A/B (docs/async_pipeline.md). The
        # pipeline is single-step-decode only, so the driver forces
        # BENCH_DECODE_STEPS=1 on BOTH sides of the comparison and
        # async_scheduling is the only variable.
        sched.async_scheduling = bool(int(os.environ["BENCH_ASYNC"]))
        if sched.async_scheduling:
            sched.decode_steps = 1
            sched.speculative_k = 0
            sched.deferred_kv_writes = False  # needs bursts
    return (EngineConfig(model=model, cache=cache, scheduler=sched),
            n_requests, prompt_len, out_len)


def run_worker(impl: str, tpu: bool) -> None:
    """Run the closed-loop engine benchmark with one attention impl
    and print the result JSON line (invoked as a subprocess so the
    parent can enforce a hard timeout)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import numpy as np

    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import (
        SamplingParams,
        SequenceState,
    )

    import jax
    try:
        # Warm restarts of the benchmark reuse compiled executables.
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax-comp-cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    config, n_requests, prompt_len, out_len = _bench_config(tpu)
    # "<impl>[+per_layer|+stacked]": optional cache-layout override.
    # The default follows CacheConfig's 'auto' (per_layer — the
    # measured winner, benchmarks/results/decode_probe.json
    # 2026-07-31: 11.07 vs 5.94 req/s at this bench config).
    layout = "auto"
    if impl.endswith(("+per_layer", "+stacked")):
        impl, layout = impl.rsplit("+", 1)
    config.cache.cache_layout = layout
    config.model.attention_impl = impl
    if config.scheduler.deferred_kv_writes:
        # The shared eligibility predicate (same one the server's
        # 'auto' uses): a BENCH_IMPLS=pallas attempt must still
        # measure, not fail at the runner's capability guard.
        from production_stack_tpu.engine.model_runner import (
            deferred_kv_eligible,
        )
        config.scheduler.deferred_kv_writes = deferred_kv_eligible(
            config.model.architecture, config.scheduler.decode_steps,
            impl, speculative_k=config.scheduler.speculative_k)
    engine = LLMEngine(config)
    # The engine's per-kernel probe may itself have degraded a path.
    impls = (config.model.attention_impl_decode
             or config.model.attention_impl,
             config.model.attention_impl_prefill
             or config.model.attention_impl)
    rng = np.random.RandomState(0)

    def make_prompt(i):
        # Shared "system prompt" prefix (exercises the prefix cache, as
        # the reference workload's shared system prompt does) + unique
        # user history.
        shared = list(range(100, 100 + prompt_len // 4))
        unique = [int(x) for x in rng.randint(
            1, config.model.vocab_size - 1, size=prompt_len * 3 // 4
        )]
        return shared + unique

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=out_len, temperature=0.0, ignore_eos=True
    )

    # Warmup: compile every shape the two phases touch — the full
    # prompt's chunk bucket AND the tail bucket the phase-2 follow-ups
    # hit (prompt + answer + 32 fresh tokens => a partial last chunk).
    # A 20-40 s XLA compile inside the timed open-loop phase would
    # masquerade as queueing/prefill latency.
    warm = engine.generate(make_prompt(-1), sampling())
    assert len(warm.output_token_ids) == out_len
    follow_len = prompt_len + out_len + 32
    warm2 = engine.generate(
        make_prompt(-2)[:1] * follow_len, sampling())
    assert len(warm2.output_token_ids) == out_len
    if config.scheduler.speculative_k > 0:
        # A highly repetitive prompt drafts immediately, so the
        # speculative verify program compiles during warmup instead
        # of inside the measured phases.
        engine.generate([5, 6, 7] * (prompt_len // 3), sampling())
    sys.stderr.write(f"[bench-worker {impl}] warmup done\n")

    # Decode-rate instrumentation: wrap the decode dispatch (normal,
    # burst and speculative-verify steps all enter run_decode) so
    # decode tokens/s is measured over decode wall time only — req/s
    # mixes prefill in and can't answer "did speculation speed up
    # decode".
    decode_stats = {"wall": 0.0, "tokens": 0}
    _orig_run_decode = engine.runner.run_decode

    def _timed_run_decode(plan):
        t = time.time()
        toks, lps = _orig_run_decode(plan)
        decode_stats["wall"] += time.time() - t
        decode_stats["tokens"] += sum(len(r) for r in toks)
        return toks, lps

    engine.runner.run_decode = _timed_run_decode

    # Decode-rate phase: steady-state decode tokens/s at full batch
    # occupancy (all slots submitted up front, 4x-length outputs so
    # decode dominates). The closed/open phases below mix prefill,
    # admission staggering and arrival pacing into their walls; this
    # phase isolates the number the decode path (burst vs speculative
    # verify) is actually responsible for.
    decode_sp = lambda: SamplingParams(  # noqa: E731
        max_tokens=4 * out_len, temperature=0.0, ignore_eos=True)
    # Prompts here are the repetitive multi-round shape the feature
    # targets (a per-request block replayed round after round, like a
    # follow-up that quotes its history) — prompt-lookup drafts from
    # exactly this repetition, while the spec-off run sees the same
    # prompts and takes the plain burst path.
    # Best of 3 reps: the phase wall is ~100 ms at the CPU config, so
    # a single rep is at the mercy of OS scheduling noise; max-of-3
    # makes the async A/B comparison repeatable. Reps after the first
    # re-prefill the same prompts (prefix-cache hit, symmetric for
    # both sides of the A/B).
    decode_phase_rate = 0.0
    for _ in range(3):
        dr_seqs = [engine.sequences[engine.add_request(
            make_prompt(500 + i)[:32] * (prompt_len // 32),
            decode_sp())]
            for i in range(config.scheduler.max_num_seqs)]
        dr_t0 = time.time()
        while any(s.state not in (SequenceState.FINISHED,
                                  SequenceState.ABORTED)
                  for s in dr_seqs):
            engine.step()
        dr_wall = time.time() - dr_t0
        # End-to-end phase rate (prefill + decode + ALL host work
        # over wall clock). The run_decode-only rate below can't see
        # the async pipeline — async steps bypass run_decode, and
        # the scheduler/commit host time the pipeline hides is
        # exactly what it excludes — so the async A/B compares this
        # number.
        dr_tokens = sum(len(s.output_token_ids) for s in dr_seqs)
        if dr_wall > 0:
            decode_phase_rate = max(decode_phase_rate,
                                    dr_tokens / dr_wall)
    decode_rate = (decode_stats["tokens"] / decode_stats["wall"]
                   if decode_stats["wall"] > 0 else 0.0)

    # Optional profiler capture of the timed region (BENCH_PROFILE=
    # <dir>); inspect with tensorboard's profile plugin or xprof.
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    # Closed-loop timed run.
    t0 = time.time()
    seqs = []
    submit_times = {}
    for i in range(n_requests):
        sp = sampling()
        seq_id = engine.add_request(make_prompt(i), sp)
        seqs.append(engine.sequences[seq_id])
        submit_times[seq_id] = time.time()
    while any(s.state not in (SequenceState.FINISHED,
                              SequenceState.ABORTED) for s in seqs):
        engine.step()
    wall = time.time() - t0
    if profile_dir:
        jax.profiler.stop_trace()

    ttfts = sorted(
        s.first_token_time - submit_times[s.seq_id]
        for s in seqs if s.first_token_time
    )
    p50_ttft = ttfts[len(ttfts) // 2] if ttfts else -1.0
    total_tokens = sum(len(s.output_token_ids) for s in seqs)
    req_per_s = n_requests / wall

    # Phase 2 — open-loop MULTI-ROUND arrivals at ~70% of the
    # closed-loop throughput (below the knee): the honest TTFT,
    # decomposed into queueing (arrival -> first scheduled) vs prefill
    # compute (first scheduled -> first token). This mirrors the
    # reference workload (lognormal user arrivals, each user's round 2
    # replays its round-1 history — a prefix-cache hit); the
    # closed-loop burst above deliberately saturates the engine and
    # its TTFT is dominated by queueing.
    n_users = max(2, n_requests // 2)
    # Each user submits 2 requests (round 1 + follow-up), so the USER
    # arrival rate is derated by 2 to keep the offered request load at
    # ~70% of the measured closed-loop capacity.
    user_rate = max(0.25, 0.7 * req_per_s / 2)
    rng_arr = np.random.RandomState(7)
    gaps = rng_arr.lognormal(
        mean=float(np.log(1.0 / user_rate)), sigma=0.5,
        size=n_users)
    seqs2, submit2 = [], {}
    round1 = {}  # seq_id -> (user prompt, Sequence)
    next_t = time.time()

    def submit(prompt):
        sid = engine.add_request(prompt, sampling())
        seq = engine.sequences[sid]
        seqs2.append(seq)
        submit2[sid] = time.time()
        return sid, seq

    def pump_round2():
        # A finished round-1 chat immediately asks its follow-up:
        # history (prompt + answer) + fresh user text.
        for sid, (prompt, seq) in list(round1.items()):
            if seq.state in (SequenceState.FINISHED,
                             SequenceState.ABORTED):
                del round1[sid]
                history = prompt + seq.output_token_ids
                follow = [int(x) for x in rng.randint(
                    1, config.model.vocab_size - 1, size=32)]
                submit(history + follow)

    for i in range(n_users):
        next_t += gaps[i]
        while engine.has_work() and time.time() < next_t:
            engine.step()
            pump_round2()
        now = time.time()
        if now < next_t:
            time.sleep(next_t - now)
        prompt = make_prompt(1000 + i)
        sid, seq = submit(prompt)
        round1[sid] = (prompt, seq)
    while (round1
           or any(s.state not in (SequenceState.FINISHED,
                                  SequenceState.ABORTED)
                  for s in seqs2)):
        engine.step()
        pump_round2()

    def pctl(vals, q):
        vals = sorted(vals)
        return vals[int(q * (len(vals) - 1))] if vals else -1.0

    ttft2 = [s.first_token_time - submit2[s.seq_id]
             for s in seqs2 if s.first_token_time]
    queueing2 = [s.first_scheduled_time - submit2[s.seq_id]
                 for s in seqs2 if s.first_scheduled_time]
    prefill2 = [s.first_token_time - s.first_scheduled_time
                for s in seqs2
                if s.first_token_time and s.first_scheduled_time]

    # MFU estimate: each processed token costs ~2*params matmul FLOPs;
    # prefill tokens and generated tokens both pass through the full
    # stack of projections (VERDICT r1: tokens/s x 2 x params / peak).
    params_n = _param_count(config.model)
    processed_tokens = n_requests * prompt_len + total_tokens
    model_flops = 2.0 * params_n * processed_tokens
    device_kind = os.environ.get("BENCH_DEVICE_KIND", "")
    mfu = (model_flops / wall / _peak_flops(device_kind)
           if tpu else None)

    extra = {
        "p50_ttft_s": round(p50_ttft, 4),
        "gen_tokens_per_s": round(total_tokens / wall, 1),
        "total_tokens_per_s": round(processed_tokens / wall, 1),
        "n_requests": n_requests,
        "prompt_len": prompt_len,
        "output_len": out_len,
        "platform": "tpu" if tpu else "cpu",
        "attention_impl": impls[0] if impls[0] == impls[1] else
        f"decode={impls[0]},prefill={impls[1]}",
        "cache_layout": config.cache.cache_layout,
        "param_count": params_n,
        "decode_batch": config.scheduler.max_num_seqs,
        "decode_burst": config.scheduler.decode_steps,
        "deferred_kv_writes": config.scheduler.deferred_kv_writes,
        "page_size": config.cache.page_size,
        "quantization": config.model.quantization,
        # Open-loop phase: user arrivals derated so the offered
        # REQUEST load sits at ~70% of closed-loop capacity.
        "arrivals_users_per_s": round(user_rate, 2),
        "arrivals_offered_req_per_s": round(2 * user_rate, 2),
        "arrivals_p50_ttft_s": round(pctl(ttft2, 0.5), 4),
        "arrivals_p90_ttft_s": round(pctl(ttft2, 0.9), 4),
        "arrivals_p50_queueing_s": round(pctl(queueing2, 0.5), 4),
        "arrivals_p50_prefill_s": round(pctl(prefill2, 0.5), 4),
    }
    # Speculative-decoding report. decode_tokens_per_s is the
    # dedicated decode-rate phase (spec-off runs report it too so the
    # driver can compare like for like); the acceptance counters span
    # the whole run.
    st = engine.stats()
    drafted = st["spec_decode_num_draft_tokens_total"]
    accepted = st["spec_decode_num_accepted_tokens_total"]
    extra["speculative_k"] = config.scheduler.speculative_k
    extra["decode_tokens_per_s"] = round(decode_rate, 1)
    extra["spec_draft_tokens"] = int(drafted)
    extra["spec_accepted_tokens"] = int(accepted)
    extra["spec_acceptance_rate"] = round(
        accepted / drafted, 4) if drafted else 0.0
    # Async-pipeline report (docs/async_pipeline.md). Overlap
    # fraction = 1 - device_idle / host time: ~0 when every step
    # serializes host work against the device, -> 1 when dispatch-
    # ahead keeps the device queue fed through the host phase.
    host_s = st["engine_step_host_seconds_total"]
    idle_s = st["engine_device_idle_seconds_total"]
    extra["async_scheduling"] = config.scheduler.async_scheduling
    extra["decode_phase_tokens_per_s"] = round(decode_phase_rate, 1)
    extra["host_device_overlap_fraction"] = (
        round(max(0.0, 1.0 - idle_s / host_s), 4) if host_s > 0
        else 0.0)
    extra["engine_step_host_s"] = round(host_s, 3)
    extra["engine_device_idle_s"] = round(idle_s, 3)
    extra["pipeline_ahead_steps"] = int(
        st["engine_pipeline_ahead_steps_total"])
    extra["pipeline_steps"] = int(st["engine_pipeline_steps_total"])
    # KV page storage report (docs/kv_quantization.md): page budget
    # after any int8 expansion, worst-case KV bytes per decode step,
    # and the analytic decode-batch ceiling at this page budget (how
    # many full-length sequences the cache can hold at once).
    extra["kv_cache_dtype"] = config.cache.resolved_kv_dtype()
    extra["kv_page_capacity"] = int(
        st["engine_kv_cache_page_capacity"])
    extra["kv_bytes_per_decode_step"] = int(
        st["engine_kv_bytes_per_decode_step"])
    pages_per_seq = -(-(prompt_len + out_len) // config.cache.page_size)
    extra["kv_max_decode_batch"] = (
        extra["kv_page_capacity"] // pages_per_seq)
    if mfu is not None:
        extra["mfu"] = round(mfu, 4)
    # Device performance observatory (docs/observability.md): compile
    # counts, HBM category peaks, and the engine's own useful-token
    # MFU so benchcompare can flag compile storms and memory
    # regressions across BENCH_* rounds.
    obs = getattr(engine.runner, "observatory", None)
    if obs is not None:
        extra["compile_events"] = obs.compile_events_by_kind()
        extra["compile_seconds"] = {
            k: round(v, 3)
            for k, v in obs.compile_seconds_by_kind().items()}
        extra["hbm_bytes"] = obs.hbm_bytes()
        extra["observatory_mfu"] = round(obs.mfu(), 4)
    print(json.dumps({
        "metric": (f"multi-round-qa-style req/s, {config.model.name}, "
                   "1 TPU chip" if tpu else
                   "multi-round-qa-style req/s, tiny llama, CPU fallback"),
        "value": round(req_per_s, 3),
        "unit": "req/s",
        "vs_baseline": round(req_per_s, 3),
        "extra": extra,
    }))


def run_disagg_worker(mode: str) -> None:
    """Disaggregation A/B worker (docs/disaggregation.md): bursty
    long-prompt arrivals landing on the same engine that serves steady
    interactive decode streams (``mode=mono``) vs on a separate
    prefill-role engine that hands the KV off through a live cache
    server (``mode=disagg``). Reports the interactive streams' ITL
    and the long prompts' TTFT — the pair of numbers disaggregation
    exists to trade between.

    Always runs the tiny-llama CPU config: the phase measures the
    scheduling interference structure (prefill chunks stalling decode
    steps), which needs two engines side by side — not a chip number.
    """
    import queue as queue_mod
    import threading

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import numpy as np

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        OffloadConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax-comp-cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    def make_engine(role="both", remote_url=None):
        return LLMEngine(EngineConfig(
            model=tiny_model_config("llama"),
            cache=CacheConfig(page_size=16, num_pages=256),
            scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                      prefill_chunk_size=64),
            offload=OffloadConfig(enable=remote_url is not None,
                                  remote_url=remote_url,
                                  host_pool_bytes=0),
            engine_role=role,
        ))

    cache_stop = None
    cache_url = None
    if mode == "disagg":
        # Live cache server: the KV handoff crosses a real HTTP wire.
        import asyncio

        from aiohttp import web

        from production_stack_tpu.engine.cache_server import (
            build_cache_server,
        )
        loop = asyncio.new_event_loop()
        started = threading.Event()
        port_box = {}

        def serve_cache():
            asyncio.set_event_loop(loop)
            runner = web.AppRunner(build_cache_server(256 * 1024 ** 2))
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", 0)
            loop.run_until_complete(site.start())
            port_box["port"] = site._server.sockets[0].getsockname()[1]
            started.set()
            loop.run_forever()

        cache_thread = threading.Thread(target=serve_cache, daemon=True)
        cache_thread.start()
        started.wait(10)
        cache_url = f"http://127.0.0.1:{port_box['port']}"
        cache_stop = lambda: loop.call_soon_threadsafe(loop.stop)  # noqa: E731

    rng = np.random.RandomState(0)
    long_prompt_len = 256  # 4 chunked-prefill steps each
    short_prompt_len = 32
    duration = float(os.environ.get("BENCH_DISAGG_DURATION_S", "10"))
    burst_every = 1.5
    burst_size = 2
    n_interactive = 3  # steady decode streams (batch leaves 1 slot free)

    inter_samp = lambda: SamplingParams(  # noqa: E731
        max_tokens=48, temperature=0.0, ignore_eos=True)
    long_samp = lambda: SamplingParams(  # noqa: E731
        max_tokens=4, temperature=0.0, ignore_eos=True)

    def prompt(n):
        return [int(x) for x in rng.randint(1, 30000, size=n)]

    decode_eng = make_engine(
        role="decode" if mode == "disagg" else "both",
        remote_url=cache_url)
    prefill_eng = None
    work_q: queue_mod.Queue = queue_mod.Queue()
    done_q: queue_mod.Queue = queue_mod.Queue()
    stop_flag = threading.Event()

    if mode == "disagg":
        prefill_eng = make_engine(role="prefill", remote_url=cache_url)
        # Warm the prefill program shapes outside the measured window.
        prefill_eng.add_request(prompt(long_prompt_len), long_samp(),
                                handoff_prefill=True)
        while prefill_eng.has_work():
            prefill_eng.step()

        def prefill_loop():
            pending = {}
            while not stop_flag.is_set():
                try:
                    while True:
                        p, t0 = work_q.get_nowait()
                        sid = prefill_eng.add_request(
                            list(p), long_samp(), handoff_prefill=True)
                        pending[sid] = (p, t0)
                except queue_mod.Empty:
                    pass
                if not prefill_eng.has_work():
                    time.sleep(0.002)
                    continue
                for out in prefill_eng.step():
                    if out.finished and out.seq_id in pending:
                        p, t0 = pending.pop(out.seq_id)
                        # The first token reaches the client here.
                        done_q.put((p, out.new_token, t0, time.time()))

        prefill_thread = threading.Thread(target=prefill_loop,
                                          daemon=True)

    # Warm the decode-side shapes too.
    decode_eng.generate(prompt(short_prompt_len),
                        SamplingParams(max_tokens=4, temperature=0.0,
                                       ignore_eos=True))

    itl = []          # interactive inter-token gaps (s)
    ttft = []         # long-prompt submit -> first token (s)
    interactive = {}  # seq_id -> last token wall time (None = none yet)
    long_pending = {}  # seq_id -> submit time (mono mode)
    long_done = 0
    interactive_tokens = 0

    def submit_interactive():
        sid = decode_eng.add_request(
            prompt(short_prompt_len), inter_samp())
        interactive[sid] = None

    for _ in range(n_interactive):
        submit_interactive()
    if mode == "disagg":
        prefill_thread.start()

    start = time.time()
    next_burst = start + 0.5
    deadline = start + duration
    while time.time() < deadline:
        now = time.time()
        if now >= next_burst:
            for _ in range(burst_size):
                if mode == "disagg":
                    work_q.put((prompt(long_prompt_len), now))
                else:
                    sid = decode_eng.add_request(
                        prompt(long_prompt_len), long_samp())
                    long_pending[sid] = now
            next_burst += burst_every
        if mode == "disagg":
            try:
                while True:
                    p, first_token, t0, t_first = done_q.get_nowait()
                    ttft.append(t_first - t0)
                    decode_eng.add_handoff(list(p), int(first_token),
                                           long_samp())
                    long_done += 1
            except queue_mod.Empty:
                pass
        if not decode_eng.has_work():
            time.sleep(0.001)
            continue
        outs = decode_eng.step()
        now = time.time()
        for out in outs:
            if out.seq_id in interactive:
                if out.new_token is not None:
                    last = interactive[out.seq_id]
                    if last is not None:
                        itl.append(now - last)
                    interactive[out.seq_id] = now
                    interactive_tokens += 1
                if out.finished:
                    del interactive[out.seq_id]
                    submit_interactive()
            elif out.seq_id in long_pending and out.new_token is not None:
                ttft.append(now - long_pending.pop(out.seq_id))
                long_done += 1

    stop_flag.set()
    if mode == "disagg":
        prefill_thread.join(timeout=5)
    if cache_stop is not None:
        cache_stop()

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    itl_p99 = pctl(itl, 0.99) or 0.0
    print(json.dumps({
        "metric": f"disagg bench ({mode}): interactive ITL p99 under "
                  "bursty long-prompt arrivals",
        "value": round(itl_p99, 4),
        "unit": "s",
        "vs_baseline": 0.0,
        "extra": {
            "mode": mode,
            "itl_p50_s": round(pctl(itl, 0.5) or 0.0, 4),
            "itl_p99_s": round(itl_p99, 4),
            "ttft_p50_s": round(pctl(ttft, 0.5) or 0.0, 4),
            "ttft_p99_s": round(pctl(ttft, 0.99) or 0.0, 4),
            "interactive_tokens": interactive_tokens,
            "long_requests_finished": long_done,
        },
    }))


def run_unified_worker(mode: str) -> None:
    """Unified ragged-step A/B worker (docs/unified_step.md): steady
    interactive decode streams sharing ONE engine with bursty
    long-prompt arrivals, with the unified mixed step on
    (``mode=on``: prefill chunks admitted into decode steps under a
    token budget) vs off (``mode=off``: bimodal alternation).
    Reports the interactive streams' decode rate and ITL and the
    long prompts' TTFT — the three numbers the ragged step trades
    between — plus the padded-row ratio of the mixed dispatches.

    Always runs the tiny-llama CPU config: like the disagg phase,
    this measures the scheduling interference structure (prefill
    chunks stalling decode steps), not a chip number.
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import numpy as np

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax-comp-cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    engine = LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=256),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                  prefill_chunk_size=64,
                                  unified_step=(mode == "on")),
    ))

    rng = np.random.RandomState(0)
    long_prompt_len = 256  # 4 chunked-prefill steps each
    short_prompt_len = 32
    duration = float(os.environ.get("BENCH_UNIFIED_DURATION_S", "10"))
    burst_every = 1.5
    burst_size = 2
    n_interactive = 3  # steady decode streams (batch leaves 1 slot)

    inter_samp = lambda: SamplingParams(  # noqa: E731
        max_tokens=48, temperature=0.0, ignore_eos=True)
    long_samp = lambda: SamplingParams(  # noqa: E731
        max_tokens=4, temperature=0.0, ignore_eos=True)

    def prompt(n):
        return [int(x) for x in rng.randint(1, 30000, size=n)]

    # Warm both program shapes outside the measured window.
    engine.generate(prompt(short_prompt_len),
                    SamplingParams(max_tokens=4, temperature=0.0,
                                   ignore_eos=True))

    itl = []          # interactive inter-token gaps (s)
    ttft = []         # long-prompt submit -> first token (s)
    interactive = {}  # seq_id -> last token wall time (None = none)
    long_pending = {}  # seq_id -> submit time
    long_done = 0
    interactive_tokens = 0

    def submit_interactive():
        sid = engine.add_request(prompt(short_prompt_len),
                                 inter_samp())
        interactive[sid] = None

    for _ in range(n_interactive):
        submit_interactive()

    def run_phase(phase_s):
        nonlocal long_done, interactive_tokens
        start = time.time()
        next_burst = start + 0.5
        deadline = start + phase_s
        while time.time() < deadline:
            now = time.time()
            if now >= next_burst:
                for _ in range(burst_size):
                    sid = engine.add_request(prompt(long_prompt_len),
                                             long_samp())
                    long_pending[sid] = now
                next_burst += burst_every
            if not engine.has_work():
                time.sleep(0.001)
                continue
            outs = engine.step()
            now = time.time()
            for out in outs:
                if out.seq_id in interactive:
                    if out.new_token is not None:
                        last = interactive[out.seq_id]
                        if last is not None:
                            itl.append(now - last)
                        interactive[out.seq_id] = now
                        interactive_tokens += 1
                    if out.finished:
                        del interactive[out.seq_id]
                        submit_interactive()
                elif (out.seq_id in long_pending
                        and out.new_token is not None):
                    ttft.append(now - long_pending.pop(out.seq_id))
                    long_done += 1
        return time.time() - start

    # Warmup phases: identical traffic, discarded samples — first-hit
    # compilation of the ragged (row bucket, W bucket) lattice
    # otherwise lands in a burst's TTFT and dominates p99. Traffic
    # wanders through the lattice over time, so keep warming until
    # the unified program's executable cache stops growing.
    warmup = float(os.environ.get("BENCH_UNIFIED_WARMUP_S", "3.0"))
    run_phase(warmup)
    jit = getattr(engine.runner, "_unified_jit", None)
    if jit is not None and hasattr(jit, "_cache_size"):
        prev = jit._cache_size()
        for _ in range(4):
            run_phase(1.6)
            size = jit._cache_size()
            if size == prev:
                break
            prev = size
    itl.clear()
    ttft.clear()
    long_pending.clear()
    long_done = 0
    interactive_tokens = 0
    st0 = engine.stats()

    wall = run_phase(duration)

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    st = engine.stats()
    ragged_steps = (st["engine_ragged_steps_total"]
                    - st0["engine_ragged_steps_total"])
    ragged_rows = (st["engine_ragged_rows_total"]
                   - st0["engine_ragged_rows_total"])
    ragged_pads = (st["engine_ragged_pad_rows_total"]
                   - st0["engine_ragged_pad_rows_total"])
    pad_ratio = ragged_pads / ragged_rows if ragged_rows else 0.0
    itl_p99 = pctl(itl, 0.99) or 0.0
    # Resolved unified attention impl (observatory one-hot value):
    # the string keys the A/B run to the kernel actually served;
    # ragged_kernel_active is its numeric shadow so benchcompare can
    # hold "the fused kernel stayed resolved" as a direction.
    unified_impl = engine.runner.observatory.attention_impls().get(
        "unified", "")
    ragged_active = int(unified_impl.startswith("pallas_ragged"))
    print(json.dumps({
        "metric": f"unified-step bench ({mode}): interactive ITL p99 "
                  "under bursty long-prompt arrivals",
        "value": round(itl_p99, 4),
        "unit": "s",
        "vs_baseline": 0.0,
        "extra": {
            "mode": mode,
            "decode_tok_s": round(interactive_tokens / wall, 1),
            "itl_p50_s": round(pctl(itl, 0.5) or 0.0, 4),
            "itl_p99_s": round(itl_p99, 4),
            "ttft_p50_s": round(pctl(ttft, 0.5) or 0.0, 4),
            "ttft_p99_s": round(pctl(ttft, 0.99) or 0.0, 4),
            "ragged_steps": int(ragged_steps),
            "ragged_pad_ratio": round(pad_ratio, 4),
            "attention_impl_unified": unified_impl,
            "ragged_kernel_active": ragged_active,
            "interactive_tokens": interactive_tokens,
            "long_requests_finished": long_done,
        },
    }))


def run_scaleout_worker() -> None:
    """Scale-out bench (docs/parallelism.md): goodput per chip as
    independent tp=2 replicas are added on the 8-device host. Each
    replica is its own engine on its own 2-device mesh built through
    ``build_mesh(devices=...)`` — the slice-as-replica layout the
    topology-aware MeshPlan produces on multi-slice hardware, scaled
    down to virtual CPU devices. Replicas share nothing (dp is the
    no-communication axis), so aggregate decode goodput should track
    the chip count; the per-chip numbers at 1/2/4 replicas and the
    1->2 / 1->4 linearity ratios ride out under ``scaleout_*`` keys.

    Methodology: the bench host time-shares every virtual device over
    the same CPU cores, so running replicas concurrently would
    measure core contention, not replica scaling. Instead all N
    engines are built and live at once (a mesh overlapping a
    neighbour's devices, or state accidentally shared across
    replicas, surfaces here), then each replica's decode rate is
    measured solo and summed — valid because the replicas exchange
    nothing by construction. Deviation from linear therefore exposes
    shared-software interference (a global lock, a spanning mesh, a
    shared cache), which is the regression this phase guards.
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import numpy as np

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ParallelConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams
    from production_stack_tpu.parallel.mesh import build_mesh

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax-comp-cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    devices = jax.devices()
    chips_per_replica = 2  # tiny-llama has 2 kv heads -> tp=2 max
    duration = float(os.environ.get("BENCH_SCALEOUT_DURATION_S", "6"))
    rng = np.random.RandomState(0)

    def make_replica(device_pair):
        mesh = build_mesh(tensor_parallel_size=chips_per_replica,
                          devices=list(device_pair))
        return LLMEngine(EngineConfig(
            model=tiny_model_config("llama"),
            cache=CacheConfig(page_size=16, num_pages=128),
            scheduler=SchedulerConfig(max_num_seqs=4,
                                      max_model_len=256,
                                      prefill_chunk_size=32),
            parallel=ParallelConfig(
                tensor_parallel_size=chips_per_replica),
        ), mesh=mesh)

    def decode_tokens(engine, stop_at, seed):
        """Steady full-batch decode until the wall deadline; returns
        tokens generated inside the window."""
        rng = np.random.RandomState(seed)  # thread-local
        samp = SamplingParams(max_tokens=160, temperature=0.0,
                              ignore_eos=True)
        seqs = [engine.add_request(
            [int(x) for x in rng.randint(1, 500, size=32)], samp)
            for _ in range(4)]
        tokens = 0
        while time.time() < stop_at:
            for out in engine.step():
                if out.new_token is not None:
                    tokens += 1
                if out.finished:  # keep the batch full to the bell
                    seqs.append(engine.add_request(
                        [int(x) for x in rng.randint(1, 500, size=32)],
                        samp))
        for sid in seqs:
            engine.abort_request(sid)
        return tokens

    extra = {"scaleout_chips_per_replica": chips_per_replica,
             "scaleout_duration_s": duration}
    per_chip = {}
    for n_replicas in (1, 2, 4):
        needed = n_replicas * chips_per_replica
        if needed > len(devices):
            extra[f"scaleout_skipped_r{n_replicas}"] = (
                f"needs {needed} devices, have {len(devices)}")
            continue
        engines = [make_replica(devices[i * chips_per_replica:
                                        (i + 1) * chips_per_replica])
                   for i in range(n_replicas)]
        # Warm the decode program on every replica outside the window.
        for eng in engines:
            eng.generate(
                [int(x) for x in rng.randint(1, 500, size=32)],
                SamplingParams(max_tokens=4, temperature=0.0,
                               ignore_eos=True))
        # Solo-measure each live replica, sum the rates (see
        # docstring: concurrent threads on a time-shared host would
        # measure core contention, not replica scaling).
        rates = []
        for i, eng in enumerate(engines):
            start = time.time()
            tokens = decode_tokens(eng, start + duration,
                                   seed=100 + i)
            rates.append(tokens / max(time.time() - start, 1e-6))
        agg = sum(rates)
        per_chip[n_replicas] = agg / needed
        extra[f"scaleout_goodput_tok_s_r{n_replicas}"] = round(agg, 1)
        extra[f"scaleout_goodput_per_chip_tok_s_r{n_replicas}"] = (
            round(per_chip[n_replicas], 1))
        sys.stderr.write(
            f"[bench] scaleout r{n_replicas}: {agg:.1f} tok/s "
            f"aggregate, {per_chip[n_replicas]:.1f} tok/s/chip\n")
    for n in (2, 4):
        if 1 in per_chip and n in per_chip and per_chip[1] > 0:
            extra[f"scaleout_linearity_1_to_{n}"] = round(
                per_chip[n] / per_chip[1], 3)
    print(json.dumps({
        "metric": "scale-out bench: decode goodput per chip at "
                  "1/2/4 tp=2 replicas",
        "value": extra.get("scaleout_linearity_1_to_2", 0.0),
        "unit": "fraction of linear",
        "vs_baseline": 0.0,
        "extra": extra,
    }))


def run_autoscale_worker() -> None:
    """Fleet autoscale bench (docs/fleet.md): router + fleet manager +
    a pool of fake-engine subprocesses driven through a load step up
    (SLO breach -> 1 -> 2 replicas) and back down (recovery -> 2 -> 1
    with a zero-loss drain). Reports the replica trajectory, the
    goodput against a TTFT+ITL SLO, and a hard zero count of dropped
    or 5xx'd requests across both transitions — the invariant the
    drain sequence exists to hold.

    Fake engines only (CPU, no JAX): the phase measures the control
    loop and the drain protocol, not model throughput.
    """
    import asyncio
    import socket
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import aiohttp
    from aiohttp import web

    from production_stack_tpu.fleet.manager import LIVE, FleetManager
    from production_stack_tpu.fleet.spec import (
        AutoscalerSpec,
        FleetSpec,
        PoolSpec,
    )
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.dynamic_config import (
        initialize_dynamic_config_watcher,
    )
    from production_stack_tpu.router.resilience import (
        ResilienceConfig,
        initialize_resilience,
    )
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    from production_stack_tpu.router.service_discovery import (
        initialize_service_discovery,
    )
    from production_stack_tpu.router.services.rewriter import (
        initialize_request_rewriter,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        get_engine_stats_scraper,
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )

    speed = float(os.environ.get("BENCH_AUTOSCALE_SPEED", "200"))
    out_len = int(os.environ.get("BENCH_AUTOSCALE_OUT_LEN", "40"))
    slo_ttft = float(os.environ.get("BENCH_AUTOSCALE_SLO_TTFT_S", "0.5"))
    slo_itl = float(os.environ.get("BENCH_AUTOSCALE_SLO_ITL_S", "0.1"))

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    async def run():
        t_start = time.time()
        initialize_service_discovery("static", urls=[], models=[],
                                     roles=[])
        initialize_request_stats_monitor(60.0)
        initialize_engine_stats_scraper(3600.0)
        initialize_routing_logic("roundrobin")
        initialize_request_rewriter("noop")
        initialize_resilience(ResilienceConfig(
            max_retries=2, backend_connect_timeout=2.0,
            backend_timeout=30.0, health_check_interval=0.0))
        runner = web.AppRunner(build_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        router_url = ("http://127.0.0.1:"
                      f"{site._server.sockets[0].getsockname()[1]}")

        config_path = os.path.join(tempfile.mkdtemp(), "dyn.json")
        base = free_port()
        spec = FleetSpec(
            pools=[PoolSpec(
                name="decode", role="decode", min_replicas=1,
                max_replicas=3, model="bench-fake",
                command=[sys.executable, "-m",
                         "production_stack_tpu.testing.fake_engine",
                         "--host", "127.0.0.1", "--port", "{port}",
                         "--model", "{model}", "--role", "{role}",
                         "--speed", str(speed), "--ttft", "0.0"],
                autoscaler=AutoscalerSpec(
                    target_waiting_per_replica=4.0, tolerance=0.1,
                    scale_up_cooldown_s=0.0,
                    scale_down_cooldown_s=0.0))],
            port_start=base, port_end=base + 9,
            router_url=router_url, router_config_path=config_path,
            drain_timeout_s=30.0,
        )
        mgr = FleetManager(spec)
        session = aiohttp.ClientSession()
        trajectory = []  # (seconds since start, desired, live)
        results = []     # per-request {status, ttft, itl[], error}

        def live_count():
            return sum(1 for r in mgr.replicas["decode"]
                       if r.state == LIVE)

        def sample():
            trajectory.append((round(time.time() - t_start, 2),
                               mgr.desired["decode"], live_count()))

        async def settle(want):
            deadline = time.time() + 30.0
            while time.time() < deadline:
                await mgr.reconcile_once()
                replicas = mgr.replicas["decode"]
                if (live_count() == want
                        and len(replicas) == want):
                    sample()
                    return
                await asyncio.sleep(0.05)
            raise RuntimeError(f"pool never settled at {want}")

        async def one_request():
            rec = {"status": None, "ttft": None, "itl": [],
                   "error": None}
            body = {"model": "bench-fake",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": out_len, "stream": True}
            t0 = time.time()
            last = None
            try:
                async with session.post(
                        router_url + "/v1/chat/completions",
                        json=body) as resp:
                    rec["status"] = resp.status
                    async for raw in resp.content:
                        line = raw.decode("utf-8", "replace").strip()
                        if (not line.startswith("data: ")
                                or line == "data: [DONE]"):
                            continue
                        delta = json.loads(
                            line[len("data: "):])["choices"][0]["delta"]
                        if not delta.get("content"):
                            continue
                        now = time.time()
                        if rec["ttft"] is None:
                            rec["ttft"] = now - t0
                        elif last is not None:
                            rec["itl"].append(now - last)
                        last = now
            except Exception as e:
                rec["error"] = f"{type(e).__name__}: {e}"
            results.append(rec)

        async def burst(n):
            await asyncio.gather(*(one_request() for _ in range(n)))

        await settle(1)
        watcher = initialize_dynamic_config_watcher(config_path, 3600.0)
        watcher.check_and_apply()
        (first,) = mgr.replicas["decode"]
        await burst(4)

        # Load step up: injected queue depth breaches the 4/replica
        # target; requests keep flowing through the transition.
        async with session.post(first.url + "/gauges",
                                json={"waiting": 8}):
            pass
        get_engine_stats_scraper().scrape_once()
        t_breach = time.time()
        desired = await mgr.autoscale_once()
        assert desired["decode"] == 2, desired
        sample()
        inflight = asyncio.ensure_future(burst(4))
        await settle(2)
        scale_up_s = time.time() - t_breach
        watcher.check_and_apply()
        await inflight
        await burst(6)

        # Recovery: queues empty; the newest replica drains while it
        # still owns a live stream, and router traffic keeps flowing.
        live = list(mgr.replicas["decode"])
        for replica in live:
            async with session.post(replica.url + "/gauges",
                                    json={"waiting": 0}):
                pass
        get_engine_stats_scraper().scrape_once()
        victim = max(live, key=lambda r: r.port)
        n_stream = int(2 * speed)  # ~2s: spans the whole drain
        parked = await session.post(
            victim.url + "/v1/chat/completions",
            json={"model": "bench-fake",
                  "messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": n_stream, "stream": True})
        t_recover = time.time()
        desired = await mgr.autoscale_once()
        assert desired["decode"] == 1, desired
        await mgr.reconcile_once()
        sample()
        watcher.check_and_apply()
        inflight = asyncio.ensure_future(burst(6))
        parked_text = await parked.text()
        parked_tokens = parked_text.count('"content": "tok')
        await settle(1)
        scale_down_s = time.time() - t_recover
        await inflight
        drained_clean = victim.process.poll() is not None

        await mgr.drain_all()
        await mgr.close()
        await session.close()
        await runner.cleanup()
        return dict(
            trajectory=trajectory, results=results,
            scale_up_s=scale_up_s, scale_down_s=scale_down_s,
            parked_tokens=parked_tokens, n_stream=n_stream,
            drained_clean=drained_clean)

    out = asyncio.run(run())

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    results = out["results"]
    dropped = sum(1 for r in results if r["error"] is not None)
    n_5xx = sum(1 for r in results
                if r["status"] is not None and r["status"] >= 500)
    ttfts = [r["ttft"] for r in results if r["ttft"] is not None]
    itls = [gap for r in results for gap in r["itl"]]
    good = sum(
        1 for r in results
        if r["status"] == 200 and r["error"] is None
        and r["ttft"] is not None and r["ttft"] <= slo_ttft
        and (pctl(r["itl"], 0.99) or 0.0) <= slo_itl)
    goodput = good / len(results) if results else 0.0
    print(json.dumps({
        "metric": "fleet autoscale bench: SLO goodput across a "
                  "1->2->1 scale cycle with zero-loss drain",
        "value": round(goodput, 4),
        "unit": "fraction",
        "vs_baseline": 0.0,
        "extra": {
            "autoscale_replica_trajectory": out["trajectory"],
            "autoscale_requests_total": len(results),
            "autoscale_dropped": dropped,
            "autoscale_5xx": n_5xx,
            "autoscale_goodput": round(goodput, 4),
            "autoscale_slo_ttft_s": slo_ttft,
            "autoscale_slo_itl_s": slo_itl,
            "autoscale_ttft_p50_s": round(pctl(ttfts, 0.5) or -1.0, 4),
            "autoscale_ttft_p99_s": round(pctl(ttfts, 0.99) or -1.0, 4),
            "autoscale_itl_p99_s": round(pctl(itls, 0.99) or -1.0, 4),
            "autoscale_scale_up_s": round(out["scale_up_s"], 2),
            "autoscale_scale_down_s": round(out["scale_down_s"], 2),
            "autoscale_drained_stream_tokens": out["parked_tokens"],
            "autoscale_drained_stream_expected": out["n_stream"],
            "autoscale_drained_stream_intact": (
                out["parked_tokens"] == out["n_stream"]),
            "autoscale_drained_replica_exited": out["drained_clean"],
        },
    }))


def run_rollout_worker() -> None:
    """Safe-rollout bench (docs/fleet.md): router + fleet manager + a
    two-replica fake-engine pool driven through two full revision
    rollouts. Scenario A (good canary): a behavior-identical new
    build must promote fleet-wide with zero 5xx while a long
    checkpointed stream started before the rollout ends byte-exact,
    carried across revisions by migrate-mode drains (resume outcome
    ``migrated``). Scenario B (bad canary): a ``degrade_new_revision``
    fault bundle must be caught by the latency judge and
    automatically rolled back with the alarm gauge latched while the
    stable set keeps serving to SLO.

    Fake engines only (CPU, no JAX): the phase measures the rollout
    controller and the migration protocol, not model throughput.
    """
    import asyncio
    import socket
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import aiohttp
    from aiohttp import web

    from production_stack_tpu.fleet.autoscaler import (
        parse_prometheus_text,
    )
    from production_stack_tpu.fleet.manager import LIVE, FleetManager
    from production_stack_tpu.fleet.spec import (
        AutoscalerSpec,
        FleetSpec,
        PoolSpec,
        RevisionSpec,
        RolloutSpec,
    )
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.dynamic_config import (
        initialize_dynamic_config_watcher,
    )
    from production_stack_tpu.router.resilience import (
        ResilienceConfig,
        initialize_resilience,
    )
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    from production_stack_tpu.router.service_discovery import (
        initialize_service_discovery,
    )
    from production_stack_tpu.router.services import request_service
    from production_stack_tpu.router.services.rewriter import (
        initialize_request_rewriter,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )

    speed = float(os.environ.get("BENCH_ROLLOUT_SPEED", "200"))
    out_len = int(os.environ.get("BENCH_ROLLOUT_OUT_LEN", "24"))
    stream_s = float(os.environ.get("BENCH_ROLLOUT_STREAM_S", "8"))
    slo_ttft = float(os.environ.get("BENCH_ROLLOUT_SLO_TTFT_S", "0.5"))

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    async def run():
        request_service.stream_resumes_by_outcome.clear()
        request_service._poison_crashes.clear()
        initialize_service_discovery("static", urls=[], models=[],
                                     roles=[])
        initialize_request_stats_monitor(60.0)
        initialize_engine_stats_scraper(3600.0)
        initialize_routing_logic("roundrobin")
        initialize_request_rewriter("noop")
        initialize_resilience(ResilienceConfig(
            max_retries=2, backend_connect_timeout=2.0,
            backend_timeout=60.0, health_check_interval=0.0))
        runner = web.AppRunner(build_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        router_url = ("http://127.0.0.1:"
                      f"{site._server.sockets[0].getsockname()[1]}")

        config_path = os.path.join(tempfile.mkdtemp(), "dyn.json")
        base = free_port()
        pool = PoolSpec(
            name="decode", role="decode", min_replicas=2,
            max_replicas=4, model="bench-fake",
            command=[sys.executable, "-m",
                     "production_stack_tpu.testing.fake_engine",
                     "--host", "127.0.0.1", "--port", "{port}",
                     "--model", "{model}", "--role", "{role}",
                     "--speed", str(speed), "--ttft", "0.0",
                     "--checkpoint-interval-tokens", "2"],
            autoscaler=AutoscalerSpec(enable=False),
            revision=RevisionSpec(build_id="v1"),
            # No SLO ledger or drift sentinel in this rig: judge on
            # crash streak + canary-vs-stable p99 latency ratio.
            rollout=RolloutSpec(
                enable=True, canary_weight=0.5, bake_s=2.0,
                max_slo_burn_rate_5m=0.0, fail_on_perf_drift=False,
                max_crash_streak=1, max_latency_ratio=3.0,
                drain_mode="migrate"))
        spec = FleetSpec(
            pools=[pool], port_start=base, port_end=base + 9,
            router_url=router_url, router_config_path=config_path,
            drain_timeout_s=30.0)
        mgr = FleetManager(spec)
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=120.0))
        watcher = initialize_dynamic_config_watcher(config_path, 3600.0)

        async def one_request(n_tokens, sink=None):
            rec = {"status": None, "ttft": None, "error": None,
                   "text": ""}
            body = {"model": "bench-fake",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": n_tokens, "stream": True}
            t0 = time.time()
            parts = []
            try:
                async with session.post(
                        router_url + "/v1/chat/completions",
                        json=body) as resp:
                    rec["status"] = resp.status
                    async for raw in resp.content:
                        line = raw.decode("utf-8", "replace").strip()
                        if (not line.startswith("data: ")
                                or line == "data: [DONE]"):
                            continue
                        event = json.loads(line[len("data: "):])
                        if "choices" not in event:
                            rec["error"] = "terminal SSE error"
                            continue
                        delta = (event["choices"][0].get("delta")
                                 or {})
                        if not delta.get("content"):
                            continue
                        if rec["ttft"] is None:
                            rec["ttft"] = time.time() - t0
                        parts.append(delta["content"])
            except Exception as e:
                rec["error"] = f"{type(e).__name__}: {e}"
            rec["text"] = "".join(parts)
            if sink is not None:
                sink.append(rec)
            return rec

        async def drive_until(pred, sink, deadline_s, desc):
            """Reconcile + hot-reload + background traffic until the
            predicate holds; the traffic is what feeds the canary
            judge its per-server latency samples."""
            deadline = time.time() + deadline_s
            i = 0
            while time.time() < deadline:
                await mgr.reconcile_once()
                watcher.check_and_apply()
                if pred():
                    return
                if i % 3 == 0:
                    await asyncio.gather(
                        *(one_request(out_len, sink=sink)
                          for _ in range(4)))
                i += 1
                await asyncio.sleep(0.05)
            raise RuntimeError(f"rollout bench never reached: {desc}")

        def all_on(build):
            reps = mgr.replicas["decode"]
            return (mgr.current_revision["decode"].build_id == build
                    and len(reps) == 2
                    and all(r.build_id == build and r.state == LIVE
                            for r in reps))

        def phase():
            return (mgr.rollout.status().get("decode") or {})

        async def metric(name, label_key, label_val):
            async with session.get(router_url + "/metrics") as resp:
                text = await resp.text()
            for mname, labels, value in parse_prometheus_text(text):
                if mname == name and labels.get(label_key) == label_val:
                    return value
            return -1.0

        out = {}
        good_results, bad_results = [], []
        try:
            await drive_until(lambda: all_on("v1"), good_results,
                              30.0, "2x v1 live")

            # ---- scenario A: good canary, long stream migrates ----
            n_stream = int(stream_s * speed)
            long_task = asyncio.ensure_future(one_request(n_stream))
            await asyncio.sleep(0.3)  # stream in flight before roll
            pool.revision = RevisionSpec(build_id="v2")
            t0 = time.time()
            await drive_until(lambda: all_on("v2"), good_results,
                              90.0, "fleet rolled to v2")
            out["good_roll_s"] = time.time() - t0
            long_rec = await long_task
            out["long_rec"] = long_rec
            out["n_stream"] = n_stream
            out["migrated"] = dict(
                request_service.stream_resumes_by_outcome
            ).get("migrated", 0)

            # ---- scenario B: bad canary, judge rolls it back ------
            pool.rollout.bake_s = 4.0
            pool.revision = RevisionSpec(
                build_id="v3",
                engine_flags=["--fault", "degrade_new_revision",
                              "--slow-ttft-s", "1.0",
                              "--slow-itl-s", "0.05"])
            t1 = time.time()
            await drive_until(
                lambda: phase().get("phase") == "rolled_back",
                bad_results, 90.0, "bad canary rolled back")
            out["bad_detect_s"] = time.time() - t1
            out["bad_verdict"] = phase().get("verdict", "")
            # The v3 canary must drain away; the stable set stays v2.
            await drive_until(lambda: all_on("v2"), bad_results,
                              60.0, "stable set restored on v2")
            out["alarm"] = await metric("vllm:rollout_alarm", "pool",
                                        "decode")
            out["rollbacks"] = await metric(
                "vllm:rollout_rollbacks_total", "pool", "decode")
            # Post-rollback traffic must be back to full SLO.
            recovery = []
            await asyncio.gather(*(one_request(out_len, sink=recovery)
                                   for _ in range(8)))
            out["recovery"] = recovery
        finally:
            await mgr.drain_all()
            await mgr.close()
            await session.close()
            await runner.cleanup()
        out["good_results"] = good_results
        out["bad_results"] = bad_results
        return out

    out = asyncio.run(run())

    def fails(recs):
        n_5xx = sum(1 for r in recs
                    if r["status"] is not None and r["status"] >= 500)
        dropped = sum(1 for r in recs if r["error"] is not None)
        return n_5xx, dropped

    expected = "".join(f"tok{i} " for i in range(out["n_stream"]))
    long_rec = out["long_rec"]
    byte_exact = long_rec["text"] == expected
    good_5xx, good_dropped = fails(out["good_results"])
    bad_5xx, bad_dropped = fails(out["bad_results"])
    recovery = out["recovery"]
    attainment = (sum(
        1 for r in recovery
        if r["status"] == 200 and r["error"] is None
        and r["ttft"] is not None and r["ttft"] <= slo_ttft)
        / len(recovery)) if recovery else 0.0
    invariants = [
        byte_exact, out["migrated"] >= 1, good_5xx == 0,
        good_dropped == 0, out["alarm"] == 1.0,
        out["rollbacks"] >= 1, bad_5xx == 0, bad_dropped == 0,
        attainment >= 0.99,
    ]
    score = sum(invariants) / len(invariants)
    print(json.dumps({
        "metric": "safe-rollout bench: good canary promotes with a "
                  "byte-exact migrated stream; bad canary auto-rolls "
                  "back behind a latched alarm",
        "value": round(score, 4),
        "unit": "fraction",
        "vs_baseline": 0.0,
        "extra": {
            "rollout_good_roll_s": round(out["good_roll_s"], 2),
            "rollout_good_5xx": good_5xx,
            "rollout_good_dropped": good_dropped,
            "rollout_migrated_streams": out["migrated"],
            "rollout_migrated_stream_tokens": len(
                long_rec["text"].split()),
            "rollout_migrated_stream_expected": out["n_stream"],
            "rollout_migrated_byte_exact": byte_exact,
            "rollout_detected_bad_canary": out["rollbacks"] >= 1,
            "rollout_bad_detect_s": round(out["bad_detect_s"], 2),
            "rollout_bad_verdict": out["bad_verdict"],
            "rollout_alarm_latched": out["alarm"] == 1.0,
            "rollout_rollbacks_total": out["rollbacks"],
            "rollout_bad_5xx": bad_5xx,
            "rollout_bad_dropped": bad_dropped,
            "rollout_attainment_after_rollback": round(attainment, 4),
        },
    }))


def run_overload_worker(mode: str) -> None:
    """QoS overload bench (docs/qos.md): router + two finite-capacity
    fake engines driven at ~2x capacity by three well-behaved
    interactive tenants plus one adversarial batch tenant, with the
    router's QoS layer on (``mode=on``: per-tenant buckets, degrade
    ladder, fair gate) vs off (``mode=off``). Reports the well-behaved
    tenants' interactive goodput (fraction answered within the SLO),
    the Jain fairness index over per-tenant served tokens, and hard
    zero counts of 5xx and silent drops — shed requests must be honest
    429 + Retry-After, never an error or a hang.

    Fake engines only (CPU, no JAX): the phase measures the admission
    policy, not model throughput. The fakes' --max-concurrency slot
    model is what makes overload visible (excess requests queue and
    TTFT inflates, like a saturated pod).
    """
    import asyncio

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import aiohttp
    from aiohttp import web

    from production_stack_tpu.qos import jain_index
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.qos import (
        RouterQoSConfig,
        get_router_qos,
        initialize_router_qos,
    )
    from production_stack_tpu.router.resilience import (
        ResilienceConfig,
        initialize_resilience,
    )
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    from production_stack_tpu.router.service_discovery import (
        initialize_service_discovery,
    )
    from production_stack_tpu.router.services.rewriter import (
        initialize_request_rewriter,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )
    from production_stack_tpu.testing.fake_engine import build_fake_engine

    speed = float(os.environ.get("BENCH_OVERLOAD_SPEED", "40"))
    out_len = int(os.environ.get("BENCH_OVERLOAD_OUT_LEN", "16"))
    slots = int(os.environ.get("BENCH_OVERLOAD_SLOTS", "2"))
    n_engines = 2
    n_good = 3
    good_rate = float(os.environ.get("BENCH_OVERLOAD_GOOD_RATE", "1.5"))
    adv_rate = float(os.environ.get("BENCH_OVERLOAD_ADV_RATE", "16"))
    window = float(os.environ.get("BENCH_OVERLOAD_DURATION_S", "4"))
    slo_s = float(os.environ.get("BENCH_OVERLOAD_SLO_S", "1.5"))
    # Analytic capacity of the slot model: total decode slots over the
    # per-request service time. The offered load above is ~2x this.
    service_s = out_len / speed
    capacity = n_engines * slots / service_s
    offered = n_good * good_rate + adv_rate

    async def run():
        engine_runners = []
        urls = []
        for _ in range(n_engines):
            runner = web.AppRunner(build_fake_engine(
                model="bench-fake", speed=speed, ttft=0.0,
                priority_aware=True, max_concurrency=slots))
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            urls.append("http://127.0.0.1:"
                        f"{site._server.sockets[0].getsockname()[1]}")
            engine_runners.append(runner)

        initialize_service_discovery(
            "static", urls=urls, models=["bench-fake"] * n_engines,
            roles=None)
        initialize_request_stats_monitor(60.0)
        initialize_engine_stats_scraper(3600.0)
        initialize_routing_logic("roundrobin")
        initialize_request_rewriter("noop")
        # Generous backend timeout: under QoS-off the whole point is
        # that queues build; a timeout mid-queue would masquerade as a
        # drop.
        initialize_resilience(ResilienceConfig(
            max_retries=2, backend_connect_timeout=5.0,
            backend_timeout=60.0, health_check_interval=0.0))
        initialize_router_qos(RouterQoSConfig(
            tenant_rate=2.0, tenant_burst=4.0, degrade_max_tokens=4,
            shed_deficit=5.0, max_concurrency=n_engines * slots,
        ) if mode == "on" else RouterQoSConfig(tenant_rate=0.0))

        router_runner = web.AppRunner(build_app())
        await router_runner.setup()
        site = web.TCPSite(router_runner, "127.0.0.1", 0)
        await site.start()
        router_url = ("http://127.0.0.1:"
                      f"{site._server.sockets[0].getsockname()[1]}")
        session = aiohttp.ClientSession()
        records = []

        async def one(tenant, cls):
            rec = {"tenant": tenant, "cls": cls, "status": None,
                   "latency": None, "tokens": 0, "retry_after": None,
                   "error": None}
            t0 = time.time()
            try:
                async with session.post(
                        router_url + "/v1/chat/completions",
                        json={"model": "bench-fake",
                              "messages": [{"role": "user",
                                            "content": "hi"}],
                              "max_tokens": out_len},
                        headers={"x-api-key": tenant,
                                 "x-priority": cls}) as resp:
                    rec["status"] = resp.status
                    rec["retry_after"] = resp.headers.get("Retry-After")
                    body = await resp.json()
                    rec["latency"] = time.time() - t0
                    if resp.status == 200:
                        rec["tokens"] = int(
                            (body.get("usage") or {})
                            .get("completion_tokens", 0))
            except Exception as e:
                rec["error"] = f"{type(e).__name__}: {e}"
            records.append(rec)

        async def offer(tenant, cls, rate, t_end):
            # Open loop: requests fire on the arrival clock regardless
            # of how slow earlier ones are — that's what makes 2x
            # offered load actually land on the stack.
            tasks = []
            while time.time() < t_end:
                tasks.append(asyncio.ensure_future(one(tenant, cls)))
                await asyncio.sleep(1.0 / rate)
            return tasks

        t_end = time.time() + window
        offers = await asyncio.gather(
            offer("adversary", "batch", adv_rate, t_end),
            *(offer(f"good-{i}", "interactive", good_rate, t_end)
              for i in range(n_good)))
        await asyncio.wait_for(
            asyncio.gather(*(t for ts in offers for t in ts)),
            timeout=120.0)

        rqos = get_router_qos()
        qos_counters = {
            "router_throttled": (rqos.tenant_throttled_total
                                 if rqos else 0),
            "router_shed": dict(rqos.shed_by_class) if rqos else {},
        }
        await session.close()
        await router_runner.cleanup()
        for runner in engine_runners:
            await runner.cleanup()
        return records, qos_counters

    records, qos_counters = asyncio.run(run())

    inter = [r for r in records if r["cls"] == "interactive"]
    goodput = (sum(1 for r in inter
                   if r["status"] == 200 and r["error"] is None
                   and r["latency"] is not None
                   and r["latency"] <= slo_s)
               / len(inter) if inter else 0.0)
    tenants = sorted({r["tenant"] for r in records})
    tokens_by_tenant = {
        t: sum(r["tokens"] for r in records
               if r["tenant"] == t and r["status"] == 200)
        for t in tenants}
    served_by_tenant = {
        t: sum(1 for r in records
               if r["tenant"] == t and r["status"] == 200)
        for t in tenants}
    n_429 = sum(1 for r in records if r["status"] == 429)
    print(json.dumps({
        "metric": f"qos overload bench ({mode}): well-behaved tenants' "
                  "interactive goodput at ~2x capacity",
        "value": round(goodput, 4),
        "unit": "fraction",
        "vs_baseline": 0.0,
        "extra": {
            "mode": mode,
            "offered_req_per_s": round(offered, 2),
            "capacity_req_per_s": round(capacity, 2),
            "offered_x_capacity": round(offered / capacity, 2),
            "interactive_goodput": round(goodput, 4),
            "interactive_slo_s": slo_s,
            "jain_tokens": round(
                jain_index(tokens_by_tenant.values()), 4),
            "served_by_tenant": served_by_tenant,
            "tokens_by_tenant": tokens_by_tenant,
            "n_requests": len(records),
            "n_429": n_429,
            "n_429_with_retry_after": sum(
                1 for r in records
                if r["status"] == 429 and r["retry_after"]),
            "n_5xx": sum(1 for r in records
                         if r["status"] is not None
                         and r["status"] >= 500),
            "dropped": sum(1 for r in records
                           if r["error"] is not None),
            **qos_counters,
        },
    }))


def run_chaos_worker(mode: str) -> None:
    """Crash-chaos bench (docs/crash_recovery.md): router + a crash-
    fault fake engine (SIGKILLed mid-stream, respawned between
    streams) + a healthy peer, streaming greedy requests through the
    kills. ``mode="on"``: engines relay resume checkpoints and the
    router must finish every stream byte-exact with zero broken
    streams and zero client-visible 5xx; ``mode="off"``: no
    checkpoints — each crashed stream must end in an honest terminal
    SSE error event (counted as broken; never a silent truncation).
    The resumed-tail TTFB (the client-visible stall a kill causes) is
    the largest inter-chunk gap of each resumed stream.

    Fake engines only (CPU, no JAX): the phase measures the failover
    protocol, not model throughput.
    """
    import asyncio
    import socket

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import aiohttp
    from aiohttp import web

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.resilience import (
        ResilienceConfig,
        initialize_resilience,
    )
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    from production_stack_tpu.router.service_discovery import (
        initialize_service_discovery,
    )
    from production_stack_tpu.router.services import request_service
    from production_stack_tpu.router.services.rewriter import (
        initialize_request_rewriter,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )

    n_streams = int(os.environ.get("BENCH_CHAOS_STREAMS", "12"))
    out_len = int(os.environ.get("BENCH_CHAOS_OUT_LEN", "16"))
    speed = float(os.environ.get("BENCH_CHAOS_SPEED", "200"))
    crash_after = int(os.environ.get("BENCH_CHAOS_CRASH_AFTER", "5"))
    ckpt = 2 if mode == "on" else 0

    def free_ports(n):
        socks = [socket.socket() for _ in range(n)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        # Roundrobin orders endpoints lexicographically by URL: the
        # first (chaotic) port must sort first so kills actually land.
        return sorted(ports, key=str)

    crash_port, ok_port = free_ports(2)
    crash_url = f"http://127.0.0.1:{crash_port}"
    ok_url = f"http://127.0.0.1:{ok_port}"

    def spawn_fake(port, *extra):
        argv = [sys.executable, "-m",
                "production_stack_tpu.testing.fake_engine",
                "--host", "127.0.0.1", "--port", str(port),
                "--model", "bench-fake", "--speed", str(speed),
                "--ttft", "0.0"]
        if ckpt:
            argv += ["--checkpoint-interval-tokens", str(ckpt)]
        argv += list(extra)
        return subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def spawn_crash():
        return spawn_fake(crash_port, "--fault", "crash",
                          "--crash-after-tokens", str(crash_after))

    async def wait_up(session, url):
        deadline = time.time() + 15.0
        while time.time() < deadline:
            try:
                async with session.get(url + "/health") as resp:
                    if resp.status == 200:
                        return
            except Exception:
                pass
            await asyncio.sleep(0.05)
        raise RuntimeError(f"fake engine at {url} never came up")

    async def run():
        request_service.stream_resumes_by_outcome.clear()
        request_service.poison_quarantines_total = 0
        request_service._poison_crashes.clear()
        initialize_service_discovery(
            "static", urls=[crash_url, ok_url],
            models=["bench-fake"] * 2)
        initialize_request_stats_monitor(60.0)
        initialize_engine_stats_scraper(3600.0)
        initialize_routing_logic("roundrobin")
        initialize_request_rewriter("noop")
        initialize_resilience(ResilienceConfig(
            max_retries=2, backend_connect_timeout=2.0,
            backend_timeout=30.0, health_check_interval=0.0))
        runner = web.AppRunner(build_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        router_url = ("http://127.0.0.1:"
                      f"{site._server.sockets[0].getsockname()[1]}")

        crash_proc = spawn_crash()
        ok_proc = spawn_fake(ok_port)
        session = aiohttp.ClientSession()
        records = []
        try:
            await wait_up(session, crash_url)
            await wait_up(session, ok_url)
            body = {"model": "bench-fake",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": out_len, "stream": True}
            for _ in range(n_streams):
                if crash_proc.poll() is not None:
                    # The chaos monkey's respawn: a fresh victim for
                    # the next stream that routes to this slot.
                    crash_proc = spawn_crash()
                    await wait_up(session, crash_url)
                rec = {"status": None, "text": "", "max_gap": 0.0,
                       "terminal_error": False, "error": None,
                       "crashed": False}
                parts = []
                last = None
                try:
                    async with session.post(
                            router_url + "/v1/chat/completions",
                            json=body) as resp:
                        rec["status"] = resp.status
                        async for raw in resp.content:
                            line = raw.decode("utf-8",
                                              "replace").strip()
                            if (not line.startswith("data: ")
                                    or line == "data: [DONE]"):
                                continue
                            event = json.loads(line[len("data: "):])
                            if "choices" not in event:
                                rec["terminal_error"] = True
                                continue
                            delta = (event["choices"][0].get("delta")
                                     or {})
                            if not delta.get("content"):
                                continue
                            now = time.time()
                            if last is not None:
                                rec["max_gap"] = max(
                                    rec["max_gap"], now - last)
                            last = now
                            parts.append(delta["content"])
                except Exception as e:
                    rec["error"] = f"{type(e).__name__}: {e}"
                rec["text"] = "".join(parts)
                rec["crashed"] = crash_proc.poll() is not None
                records.append(rec)
            outcomes = dict(request_service.stream_resumes_by_outcome)
        finally:
            for proc in (crash_proc, ok_proc):
                if proc.poll() is None:
                    proc.kill()
                proc.wait(timeout=10)
            await session.close()
            await runner.cleanup()
        return records, outcomes

    records, outcomes = asyncio.run(run())

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    expected = "".join(f"tok{i} " for i in range(out_len))
    total = len(records)
    crashed = sum(1 for r in records if r["crashed"])
    byte_exact = sum(1 for r in records if r["text"] == expected)
    broken = sum(1 for r in records
                 if r["terminal_error"] or r["error"] is not None)
    resume_gaps = [r["max_gap"] for r in records
                   if r["crashed"] and not r["terminal_error"]
                   and r["error"] is None]
    survival = byte_exact / total if total else 0.0
    print(json.dumps({
        "metric": f"crash chaos bench ({mode}): byte-exact stream "
                  "survival through mid-stream engine kills",
        "value": round(survival, 4),
        "unit": "fraction",
        "vs_baseline": 0.0,
        "extra": {
            "mode": mode,
            "chaos_streams_total": total,
            "chaos_crashed_streams": crashed,
            "chaos_resumed_streams": outcomes.get("resumed", 0),
            "chaos_broken_streams": broken,
            "chaos_byte_exact_streams": byte_exact,
            "chaos_survival": round(survival, 4),
            "chaos_5xx": sum(1 for r in records
                             if r["status"] is not None
                             and r["status"] >= 500),
            "chaos_dropped": sum(1 for r in records
                                 if r["error"] is not None),
            "chaos_resume_gap_p50_s": round(
                pctl(resume_gaps, 0.5) or -1.0, 4),
            "chaos_resume_gap_p99_s": round(
                pctl(resume_gaps, 0.99) or -1.0, 4),
            "chaos_resume_outcomes": outcomes,
        },
    }))


def run_kvecon_worker(mode: str) -> None:
    """KV-economy routing A/B (docs/kv_economy.md): a multi-tenant
    prefix-heavy conversation mix against fake engines whose prefix
    hot sets have real capacity (pinning too many tenants on one
    replica thrashes its LRU), with the routing policy as the only
    variable:

      summary  -- kvstateaware on live /kv/summary scrapes
      hashring -- session affinity keyed on the prompt's first chain
                  block (blind consistent-hash pinning)
      llq      -- least loaded (spreads tenants, no reuse anywhere)

    Fake engines only (CPU, no JAX): TTFT shrinks 90% on a full
    prefix hit, so the phase measures placement quality, not model
    throughput. Reported: client TTFT percentiles and the aggregate
    prefix hit rate read straight off the engine states.
    """
    import asyncio

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import aiohttp
    from aiohttp import web

    from production_stack_tpu.kvecon.summary import chain_text
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.resilience import (
        ResilienceConfig,
        initialize_resilience,
    )
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    from production_stack_tpu.router.service_discovery import (
        initialize_service_discovery,
    )
    from production_stack_tpu.router.services.rewriter import (
        initialize_request_rewriter,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )
    from production_stack_tpu.testing.fake_engine import build_fake_engine

    # Heterogeneous KV capacity (the bf16-vs-int8 headroom spread the
    # summaries exist to expose): one value per engine, hot-set cap ==
    # advertised total pages.
    capacities = [int(c) for c in os.environ.get(
        "BENCH_KVECON_CAPACITY", "80,52,26").split(",")]
    n_tenants = int(os.environ.get("BENCH_KVECON_TENANTS", "12"))
    rounds = int(os.environ.get("BENCH_KVECON_ROUNDS", "6"))
    ttft = float(os.environ.get("BENCH_KVECON_TTFT_S", "0.08"))
    speed = float(os.environ.get("BENCH_KVECON_SPEED", "400"))
    out_len = int(os.environ.get("BENCH_KVECON_OUT_LEN", "8"))
    n_engines = len(capacities)

    # Per-tenant shared prefix: ~6 chain blocks of distinct system
    # prompt; each round appends ~1 block of conversation, so by the
    # last round a tenant's chain is ~13 blocks. The 80/52/26 fleet
    # fits exactly a 6/4/2 tenant split -- the split headroom-aware
    # packing finds and blind hashing can't (a ring's ~even spread
    # pins ~4 tenants on the 26-page replica, which thrashes).
    def system_text(t):
        seed = f"tenant-{t:03d} knowledge base. "
        return (seed * (6 * 256 // len(seed) + 1))[:6 * 256]

    def turn_text(t, r):
        return (f"tenant-{t:03d} round-{r:02d} question: " * 8)[:220]

    async def run():
        runners = []
        states = []
        urls = []
        for cap in capacities:
            app = build_fake_engine(model="bench-fake", speed=speed,
                                    ttft=ttft, kv_hot_capacity=cap,
                                    kv_total_pages=cap)
            states.append(app["state"])
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            urls.append("http://127.0.0.1:"
                        f"{site._server.sockets[0].getsockname()[1]}")

        initialize_service_discovery("static", urls=urls,
                                     models=["bench-fake"] * n_engines)
        initialize_request_stats_monitor(60.0)
        scraper = initialize_engine_stats_scraper(3600.0)
        if mode == "summary":
            initialize_routing_logic("kvstateaware")
        elif mode == "hashring":
            initialize_routing_logic("session",
                                     session_key="x-session-id")
        else:
            initialize_routing_logic("llq")
        initialize_request_rewriter("noop")
        initialize_resilience(ResilienceConfig(
            max_retries=2, backend_connect_timeout=2.0,
            backend_timeout=30.0, health_check_interval=0.0))
        router = web.AppRunner(build_app())
        await router.setup()
        site = web.TCPSite(router, "127.0.0.1", 0)
        await site.start()
        router_url = ("http://127.0.0.1:"
                      f"{site._server.sockets[0].getsockname()[1]}")

        loop = asyncio.get_event_loop()
        session = aiohttp.ClientSession()
        results = []

        async def one_request(tenant, rnd):
            messages = [{"role": "system",
                         "content": system_text(tenant)}]
            for r in range(rnd + 1):
                messages.append({"role": "user",
                                 "content": turn_text(tenant, r)})
            ring_key = str(chain_text(system_text(tenant))[0])
            rec = {"ttft": None, "error": None}
            t0 = time.time()
            try:
                async with session.post(
                        router_url + "/v1/chat/completions",
                        json={"model": "bench-fake",
                              "messages": messages,
                              "max_tokens": out_len, "stream": True},
                        headers={"x-session-id": ring_key}) as resp:
                    if resp.status != 200:
                        rec["error"] = f"status {resp.status}"
                    async for raw in resp.content:
                        line = raw.decode("utf-8", "replace").strip()
                        if (not line.startswith("data: ")
                                or line == "data: [DONE]"):
                            continue
                        delta = json.loads(
                            line[len("data: "):])["choices"][0]["delta"]
                        if delta.get("content") and rec["ttft"] is None:
                            rec["ttft"] = time.time() - t0
            except Exception as e:
                rec["error"] = f"{type(e).__name__}: {e}"
            results.append(rec)

        # Sequential submission with a fresh scrape before each
        # request: kvstateaware routes on what the engines advertise
        # RIGHT NOW (headroom packs cold tenants, hits pin warm
        # ones); the sync scraper runs in an executor so it doesn't
        # deadlock the loop serving the in-process fakes.
        for rnd in range(rounds):
            for tenant in range(n_tenants):
                await loop.run_in_executor(None, scraper.scrape_once)
                await one_request(tenant, rnd)

        scraper.close()
        await session.close()
        await router.cleanup()
        for runner in runners:
            await runner.cleanup()

        hit = sum(s.prefix_hit_tokens for s in states)
        query = sum(s.prefix_query_tokens for s in states)
        return dict(
            results=results,
            hit_rate=(hit / query) if query else 0.0,
            per_engine_hot=[len(s.kv_hot) for s in states],
        )

    out = asyncio.run(run())

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    results = out["results"]
    ttfts = [r["ttft"] for r in results if r["ttft"] is not None]
    dropped = sum(1 for r in results if r["error"] is not None)
    print(json.dumps({
        "metric": f"kv-economy routing bench ({mode}): aggregate "
                  "prefix hit rate across capped fake engines",
        "value": round(out["hit_rate"], 4),
        "unit": "fraction",
        "vs_baseline": 0.0,
        "extra": {
            "policy": mode,
            "requests_total": len(results),
            "dropped": dropped,
            "prefix_hit_rate": round(out["hit_rate"], 4),
            "ttft_p50_s": round(pctl(ttfts, 0.5) or -1.0, 4),
            "ttft_p99_s": round(pctl(ttfts, 0.99) or -1.0, 4),
            "per_engine_hot_chains": out["per_engine_hot"],
        },
    }))


def run_drift_worker(mode: str) -> None:
    """Self-tuning drift bench (docs/autotuning.md): one tiny CPU
    engine under a deliberately drifting workload — a steady phase,
    an acceptance-collapse phase (interactive streams flip from
    greedy to sampled, so prompt-lookup drafts stop landing), and a
    bursty/tenant-shift phase (long-prompt burst rate ramps up and
    background-priority prompts pile into the queue) — with the
    autotuner in ``mode`` (off|shadow|on) closing the loop on
    speculative k, the unified-step prefill budget, and the QoS shed
    gate. Scores goodput: interactive tokens whose inter-token gap
    meets the SLO (derived from this engine's own warmup ITL, so the
    bar is identical across modes on the same box).

    Also reports the compile-event delta over the measured window —
    every knob is a non-shape input, so controller decisions must
    never add compile events beyond what the traffic itself warms —
    and a greedy-output hash, which ``shadow`` must keep
    byte-identical to ``off``.
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import hashlib

    import numpy as np

    from production_stack_tpu.autotune import (
        Autotuner,
        PrefillBudgetController,
        QoSShedController,
        SpecKController,
        observatory_drift_flags,
    )
    from production_stack_tpu.engine.config import (
        AutotuneConfig,
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import (
        SamplingParams,
        SequenceState,
    )

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax-comp-cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    spec_k = 6
    engine = LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=256),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                  prefill_chunk_size=64,
                                  unified_step=True,
                                  speculative_k=spec_k),
    ))

    rng = np.random.RandomState(0)
    long_prompt_len = 256
    short_prompt_len = 32
    phase_s = float(os.environ.get("BENCH_DRIFT_PHASE_S", "5"))
    n_interactive = 3

    def prompt(n, r=rng):
        return [int(x) for x in r.randint(1, 30000, size=n)]

    def samp(max_tokens, temp=0.0, top_k=0):
        return SamplingParams(max_tokens=max_tokens, temperature=temp,
                              top_k=top_k, ignore_eos=True)

    itl = []           # interactive inter-token gaps (s)
    good_tokens = 0    # gaps meeting the SLO
    interactive_tokens = 0
    interactive = {}   # seq_id -> last token wall time (None = none)
    slo_s = None       # set after warmup
    # Current phase's interactive sampling. The collapse phase runs
    # temperature 2 with a tight top_k: outputs wander over a small
    # effective alphabet, so the ngram proposer keeps finding
    # recurring trailing grams (drafting is sustained) while the
    # sampled continuations diverge from the drafted ones —
    # acceptance collapses without drafting drying up.
    inter_samp = (0.0, 0)   # (temperature, top_k)
    tuner = None       # built after warmup (SLO-derived target)

    def submit_interactive():
        temp, top_k = inter_samp
        sid = engine.add_request(prompt(short_prompt_len),
                                 samp(40, temp, top_k), priority=0)
        interactive[sid] = None

    # Warm both program shapes outside the measured window.
    engine.generate(prompt(short_prompt_len), samp(4))

    for _ in range(n_interactive):
        submit_interactive()

    def run_phase(dur_s, burst_every, burst_size, bg_every=None):
        """Drive one traffic phase; returns its wall time."""
        nonlocal good_tokens, interactive_tokens
        start = time.time()
        next_burst = start + 0.5
        next_bg = start + 0.5 if bg_every else None
        deadline = start + dur_s
        while time.time() < deadline:
            now = time.time()
            if now >= next_burst:
                for _ in range(burst_size):
                    # Batch class (priority 1): long prompts must not
                    # starve interactive resubmissions at admission.
                    engine.add_request(prompt(long_prompt_len),
                                       samp(4), priority=1)
                next_burst += burst_every
            if next_bg is not None and now >= next_bg:
                engine.add_request(prompt(long_prompt_len),
                                   samp(4), priority=2)
                next_bg += bg_every
            if tuner is not None:
                tuner.maybe_tick()
            if not engine.has_work():
                time.sleep(0.001)
                continue
            outs = engine.step()
            now = time.time()
            for out in outs:
                if out.seq_id in interactive:
                    if out.new_token is not None:
                        last = interactive[out.seq_id]
                        if last is not None:
                            gap = now - last
                            itl.append(gap)
                            if slo_s is not None and gap <= slo_s:
                                good_tokens += 1
                        interactive[out.seq_id] = now
                        interactive_tokens += 1
                    if out.finished:
                        del interactive[out.seq_id]
                        submit_interactive()
        return time.time() - start

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    # Warmup: identical traffic until the unified program's
    # executable cache stops growing (same discipline as the unified
    # worker — first-hit bucket compiles must not land in the
    # measured window, or the compile-event delta would blame the
    # controllers for traffic-warmed shapes).
    run_phase(float(os.environ.get("BENCH_DRIFT_WARMUP_S", "3.0")),
              burst_every=1.0, burst_size=2, bg_every=1.5)
    jit = getattr(engine.runner, "_unified_jit", None)
    if jit is not None and hasattr(jit, "_cache_size"):
        prev = jit._cache_size()
        for _ in range(4):
            run_phase(1.6, burst_every=1.0, burst_size=2,
                      bg_every=1.5)
            size = jit._cache_size()
            if size == prev:
                break
            prev = size
    # Also warm the shrunk-budget bucket lattice: the on-mode
    # controller legitimately narrows chunk admission, which walks
    # ragged buckets the static budget never visits — those first-hit
    # compiles are traffic shapes, not controller recompiles, and
    # must not land in the measured ledger either.
    static_budget = engine.scheduler.mixed_prefill_budget
    engine.scheduler.mixed_prefill_budget = (
        engine.config.scheduler.prefill_chunk_size)
    run_phase(1.2, burst_every=0.6, burst_size=2, bg_every=1.0)
    engine.scheduler.mixed_prefill_budget = static_budget

    # SLO from this engine's own warmup ITL: the goodput bar and the
    # prefill controller's target are the same number, so "autotune
    # held the SLO" is exactly what goodput measures.
    slo_s = max((pctl(itl, 0.5) or 0.005) * 4.0, 0.005)
    cfg = AutotuneConfig(mode=mode, interval_s=0.25, dead_band=0.02,
                         target_itl_ms=slo_s * 1000.0)
    # Wide guardrail band: this workload's phase flips move the
    # step-time medians legitimately (sampled verify, burst mixes) —
    # a serving-default band would blame the controllers for the
    # scripted drift. The freeze semantics themselves are held by
    # tests/test_autotune.py; here the guardrail only catches a
    # controller that genuinely explodes step time.
    tuner = Autotuner(
        cfg,
        [SpecKController(engine, cfg),
         PrefillBudgetController(engine, cfg),
         QoSShedController(engine, cfg)],
        tracer=engine.tracer,
        drift_flags=observatory_drift_flags(engine.runner, band=4.0))

    # Greedy parity segment: fixed prompts from a dedicated RNG, run
    # with the tuner live. ``shadow`` must hash identically to
    # ``off`` — computing without applying may not perturb a single
    # sampled token.
    prng = np.random.RandomState(7)
    parity_seqs = [engine.sequences[engine.add_request(
        prompt(short_prompt_len, prng), samp(24), priority=0)]
        for _ in range(4)]
    done = (SequenceState.FINISHED, SequenceState.ABORTED)
    while any(seq.state not in done for seq in parity_seqs):
        tuner.maybe_tick()
        if not engine.has_work():
            time.sleep(0.001)
            continue
        for out in engine.step():
            # Keep the steady streams alive through the parity
            # segment — their finish events land here, not in
            # run_phase.
            if out.seq_id in interactive and out.finished:
                del interactive[out.seq_id]
                submit_interactive()
    greedy_hash = hashlib.sha256(json.dumps(
        [list(seq.output_token_ids)
         for seq in parity_seqs]).encode()).hexdigest()[:16]

    itl.clear()
    good_tokens = 0
    interactive_tokens = 0
    for sid in interactive:
        interactive[sid] = None  # don't count a cross-window gap
    obs = engine.runner.observatory
    compiles0 = obs.compile_events_total()
    st0 = engine.stats()

    # Measured drift phases.
    inter_samp = (0.0, 0)
    steady_wall = run_phase(phase_s, burst_every=2.0, burst_size=1)
    steady_good = good_tokens
    st_steady = engine.stats()
    inter_samp = (2.0, 4)  # acceptance collapse: drafts stop landing
    collapse_wall = run_phase(phase_s, burst_every=2.0, burst_size=1)
    collapse_good = good_tokens - steady_good
    st_collapse = engine.stats()
    inter_samp = (0.0, 0)  # burst ramp + tenant shift
    burst_wall = run_phase(phase_s, burst_every=0.5, burst_size=2,
                           bg_every=0.7)
    burst_good = good_tokens - steady_good - collapse_good

    st = engine.stats()
    drafted = (st["spec_decode_num_draft_tokens_total"]
               - st0["spec_decode_num_draft_tokens_total"])
    accepted = (st["spec_decode_num_accepted_tokens_total"]
                - st0["spec_decode_num_accepted_tokens_total"])
    c_drafted = (st_collapse["spec_decode_num_draft_tokens_total"]
                 - st_steady["spec_decode_num_draft_tokens_total"])
    c_accepted = (
        st_collapse["spec_decode_num_accepted_tokens_total"]
        - st_steady["spec_decode_num_accepted_tokens_total"])
    compile_delta = int(obs.compile_events_total() - compiles0)
    drift_wall = collapse_wall + burst_wall
    drift_good = collapse_good + burst_good
    knobs = tuner.knob_values()
    frozen = sum(1 for f in tuner.frozen_flags().values() if f)

    print(json.dumps({
        "metric": f"self-tuning drift bench ({mode}): goodput "
                  "(SLO-meeting interactive tok/s) on the drifting "
                  "phases",
        "value": round(drift_good / drift_wall, 1),
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "extra": {
            "mode": mode,
            "slo_s": round(slo_s, 4),
            "goodput_tok_s": round(drift_good / drift_wall, 1),
            "steady_goodput_tok_s": round(
                steady_good / steady_wall, 1),
            "collapse_goodput_tok_s": round(
                collapse_good / collapse_wall, 1),
            "burst_goodput_tok_s": round(burst_good / burst_wall, 1),
            "itl_p50_s": round(pctl(itl, 0.5) or 0.0, 4),
            "itl_p99_s": round(pctl(itl, 0.99) or 0.0, 4),
            "interactive_tokens": interactive_tokens,
            "spec_acceptance_rate": round(
                accepted / drafted, 4) if drafted else None,
            "collapse_spec_acceptance": round(
                c_accepted / c_drafted, 4) if c_drafted else None,
            "decisions": sum(tuner.decisions_total.values()),
            "applied": sum(tuner.applied_total.values()),
            "frozen_controllers": frozen,
            "spec_k_knob": round(knobs.get("spec_k", 0.0), 2),
            "prefill_budget_knob": round(
                knobs.get("prefill_budget", 0.0), 1),
            "qos_shed_knob": round(knobs.get("qos_shed", 0.0), 3),
            "compile_events_delta": compile_delta,
            "greedy_hash": greedy_hash,
        },
    }))


def _spawn_worker(impl: str, tpu: bool, timeout: int, extra_env=None):
    """Run one benchmark worker; returns (result_dict | None, error)."""
    cmd = [sys.executable, os.path.abspath(__file__),
           "--worker", impl] + (["--tpu"] if tpu else [])
    env = dict(os.environ)
    env["BENCH_DEVICE_KIND"] = _PROBE_LOG.get("device_kind", "")
    env.update(extra_env or {})
    try:
        out = subprocess.run(cmd, timeout=timeout, capture_output=True,
                             text=True, env=env)
    except subprocess.TimeoutExpired:
        return None, (f"{impl} worker exceeded {timeout}s "
                      "(hang — possible Mosaic compile wedge)")
    sys.stderr.write(out.stderr[-2000:] + "\n")
    if out.returncode != 0:
        return None, (f"{impl} worker rc={out.returncode}: "
                      + out.stderr.strip()[-500:])
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue  # truncated line (worker killed mid-print)
    return None, f"{impl} worker printed no JSON"


def _load_baseline() -> float:
    try:
        with open(os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "BASELINE.json")) as f:
            return float(json.load(f)["published"]["req_per_s"])
    except Exception:
        return 1.0


def main() -> None:
    if "--worker" in sys.argv:
        impl = sys.argv[sys.argv.index("--worker") + 1]
        if impl == "disagg":
            run_disagg_worker(os.environ.get("BENCH_DISAGG_MODE", "mono"))
        elif impl == "unified":
            run_unified_worker(
                os.environ.get("BENCH_UNIFIED_MODE", "off"))
        elif impl == "autoscale":
            run_autoscale_worker()
        elif impl == "rollout":
            run_rollout_worker()
        elif impl == "overload":
            run_overload_worker(
                os.environ.get("BENCH_OVERLOAD_QOS", "off"))
        elif impl == "chaos":
            run_chaos_worker(os.environ.get("BENCH_CHAOS_CKPT", "on"))
        elif impl == "kvecon":
            run_kvecon_worker(
                os.environ.get("BENCH_KVECON_POLICY", "summary"))
        elif impl == "scaleout":
            run_scaleout_worker()
        elif impl == "drift":
            run_drift_worker(
                os.environ.get("BENCH_DRIFT_AUTOTUNE", "off"))
        else:
            run_worker(impl, tpu="--tpu" in sys.argv)
        return

    tpu = _tpu_available()
    timeout = int(os.environ.get("BENCH_WORKER_TIMEOUT_S", "1500"))
    if not tpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        if os.environ.get("PYTHONPATH", "").find("axon") != -1:
            os.environ["PYTHONPATH"] = ""

    # The default attempt list is the measured winner first (xla
    # attention + per_layer cache via CacheConfig 'auto' — 11.07
    # req/s on-chip 2026-07-31), stacked as the fallback. The 'auto'
    # dispatch (pallas prefill) is deliberately NOT attempted by the
    # driver: its fresh Mosaic AOT compile is the known tunnel-wedge
    # trigger (2026-07-31 01:27 UTC the auto worker hung 1500 s and
    # wedged the tunnel for the phases after it — results/
    # round5_notes.md); a wedge here would take the fallback attempts
    # down with it. BENCH_IMPLS overrides for experiments (e.g.
    # BENCH_IMPLS="auto,xla+stacked").
    if os.environ.get("BENCH_IMPLS"):
        attempts = os.environ["BENCH_IMPLS"].split(",")
    else:
        attempts = ["xla", "xla+stacked"] if tpu else ["xla"]
    errors = {}
    result = None
    for impl in attempts:
        sys.stderr.write(f"[bench] running {impl} worker "
                         f"(timeout {timeout}s)...\n")
        result, err = _spawn_worker(impl, tpu, timeout,
                                    extra_env={"BENCH_SPEC_K": "0"})
        if result is not None:
            break
        errors[f"{impl}_error"] = err
        sys.stderr.write(
            "[bench] " + "=" * 60 + "\n"
            f"[bench] WARNING: {err}\n"
            "[bench] " + "=" * 60 + "\n")

    if result is not None:
        # Second pass with draft-free speculative decoding on
        # (docs/speculative.md), same impl and same subprocess-timeout
        # harness. Its numbers ride in extra under spec_on_* so the
        # top-level metric/value/vs_baseline schema is unchanged.
        spec_k = os.environ.get("BENCH_SPEC_K", "8")
        sys.stderr.write(f"[bench] running {impl} spec-on worker "
                         f"(k={spec_k}, timeout {timeout}s)...\n")
        spec_result, spec_err = _spawn_worker(
            impl, tpu, timeout, extra_env={"BENCH_SPEC_K": spec_k})
        if spec_result is not None:
            se = spec_result.get("extra", {})
            result["extra"]["spec_on_req_per_s"] = spec_result["value"]
            for key in ("decode_tokens_per_s", "spec_acceptance_rate",
                        "spec_draft_tokens", "spec_accepted_tokens",
                        "speculative_k"):
                result["extra"][f"spec_on_{key}"] = se.get(key)
        else:
            errors["spec_on_error"] = spec_err
            sys.stderr.write(f"[bench] WARNING: {spec_err}\n")

        # Async-pipeline A/B (docs/async_pipeline.md): same impl and
        # harness, both sides forced to single-step decode so
        # async_scheduling is the only variable. Numbers ride in
        # extra under async_off_* / async_on_*; the full-occupancy
        # decode phase (decode_phase_tokens_per_s) is the comparison
        # the pipeline targets.
        ab = {}
        for tag, flag in (("async_off", "0"), ("async_on", "1")):
            sys.stderr.write(f"[bench] running {impl} {tag} worker "
                             f"(timeout {timeout}s)...\n")
            ab_result, ab_err = _spawn_worker(
                impl, tpu, timeout,
                extra_env={"BENCH_SPEC_K": "0",
                           "BENCH_DECODE_STEPS": "1",
                           "BENCH_ASYNC": flag})
            if ab_result is None:
                errors[f"{tag}_error"] = ab_err
                sys.stderr.write(f"[bench] WARNING: {ab_err}\n")
                continue
            ab[tag] = ab_result
            ae = ab_result.get("extra", {})
            result["extra"][f"{tag}_req_per_s"] = ab_result["value"]
            for key in ("decode_phase_tokens_per_s",
                        "host_device_overlap_fraction",
                        "engine_step_host_s", "engine_device_idle_s",
                        "pipeline_ahead_steps", "pipeline_steps"):
                result["extra"][f"{tag}_{key}"] = ae.get(key)

        # KV-dtype A/B (docs/kv_quantization.md): same impl and
        # harness, same page_size/num_pages input on both sides (=
        # the same HBM byte budget) — kv_cache_dtype is the only
        # variable, and the int8 side's EngineConfig expands its page
        # count ~2x at those bytes. Numbers ride in extra under
        # kv_bf16_* / kv_int8_*: decode rate for the <=5%% regression
        # check, page capacity + analytic max decode batch for the
        # capacity win.
        for tag, dt in (("kv_bf16", "bf16"), ("kv_int8", "int8")):
            sys.stderr.write(f"[bench] running {impl} {tag} worker "
                             f"(timeout {timeout}s)...\n")
            kv_result, kv_err = _spawn_worker(
                impl, tpu, timeout,
                extra_env={"BENCH_SPEC_K": "0", "BENCH_KV_DTYPE": dt})
            if kv_result is None:
                errors[f"{tag}_error"] = kv_err
                sys.stderr.write(f"[bench] WARNING: {kv_err}\n")
                continue
            ke = kv_result.get("extra", {})
            result["extra"][f"{tag}_req_per_s"] = kv_result["value"]
            for key in ("decode_tokens_per_s", "kv_page_capacity",
                        "kv_bytes_per_decode_step",
                        "kv_max_decode_batch"):
                result["extra"][f"{tag}_{key}"] = ke.get(key)

        # Disaggregated prefill/decode A/B (docs/disaggregation.md):
        # bursty long-prompt arrivals on the engine serving steady
        # interactive decode streams, vs handed off to a separate
        # prefill engine through a live cache server. Always the
        # tiny CPU config (the phase measures scheduling interference
        # structure, not a chip number — and two engines on one chip
        # would fight over HBM). Interactive ITL p99 and long-prompt
        # TTFT ride in extra under disagg_mono_* / disagg_split_*.
        for tag, mode in (("disagg_mono", "mono"),
                          ("disagg_split", "disagg")):
            sys.stderr.write(f"[bench] running {tag} worker "
                             f"(timeout {timeout}s)...\n")
            dg_result, dg_err = _spawn_worker(
                "disagg", False, timeout,
                extra_env={"BENCH_DISAGG_MODE": mode,
                           "JAX_PLATFORMS": "cpu"})
            if dg_result is None:
                errors[f"{tag}_error"] = dg_err
                sys.stderr.write(f"[bench] WARNING: {dg_err}\n")
                continue
            de = dg_result.get("extra", {})
            for key in ("itl_p50_s", "itl_p99_s", "ttft_p50_s",
                        "ttft_p99_s", "interactive_tokens",
                        "long_requests_finished"):
                result["extra"][f"{tag}_{key}"] = de.get(key)

        # Unified ragged-step A/B (docs/unified_step.md): the same
        # mixed workload as the disagg phase on ONE engine —
        # bursty long prompts against steady interactive decode —
        # with the unified mixed step as the only variable. Always
        # the tiny CPU config (scheduling interference structure,
        # not a chip number). Interactive decode rate/ITL, long-
        # prompt TTFT and the mixed dispatches' pad ratio ride in
        # extra under unified_off_* / unified_on_*.
        for tag, mode in (("unified_off", "off"), ("unified_on", "on")):
            sys.stderr.write(f"[bench] running {tag} worker "
                             f"(timeout {timeout}s)...\n")
            un_result, un_err = _spawn_worker(
                "unified", False, timeout,
                extra_env={"BENCH_UNIFIED_MODE": mode,
                           "JAX_PLATFORMS": "cpu"})
            if un_result is None:
                errors[f"{tag}_error"] = un_err
                sys.stderr.write(f"[bench] WARNING: {un_err}\n")
                continue
            ue = un_result.get("extra", {})
            for key in ("decode_tok_s", "itl_p99_s", "ttft_p99_s",
                        "ragged_pad_ratio", "ragged_steps",
                        "attention_impl_unified",
                        "ragged_kernel_active",
                        "interactive_tokens",
                        "long_requests_finished"):
                result["extra"][f"{tag}_{key}"] = ue.get(key)

        # Fleet autoscale phase (docs/fleet.md): the control loop +
        # zero-loss drain over fake-engine subprocesses — replica
        # trajectory, SLO goodput, and a hard zero dropped/5xx count
        # across the 1->2->1 cycle ride in extra under autoscale_*.
        sys.stderr.write(f"[bench] running autoscale worker "
                         f"(timeout {timeout}s)...\n")
        as_result, as_err = _spawn_worker(
            "autoscale", False, timeout,
            extra_env={"JAX_PLATFORMS": "cpu"})
        if as_result is None:
            errors["autoscale_error"] = as_err
            sys.stderr.write(f"[bench] WARNING: {as_err}\n")
        else:
            for key, value in as_result.get("extra", {}).items():
                if key.startswith("autoscale_"):
                    result["extra"][key] = value

        # Safe-rollout phase (docs/fleet.md): canary-scored rolling
        # upgrade A/B over fake-engine subprocesses — a good canary
        # promotes fleet-wide with a byte-exact migrated stream and
        # zero 5xx, a fault-injected bad canary auto-rolls-back
        # behind a latched alarm. Rides in extra under rollout_*.
        sys.stderr.write(f"[bench] running rollout worker "
                         f"(timeout {timeout}s)...\n")
        ro_result, ro_err = _spawn_worker(
            "rollout", False, timeout,
            extra_env={"JAX_PLATFORMS": "cpu"})
        if ro_result is None:
            errors["rollout_error"] = ro_err
            sys.stderr.write(f"[bench] WARNING: {ro_err}\n")
        else:
            for key, value in ro_result.get("extra", {}).items():
                if key.startswith("rollout_"):
                    result["extra"][key] = value

        # QoS overload A/B (docs/qos.md): the same ~2x-capacity mixed-
        # tenant load with the router's QoS layer as the only variable.
        # Interactive goodput, Jain fairness over served tokens, and
        # the zero-5xx / zero-silent-drop invariants ride in extra
        # under overload_qos_off_* / overload_qos_on_*.
        for tag, qmode in (("overload_qos_off", "off"),
                           ("overload_qos_on", "on")):
            sys.stderr.write(f"[bench] running {tag} worker "
                             f"(timeout {timeout}s)...\n")
            ov_result, ov_err = _spawn_worker(
                "overload", False, timeout,
                extra_env={"BENCH_OVERLOAD_QOS": qmode,
                           "JAX_PLATFORMS": "cpu"})
            if ov_result is None:
                errors[f"{tag}_error"] = ov_err
                sys.stderr.write(f"[bench] WARNING: {ov_err}\n")
                continue
            oe = ov_result.get("extra", {})
            for key in ("interactive_goodput", "jain_tokens",
                        "offered_x_capacity", "n_requests", "n_429",
                        "n_429_with_retry_after", "n_5xx", "dropped",
                        "router_throttled"):
                result["extra"][f"{tag}_{key}"] = oe.get(key)

        # Mid-stream crash chaos A/B (docs/crash_recovery.md): the
        # same kill-an-engine-mid-stream workload with resume
        # checkpointing as the only variable. With it on, every
        # crashed stream must finish byte-exact (broken == 0, 5xx ==
        # 0); with it off, crashed streams end in honest terminal SSE
        # errors. Survival, resume counts and the resumed-tail stall
        # ride in extra under chaos_ckpt_on_* / chaos_ckpt_off_*.
        for tag, cmode in (("chaos_ckpt_on", "on"),
                           ("chaos_ckpt_off", "off")):
            sys.stderr.write(f"[bench] running {tag} worker "
                             f"(timeout {timeout}s)...\n")
            ch_result, ch_err = _spawn_worker(
                "chaos", False, timeout,
                extra_env={"BENCH_CHAOS_CKPT": cmode,
                           "JAX_PLATFORMS": "cpu"})
            if ch_result is None:
                errors[f"{tag}_error"] = ch_err
                sys.stderr.write(f"[bench] WARNING: {ch_err}\n")
                continue
            ce = ch_result.get("extra", {})
            for key in ("chaos_streams_total", "chaos_crashed_streams",
                        "chaos_resumed_streams", "chaos_broken_streams",
                        "chaos_byte_exact_streams", "chaos_survival",
                        "chaos_5xx", "chaos_dropped",
                        "chaos_resume_gap_p50_s",
                        "chaos_resume_gap_p99_s"):
                result["extra"][f"{tag}_{key}"] = ce.get(key)

        # Cluster KV economy routing A/B (docs/kv_economy.md): the
        # same multi-tenant prefix-heavy mix against capped-hot-set
        # fake engines, with the routing policy as the only variable.
        # Summary routing must beat both the blind hash ring and
        # least-loaded on hit rate with TTFT p50 improved; numbers
        # ride in extra under kvecon_{summary,hashring,llq}_*.
        for tag, kmode in (("kvecon_summary", "summary"),
                           ("kvecon_hashring", "hashring"),
                           ("kvecon_llq", "llq")):
            sys.stderr.write(f"[bench] running {tag} worker "
                             f"(timeout {timeout}s)...\n")
            ke_result, ke_err = _spawn_worker(
                "kvecon", False, timeout,
                extra_env={"BENCH_KVECON_POLICY": kmode,
                           "JAX_PLATFORMS": "cpu"})
            if ke_result is None:
                errors[f"{tag}_error"] = ke_err
                sys.stderr.write(f"[bench] WARNING: {ke_err}\n")
                continue
            ke = ke_result.get("extra", {})
            for key in ("prefix_hit_rate", "ttft_p50_s",
                        "ttft_p99_s", "requests_total", "dropped"):
                result["extra"][f"{tag}_{key}"] = ke.get(key)

        # Scale-out phase (docs/parallelism.md): independent tp=2
        # replicas on disjoint 2-device meshes — the slice-as-replica
        # layout MeshPlan produces — at 1/2/4 replicas on the
        # 8-virtual-device host. Aggregate decode goodput per chip
        # and the 1->2 / 1->4 linearity ratios ride in extra under
        # scaleout_*; the acceptance bar is per-chip goodput within
        # 10% of linear going 1 -> 2 replicas.
        sys.stderr.write(f"[bench] running scaleout worker "
                         f"(timeout {timeout}s)...\n")
        so_result, so_err = _spawn_worker(
            "scaleout", False, timeout,
            extra_env={
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                              " --xla_force_host_platform_device_"
                              "count=8").strip()})
        if so_result is None:
            errors["scaleout_error"] = so_err
            sys.stderr.write(f"[bench] WARNING: {so_err}\n")
        else:
            for key, value in so_result.get("extra", {}).items():
                if key.startswith("scaleout_"):
                    result["extra"][key] = value

        # Self-tuning drift A/B (docs/autotuning.md): the same
        # drifting workload (acceptance collapse, burst ramp, tenant
        # shift) with the autotuner off / shadow / on as the only
        # variable. The acceptance bar is on-goodput >= off-goodput
        # on the drifting phases with zero extra compile events, and
        # shadow's greedy output hash byte-identical to off's
        # (shadow computes, never applies). Numbers ride in extra
        # under autotune_{off,shadow,on}_*.
        drift = {}
        for tag, dmode in (("autotune_off", "off"),
                           ("autotune_shadow", "shadow"),
                           ("autotune_on", "on")):
            sys.stderr.write(f"[bench] running {tag} worker "
                             f"(timeout {timeout}s)...\n")
            dr_result, dr_err = _spawn_worker(
                "drift", False, timeout,
                extra_env={"BENCH_DRIFT_AUTOTUNE": dmode,
                           "JAX_PLATFORMS": "cpu"})
            if dr_result is None:
                errors[f"{tag}_error"] = dr_err
                sys.stderr.write(f"[bench] WARNING: {dr_err}\n")
                continue
            drift[tag] = dr_result.get("extra", {})
            for key in ("goodput_tok_s", "collapse_goodput_tok_s",
                        "burst_goodput_tok_s", "itl_p99_s",
                        "spec_acceptance_rate", "decisions",
                        "applied", "frozen_controllers",
                        "spec_k_knob", "prefill_budget_knob",
                        "compile_events_delta"):
                result["extra"][f"{tag}_{key}"] = drift[tag].get(key)
        if "autotune_off" in drift and "autotune_on" in drift:
            result["extra"]["autotune_on_extra_compile_events"] = max(
                0, (drift["autotune_on"].get(
                        "compile_events_delta") or 0)
                - (drift["autotune_off"].get(
                       "compile_events_delta") or 0))
        if "autotune_off" in drift and "autotune_shadow" in drift:
            result["extra"]["autotune_shadow_byte_identical"] = int(
                drift["autotune_shadow"].get("greedy_hash")
                == drift["autotune_off"].get("greedy_hash"))

    if result is None:
        # Never hang the driver: report the failure as the metric line.
        extra = dict(_PROBE_LOG)
        extra.update(errors)
        print(json.dumps({
            "metric": "multi-round-qa-style req/s (FAILED)",
            "value": 0.0,
            "unit": "req/s",
            "vs_baseline": 0.0,
            "extra": extra,
        }))
        return

    result["extra"].update(_PROBE_LOG)
    result["extra"].update(errors)
    if result["extra"].get("platform") == "tpu":
        # BASELINE.json's published entry was measured on this TPU
        # rig; comparing a CPU-fallback number against it would be
        # meaningless.
        result["vs_baseline"] = round(
            result["value"] / _load_baseline(), 3)
    else:
        result["vs_baseline"] = 0.0
        result["extra"]["vs_baseline_note"] = (
            "no comparison: CPU fallback vs a TPU-measured baseline")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
