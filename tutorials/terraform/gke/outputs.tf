output "cluster_endpoint" {
  value = google_container_cluster.stack.endpoint
}

output "kubeconfig_command" {
  value = "gcloud container clusters get-credentials ${var.cluster_name} --zone ${var.zone} --project ${var.project_id}"
}
