# GKE + TPU v5e infrastructure for the TPU serving stack
# (counterpart of reference tutorials/terraform/gke, which provisions a
# GPU cluster; here the engine pool is a TPU pod-slice node pool and no
# device operator is needed).

terraform {
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = ">= 5.0"
    }
    helm = {
      source  = "hashicorp/helm"
      version = ">= 2.12"
    }
  }
}

provider "google" {
  project = var.project_id
  region  = var.region
}

resource "google_container_cluster" "stack" {
  name     = var.cluster_name
  location = var.zone

  # Router/observability/control-plane tier.
  initial_node_count = 2
  node_config {
    machine_type = "e2-standard-8"
  }

  addons_config {
    gcp_filestore_csi_driver_config {
      enabled = true
    }
  }
  deletion_protection = false
}

resource "google_container_node_pool" "tpu" {
  name     = "tpu-pool"
  cluster  = google_container_cluster.stack.name
  location = var.zone

  initial_node_count = var.tpu_node_count

  autoscaling {
    min_node_count = var.tpu_node_count
    max_node_count = var.tpu_max_nodes
  }

  node_config {
    machine_type = var.tpu_machine_type # e.g. ct5lp-hightpu-8t

    taint {
      key    = "google.com/tpu"
      value  = "present"
      effect = "NO_SCHEDULE"
    }
  }

  placement_policy {
    type         = "COMPACT"
    tpu_topology = var.tpu_topology
  }
}

provider "helm" {
  kubernetes {
    host  = "https://${google_container_cluster.stack.endpoint}"
    token = data.google_client_config.default.access_token
    cluster_ca_certificate = base64decode(
      google_container_cluster.stack.master_auth[0].cluster_ca_certificate
    )
  }
}

data "google_client_config" "default" {}

resource "helm_release" "tpu_stack" {
  count      = var.install_chart ? 1 : 0
  name       = "tpu-stack"
  chart      = "${path.module}/../../../helm"
  depends_on = [google_container_node_pool.tpu]

  values = [file(var.values_file)]
}
