variable "project_id" {
  type        = string
  description = "GCP project"
}

variable "region" {
  type    = string
  default = "us-central2"
}

variable "zone" {
  type    = string
  default = "us-central2-b"
}

variable "cluster_name" {
  type    = string
  default = "tpu-stack"
}

variable "tpu_machine_type" {
  type        = string
  default     = "ct5lp-hightpu-8t" # v5e, 8 chips/node
  description = "TPU VM machine type for the engine pool"
}

variable "tpu_topology" {
  type    = string
  default = "2x4"
}

variable "tpu_node_count" {
  type    = number
  default = 1
}

variable "tpu_max_nodes" {
  type        = number
  default     = 4
  description = "Autoscaler ceiling (match the HPA's maxReplicas)"
}

variable "install_chart" {
  type    = bool
  default = true
}

variable "values_file" {
  type    = string
  default = "../../../deployment_on_cloud/gcp/production_stack_specification.yaml"
}
