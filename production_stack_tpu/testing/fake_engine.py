"""Fake serving engine for router tests and perf rigs.

Capability parity with reference src/tests/perftest/fake-openai-server.py:
an OpenAI-compatible HTTP server that streams chat-completion chunks at a
configurable tokens/sec rate (``--speed``) after a configurable first-token
delay (``--ttft``), and exposes a synthetic vLLM-style ``/metrics``
exposition — so the full router stack can be exercised with zero TPUs.

Fault injection (for resilience tests): ``--fault MODE`` at startup or
``POST /fault {"mode": MODE}`` at runtime, with MODE one of

- ``error500``       every API request answers 500 ( /health too )
- ``hang``           accept the connection, never send a response
- ``slow_first_token``  first token delayed by ``--fault-ttft`` seconds
- ``abort_mid_stream``  stream a couple of chunks, then drop the socket
- ``crash``          chaos (docs/crash_recovery.md): SIGKILL the whole
                     process after ``--crash-after-tokens`` streamed
                     tokens — the rawest mid-stream death, no FIN, no
                     terminating chunk. Only sane for subprocess fakes
                     (fleet pools, chaos tests); an in-process fake
                     would kill the test runner.
- ``hang_step``      a wedged device step: streams stall mid-response
                     without closing, and /health answers 503
                     ``{"status": "watchdog"}`` like the real server's
                     ``--step-watchdog-s`` trip.
- ``unhealthy``      API keeps working but /health answers 500
- ``kv_missing``     disagg: a prefill-role fake emits descriptors whose
                     pages are unavailable; a decode-role fake answers
                     409 to every handoff (KV never restorable here)
- ``overload``       QoS (docs/qos.md): the fake is "saturated" — it
                     keeps serving ``interactive`` requests but answers
                     429 + Retry-After to every other priority class,
                     counting them in ``vllm:qos_shed_total{class=...}``
                     and emitting a ``qos_shed`` span event. With
                     ``--priority-aware`` the class comes from the
                     request's ``x-priority`` header; without it every
                     request is treated as the deployment default
                     (batch), i.e. everything is shed.
- ``slow_ttft``      SLO-breach timing fault (docs/observability.md):
                     first token delayed by an extra ``--slow-ttft-s``
                     seconds — the stream still completes cleanly, so
                     router-side SLO ledger / slow-archive tests see a
                     breaching-but-successful request
- ``slow_itl``       SLO-breach timing fault: every streamed token
                     takes ``--slow-itl-s`` seconds instead of
                     ``1/speed``
- ``degrade_new_revision``  rollout-canary fault bundle
                     (docs/fleet.md): slow_ttft AND slow_itl at once
                     while /health stays green — the shape of a bad
                     build that boots fine but serves badly, which
                     only the rollout judge's bake-window scoring
                     catches
- ``null``/absent    healthy (clears a previously set fault)

Disaggregation (docs/disaggregation.md): ``--role prefill|decode|both``
is reported in ``/health`` for the router's role discovery, and the
fakes serve ``/v1/disagg/prefill`` (returns a handoff descriptor) and
``/v1/disagg/handoff`` (streams from a descriptor) with output
byte-identical to the monolithic fake endpoints.

Fleet-manager support (docs/fleet.md), mirroring the real engine server:

- ``POST /drain`` flips DRAINING — new admissions answer 503 +
  Retry-After while in-flight streams finish byte-identically; with
  ``{"exit": true}`` the process exits clean once idle.
- ``POST /gauges`` injects deterministic load-gauge values (waiting
  depth, cache usage) into ``/metrics`` so autoscaler tests can drive
  SLO signals without real load.

Connection refusal needs no mode: point the router at an unbound port.

Run: ``python -m production_stack_tpu.testing.fake_engine --port 9001``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid
from typing import Optional

from aiohttp import web

# Stdlib-only by design (no JAX, no engine imports beyond it): the fake
# reuses the real engine's tracer so router-side stitching tests see
# genuine {"span": "engine_request"} lines without a TPU.
from production_stack_tpu.engine.tracing import EngineTracer
from production_stack_tpu.version import __version__
from production_stack_tpu.kvecon.summary import (
    chain_text,
    expected_hit_blocks,
    routable_text,
    TOKENS_PER_BLOCK,
)
from production_stack_tpu.qos import (
    DEFAULT_PRIORITY,
    parse_priority,
    Priority,
    PRIORITY_HEADER,
    priority_name,
    shed_counter_dict,
)


FAULT_MODES = (
    "error500", "hang", "slow_first_token", "abort_mid_stream", "crash",
    "hang_step", "unhealthy", "kv_missing", "overload",
    "slow_ttft", "slow_itl", "degrade_new_revision",
)

ENGINE_ROLES = ("prefill", "decode", "both")

# endpoint-contract markers (staticcheck/analyzers/endpoint_contract.py):
# every real-server route is mirrored here or listed below with the
# reason the fake cannot (or need not) fake it. Both directions are
# linted — a stale or redundant entry is itself a finding.
FAKE_ENGINE_EXEMPT = {
    "POST /v1/embeddings":
        "pooling endpoints run a real model forward (hidden-state "
        "pooling); router tests exercise generation routing, and a "
        "fabricated embedding vector would only test the fabrication",
    "POST /v1/score":
        "cross-encoder scoring needs a real forward pass — see "
        "POST /v1/embeddings",
    "POST /score":
        "alias of /v1/score — same real-forward dependency",
    "POST /v1/rerank":
        "rerank is score over N candidates — same real-forward "
        "dependency",
    "POST /rerank":
        "alias of /v1/rerank — same real-forward dependency",
    "POST /debug/profiler/start":
        "drives the live JAX profiler on the device; meaningless "
        "without a TPU and never routed through the router",
    "POST /debug/profiler/stop":
        "paired with /debug/profiler/start — same device dependency",
    "POST /kv/batch_get":
        "cache-server route: tests run the real CacheServer app "
        "in-process (it has no device dependency) instead of faking it",
    "PUT /kv/{key}":
        "cache-server route — real CacheServer runs in-process for "
        "tests",
    "HEAD /kv/{key}":
        "cache-server route — real CacheServer runs in-process for "
        "tests",
    "GET /kv/{key}":
        "cache-server route — real CacheServer runs in-process for "
        "tests",
    "GET /stats":
        "cache-server route — real CacheServer runs in-process for "
        "tests",
}

# Routes only the fake serves: test hooks with no real-server twin.
FAKE_ONLY_ROUTES = {
    "POST /fault": "fault-injection hook for resilience tests",
    "POST /gauges": "injects deterministic load-gauge values so "
                    "autoscaler tests can drive SLO signals",
    "POST /kv/summary": "lets KV-economy tests plant the hot-chain "
                        "snapshot the GET serves",
    "GET /cluster/status": "single-fake stand-in for the ROUTER's "
                           "fleet rollup (router/app.py serves the "
                           "real one) so stacktop render tests run "
                           "without a router",
    "POST /autotune/knobs": "plants knob values / frozen flags the "
                            "fake reports in /metrics and "
                            "/cluster/status, so router and fleet "
                            "self-tuning tests run without a real "
                            "engine's controller loop",
}


class FakeEngineState:
    def __init__(self, model: str, speed: float, ttft: float,
                 max_tokens_default: int = 32,
                 fault: Optional[str] = None, fault_ttft: float = 5.0,
                 role: str = "both", priority_aware: bool = False,
                 max_concurrency: int = 0,
                 checkpoint_interval: int = 0,
                 crash_after_tokens: int = 4,
                 kv_hot_capacity: int = 128,
                 kv_total_pages: int = 512,
                 build_id: str = ""):
        self.model = model
        self.speed = speed  # tokens per second
        self.ttft = ttft  # seconds before first token
        self.max_tokens_default = max_tokens_default
        self.running = 0
        self.waiting = 0
        self.total_served = 0
        self.fault = fault  # one of FAULT_MODES or None
        self.fault_ttft = fault_ttft  # slow_first_token delay
        # SLO-breach timing faults (docs/observability.md): extra
        # first-token delay / per-token cadence under the slow_ttft /
        # slow_itl fault modes.
        self.slow_ttft_s = 0.75
        self.slow_itl_s = 0.2
        self.requests_received = 0  # API hits incl. faulted ones
        self.role = role  # reported in /health for role discovery
        self.disagg_prefills = 0  # descriptors emitted
        self.disagg_decodes = 0  # handoffs streamed
        self.draining = False  # POST /drain flips; 503s new admissions
        # Migrate-mode drain (fleet rollouts, docs/fleet.md): in-flight
        # checkpointed streams are cut at their next checkpoint
        # boundary so the router resumes them on a live replica instead
        # of waiting out multi-minute generations.
        self.migrate_drain = False
        # Build revision reported in /version and /health so rollout
        # tests and bench can assert revision membership.
        self.build_id = build_id
        self.cache_usage = None  # POST /gauges override; None = derived
        # QoS (docs/qos.md): when priority-aware the fake reads the
        # x-priority header; the overload fault sheds non-interactive
        # classes and these counters back vllm:qos_shed_total.
        self.priority_aware = priority_aware
        self.qos_shed_counts = shed_counter_dict()
        # Capacity model (bench.py overload phase): > 0 = that many
        # decode slots; excess requests QUEUE (waiting gauge rises,
        # TTFT inflates) exactly like a saturated pod — without it the
        # fake serves unlimited concurrency and overload is invisible.
        self.max_concurrency = max_concurrency
        self._slots: Optional[asyncio.Semaphore] = None
        # Crash recovery (docs/crash_recovery.md): with a checkpoint
        # interval set, streams carry ``: checkpoint {json}`` comment
        # frames every N tokens and /v1/resume continues a broken
        # stream from a descriptor; the crash fault SIGKILLs the
        # process after this many streamed tokens.
        self.checkpoint_interval = checkpoint_interval
        self.crash_after_tokens = crash_after_tokens
        self.stream_resumes = 0
        # Real EngineTracer (engine/tracing.py): fakes emit the same
        # engine-span lines and serve /debug/trace/{id} as the real
        # server. None disables tracing entirely.
        self.tracer: Optional[EngineTracer] = None
        # Cluster KV economy (docs/kv_economy.md): capped LRU hot set
        # of text-domain prefix chain hashes — the fake's stand-in for
        # "which prefixes have live KV here". The CAP matters: a fake
        # with unbounded memory would make every routing policy look
        # prefix-perfect, so pinning too many distinct prefixes on one
        # replica must thrash, exactly like a real page budget.
        self.kv_hot_capacity = kv_hot_capacity
        self.kv_total_pages = kv_total_pages
        self.kv_hot: "dict[int, float]" = {}  # chain_hash -> hits
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0
        # POST /kv/summary overrides (None = derived from kv_hot).
        self.kv_summary_override: Optional[dict] = None
        # Self-tuning (docs/autotuning.md): the fake has no controller
        # loop — POST /autotune/knobs plants these, and they surface in
        # /metrics, /autotune/status and /cluster/status exactly where
        # the real server reports its live controllers.
        self.autotune_mode = "off"
        self.autotune_knobs: "dict[str, float]" = {}
        self.autotune_frozen: "dict[str, bool]" = {}
        self.autotune_decisions: "dict[str, float]" = {}

    def autotune_active(self) -> int:
        if self.autotune_mode != "on":
            return 0
        return sum(1 for name in self.autotune_knobs
                   if not self.autotune_frozen.get(name))

    def observe_prefix(self, body: dict) -> float:
        """Score the request against the hot set (fraction of prompt
        blocks with 'live KV'), then fold its chains in with LRU
        eviction at the capacity cap. Returns the hit fraction."""
        text = routable_text(body)
        if not text:
            return 0.0
        chains = chain_text(text)
        if not chains:
            return 0.0
        hit = expected_hit_blocks(chains, self.kv_hot)
        self.prefix_hit_tokens += hit * TOKENS_PER_BLOCK
        self.prefix_query_tokens += len(chains) * TOKENS_PER_BLOCK
        now = time.monotonic()
        for h in chains:
            self.kv_hot.pop(h, None)  # re-insert = move to MRU end
            self.kv_hot[h] = now
        while len(self.kv_hot) > self.kv_hot_capacity:
            self.kv_hot.pop(next(iter(self.kv_hot)))
        return hit / len(chains)

    def prefix_hit_rate(self) -> float:
        if self.prefix_query_tokens == 0:
            return 0.0
        return self.prefix_hit_tokens / self.prefix_query_tokens

    def kv_summary_payload(self) -> dict:
        if self.kv_summary_override is not None:
            return self.kv_summary_override
        hot = sorted(self.kv_hot.items(), key=lambda kv: -kv[1])
        return {
            "hot_chains": [[h, 1.0] for h, _ in hot],
            "free_pages": max(
                0, self.kv_total_pages - len(self.kv_hot)
                - self.running),
            "total_pages": self.kv_total_pages,
            "kv_dtype": "bf16",
        }

    def slot_sem(self) -> Optional[asyncio.Semaphore]:
        # Lazily created so the semaphore binds to the serving loop.
        if self.max_concurrency > 0 and self._slots is None:
            self._slots = asyncio.Semaphore(self.max_concurrency)
        return self._slots


def _request_priority(state: FakeEngineState,
                      request: web.Request) -> Priority:
    """Priority class of a request: the x-priority header when the fake
    is --priority-aware (malformed values fall back to the default, the
    fake never 400s on it), else the deployment default."""
    if not state.priority_aware:
        return DEFAULT_PRIORITY
    raw = request.headers.get(PRIORITY_HEADER)
    if not raw:
        return DEFAULT_PRIORITY
    try:
        return parse_priority(raw)
    except ValueError:
        return DEFAULT_PRIORITY


async def _apply_api_fault(state: FakeEngineState,
                           request: web.Request) -> Optional[web.Response]:
    """Returns an error response (or hangs) per the active fault mode;
    None when the request should proceed normally."""
    if state.draining:
        # Zero-loss drain: mirror the real engine server's retryable
        # rejection — the router fails the request over to a live
        # replica (never a client-visible 5xx).
        return web.json_response(
            {"error": {"message": "engine is draining; retry on "
                                  "another replica"}},
            status=503, headers={"Retry-After": "1"},
        )
    if state.fault == "overload":
        # Saturated-but-healthy: interactive traffic still flows, every
        # other class gets the same honest 429 + Retry-After the real
        # engine's shed gate produces (never a 5xx, never a drop).
        pri = _request_priority(state, request)
        if pri != Priority.INTERACTIVE:
            state.qos_shed_counts[priority_name(pri)] += 1
            if state.tracer is not None:
                seq_id = f"shed-{uuid.uuid4().hex[:12]}"
                state.tracer.start(
                    seq_id,
                    request_id=request.headers.get("x-request-id"),
                    prompt_tokens=0)
                state.tracer.event(seq_id, "qos_shed",
                                   priority=priority_name(pri),
                                   retry_after_s=1)
                state.tracer.finish(seq_id, reason="shed",
                                    arrival_ts=time.time())
            return web.json_response(
                {"error": {"message": "engine overloaded (injected); "
                                      "retry later",
                           "type": "overloaded_error"}},
                status=429, headers={"Retry-After": "1"},
            )
    if state.fault == "error500":
        return web.json_response(
            {"error": {"message": "injected fault", "type": "server_error"}},
            status=500,
        )
    if state.fault == "hang":
        await asyncio.sleep(3600)
        return web.json_response({"error": "hang elapsed"}, status=500)
    if state.fault == "slow_first_token":
        await asyncio.sleep(state.fault_ttft)
    return None


def _echo_headers(request: web.Request) -> dict:
    """Echo the router's x-request-id so clients (and tests) can
    correlate a response with its /debug/trace/{id} timeline."""
    trace_id = request.headers.get("x-request-id")
    return {"x-request-id": trace_id} if trace_id else {}


def _sse(payload: dict) -> bytes:
    return f"data: {json.dumps(payload)}\n\n".encode()


def _chunk(request_id: str, model: str, text: Optional[str],
           finish: Optional[str] = None, role: Optional[str] = None) -> dict:
    delta = {}
    if role:
        delta["role"] = role
    if text is not None:
        delta["content"] = text
    return {
        "id": request_id,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": 0, "delta": delta, "finish_reason": finish}
        ],
    }


def _ckpt_frame(request_id: str, model: str, n_tokens: int,
                done: int) -> bytes:
    """SSE comment frame carrying the fake's resume descriptor — same
    in-band relay channel the real engine uses; invisible to SSE
    clients, captured (and stripped) by the router."""
    desc = {
        "version": 1,
        "fake": True,
        "response_id": request_id,
        "chat": True,
        "model": model,
        "kv_dtype": "bf16",
        "n_tokens": n_tokens,
        "output_tokens": done,
        "sampling": {"max_tokens": n_tokens},
    }
    return f": checkpoint {json.dumps(desc)}\n\n".encode()


def _sigkill_self() -> None:
    # The rawest mid-stream death: no FIN, no terminating chunk.
    import os
    import signal
    os.kill(os.getpid(), signal.SIGKILL)


async def chat_completions(request: web.Request) -> web.StreamResponse:
    state: FakeEngineState = request.app["state"]
    state.requests_received += 1
    fault_resp = await _apply_api_fault(state, request)
    if fault_resp is not None:
        return fault_resp
    body = await request.json()
    n_tokens = int(
        body.get("max_tokens")
        or body.get("max_completion_tokens")
        or state.max_tokens_default
    )
    stream = bool(body.get("stream", False))
    request_id = f"chatcmpl-{uuid.uuid4().hex[:16]}"
    model = body.get("model", state.model)
    # KV economy TTFT model (docs/kv_economy.md): prefill time scales
    # with the cold fraction of the prompt — a prefix already hot on
    # this replica skips its share of --ttft, so routing policies that
    # land repeat prefixes on the same pod measurably win.
    hit_frac = state.observe_prefix(body)
    ttft_eff = state.ttft * (1.0 - 0.9 * hit_frac)
    # SLO-breach timing faults: breach-but-succeed, so the router's
    # SLO ledger classifies a completed request as bad and captures
    # its exemplar (docs/observability.md). degrade_new_revision is
    # both at once — a bad build that boots healthy but serves badly.
    if state.fault in ("slow_ttft", "degrade_new_revision"):
        ttft_eff += state.slow_ttft_s
    tok_delay = (state.slow_itl_s
                 if state.fault in ("slow_itl", "degrade_new_revision")
                 else 1.0 / state.speed)
    words = [f"tok{i} " for i in range(n_tokens)]
    tracer, arrival = state.tracer, time.time()
    if tracer is not None:
        tracer.start(request_id,
                     request_id=request.headers.get("x-request-id"),
                     prompt_tokens=8)

    sem = state.slot_sem()
    if sem is not None:
        state.waiting += 1
        try:
            await sem.acquire()
        finally:
            state.waiting -= 1
    state.running += 1
    try:
        await asyncio.sleep(ttft_eff)
        first_ts = time.time()
        if tracer is not None:
            tracer.event(request_id, "prefill_chunk",
                         start=0, tokens=8, last=True)
            tracer.event(request_id, "first_token", token=0)
        if not stream:
            await asyncio.sleep(n_tokens * tok_delay)
            state.total_served += 1
            if tracer is not None:
                tracer.finish(request_id, reason="stop",
                              arrival_ts=arrival,
                              first_scheduled_ts=arrival,
                              first_token_ts=first_ts,
                              finish_ts=time.time(),
                              prompt_tokens=8, output_tokens=n_tokens)
            return web.json_response({
                "id": request_id,
                "object": "chat.completion",
                "created": int(time.time()),
                "model": model,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant",
                                "content": "".join(words)},
                    "finish_reason": "stop",
                }],
                "usage": {
                    "prompt_tokens": 0,
                    "completion_tokens": n_tokens,
                    "total_tokens": n_tokens,
                },
            }, headers=_echo_headers(request))
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            **_echo_headers(request),
        })
        await resp.prepare(request)
        await resp.write(_sse(_chunk(request_id, model, None,
                                     role="assistant")))
        for i, word in enumerate(words):
            if state.fault == "abort_mid_stream" and i >= 2:
                # A couple of chunks are downstream; now drop the socket
                # without a terminating chunk or [DONE].
                if tracer is not None:
                    tracer.finish(request_id, reason="abort",
                                  arrival_ts=arrival,
                                  first_token_ts=first_ts)
                if request.transport is not None:
                    request.transport.close()
                return resp
            if (state.fault == "crash"
                    and i >= state.crash_after_tokens):
                _sigkill_self()
            if state.fault == "hang_step":
                # A wedged device step: the stream stalls open while
                # /health reports the watchdog trip.
                await asyncio.sleep(3600)
            await asyncio.sleep(tok_delay)
            await resp.write(_sse(_chunk(request_id, model, word)))
            if (state.checkpoint_interval > 0
                    and (i + 1) % state.checkpoint_interval == 0):
                await resp.write(_ckpt_frame(request_id, model,
                                             n_tokens, i + 1))
                if state.migrate_drain and i + 1 < n_tokens:
                    # Migrate-mode drain cut (docs/fleet.md): the
                    # checkpoint just shipped; dropping the socket
                    # abruptly (no FIN handshake semantics a client
                    # would read as completion) makes the router
                    # resume the stream byte-exactly on a live
                    # replica instead of waiting this one out.
                    if tracer is not None:
                        tracer.event(request_id, "migrate_ship",
                                     tokens_done=i + 1)
                        tracer.finish(request_id, reason="migrate",
                                      arrival_ts=arrival,
                                      first_token_ts=first_ts,
                                      prompt_tokens=8,
                                      output_tokens=i + 1)
                    # In-band marker so the router classifies this cut
                    # as a migration even before its dynamic-config
                    # watcher observes the migrating list.
                    await resp.write(b": migrating\n\n")
                    if request.transport is not None:
                        request.transport.close()
                    return resp
        await resp.write(_sse(_chunk(request_id, model, None,
                                     finish="stop")))
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        state.total_served += 1
        if tracer is not None:
            tracer.finish(request_id, reason="stop",
                          arrival_ts=arrival,
                          first_scheduled_ts=arrival,
                          first_token_ts=first_ts,
                          finish_ts=time.time(),
                          prompt_tokens=8, output_tokens=n_tokens)
        return resp
    finally:
        state.running -= 1
        if sem is not None:
            sem.release()


async def completions(request: web.Request) -> web.Response:
    state: FakeEngineState = request.app["state"]
    state.requests_received += 1
    fault_resp = await _apply_api_fault(state, request)
    if fault_resp is not None:
        return fault_resp
    body = await request.json()
    n_tokens = int(body.get("max_tokens") or state.max_tokens_default)
    hit_frac = state.observe_prefix(body)
    sem = state.slot_sem()
    if sem is not None:
        state.waiting += 1
        try:
            await sem.acquire()
        finally:
            state.waiting -= 1
    state.running += 1
    try:
        # Same SLO-breach timing faults as chat_completions: the whole
        # body is delayed by the faulted ttft + per-token cadence.
        ttft_eff = state.ttft * (1.0 - 0.9 * hit_frac)
        if state.fault in ("slow_ttft", "degrade_new_revision"):
            ttft_eff += state.slow_ttft_s
        tok_delay = (state.slow_itl_s
                     if state.fault in ("slow_itl",
                                        "degrade_new_revision")
                     else 1.0 / state.speed)
        await asyncio.sleep(ttft_eff + n_tokens * tok_delay)
        state.total_served += 1
        return web.json_response({
            "id": f"cmpl-{uuid.uuid4().hex[:16]}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", state.model),
            "choices": [{
                "index": 0,
                "text": " ".join(f"tok{i}" for i in range(n_tokens)),
                "finish_reason": "length",
            }],
            "usage": {"prompt_tokens": 0, "completion_tokens": n_tokens,
                      "total_tokens": n_tokens},
        })
    finally:
        state.running -= 1
        if sem is not None:
            sem.release()


async def disagg_prefill(request: web.Request) -> web.Response:
    """Fake prefill hop: returns a handoff descriptor without doing any
    work. Under the ``kv_missing`` fault the descriptor is poisoned
    (``pages_available: false``) so a well-behaved decode fake 409s it."""
    state: FakeEngineState = request.app["state"]
    state.requests_received += 1
    fault_resp = await _apply_api_fault(state, request)
    if fault_resp is not None:
        return fault_resp
    body = await request.json()
    n_tokens = int(
        body.get("max_tokens")
        or body.get("max_completion_tokens")
        or state.max_tokens_default
    )
    chat = isinstance(body.get("messages"), list)
    seq_id = f"disagg-{uuid.uuid4().hex[:16]}"
    tracer, arrival = state.tracer, time.time()
    if tracer is not None:
        tracer.start(seq_id,
                     request_id=request.headers.get("x-request-id"),
                     prompt_tokens=8)
    await asyncio.sleep(state.ttft)
    state.disagg_prefills += 1
    state.total_served += 1
    available = state.fault != "kv_missing"
    if tracer is not None:
        first_ts = time.time()
        tracer.event(seq_id, "prefill_chunk",
                     start=0, tokens=8, last=True)
        tracer.event(seq_id, "first_token", token=0)
        tracer.event(seq_id, "handoff_ship",
                     num_pages=1 if available else 0,
                     kv_bytes=4096 if available else 0)
        tracer.finish(seq_id, reason="handoff", arrival_ts=arrival,
                      first_scheduled_ts=arrival, first_token_ts=first_ts,
                      finish_ts=first_ts, prompt_tokens=8,
                      output_tokens=1)
    return web.json_response({"descriptor": {
        "version": 1,
        "request_id": seq_id,
        "chat": chat,
        "model": body.get("model", state.model),
        "token_ids": [0] * 8,
        "first_token": 0,
        "finish_reason": None,
        "kv_dtype": "bf16",
        "page_keys": ["fake-page-0"] if available else [],
        "num_pages": 1 if available else 0,
        "kv_bytes": 4096 if available else 0,
        "pages_available": available,
        "sampling": {"max_tokens": n_tokens},
    }}, headers=_echo_headers(request))


async def disagg_handoff(request: web.Request) -> web.StreamResponse:
    """Fake decode hop: streams the same token text the monolithic fake
    endpoints produce, resuming from a prefill fake's descriptor.
    Answers 409 for poisoned descriptors or under its own
    ``kv_missing`` fault — the router must fall back monolithically."""
    state: FakeEngineState = request.app["state"]
    state.requests_received += 1
    fault_resp = await _apply_api_fault(state, request)
    if fault_resp is not None:
        return fault_resp
    body = await request.json()
    desc = body.get("descriptor") or {}
    if state.fault == "kv_missing" or not desc.get("pages_available", True):
        return web.json_response(
            {"error": {"message": "handoff KV not restorable here"}},
            status=409,
        )
    n_tokens = int(
        (desc.get("sampling") or {}).get("max_tokens")
        or state.max_tokens_default
    )
    stream = bool(body.get("stream", False))
    chat = bool(desc.get("chat", True))
    model = desc.get("model", state.model)
    request_id = f"chatcmpl-{uuid.uuid4().hex[:16]}"
    words = [f"tok{i} " for i in range(n_tokens)]
    tracer, arrival = state.tracer, time.time()
    if tracer is not None:
        tracer.start(request_id,
                     request_id=request.headers.get("x-request-id"),
                     prompt_tokens=len(desc.get("token_ids") or []))
        tracer.event(request_id, "awaiting_kv_park")
        tracer.event(request_id, "awaiting_kv_restore",
                     waited_ms=0.0, outcome="ready")
        tracer.event(request_id, "first_token",
                     token=int(desc.get("first_token") or 0))

    def _finish_span(reason: str) -> None:
        if tracer is not None:
            tracer.finish(request_id, reason=reason, arrival_ts=arrival,
                          first_scheduled_ts=arrival,
                          first_token_ts=arrival, finish_ts=time.time(),
                          prompt_tokens=len(desc.get("token_ids") or []),
                          output_tokens=n_tokens)

    state.running += 1
    state.disagg_decodes += 1
    try:
        if not stream:
            await asyncio.sleep(n_tokens / state.speed)
            state.total_served += 1
            _finish_span("stop")
            if chat:
                return web.json_response({
                    "id": request_id,
                    "object": "chat.completion",
                    "created": int(time.time()),
                    "model": model,
                    "choices": [{
                        "index": 0,
                        "message": {"role": "assistant",
                                    "content": "".join(words)},
                        "finish_reason": "stop",
                    }],
                    "usage": {
                        "prompt_tokens": 0,
                        "completion_tokens": n_tokens,
                        "total_tokens": n_tokens,
                    },
                }, headers=_echo_headers(request))
            return web.json_response({
                "id": f"cmpl-{uuid.uuid4().hex[:16]}",
                "object": "text_completion",
                "created": int(time.time()),
                "model": model,
                "choices": [{
                    "index": 0,
                    "text": " ".join(f"tok{i}" for i in range(n_tokens)),
                    "finish_reason": "length",
                }],
                "usage": {"prompt_tokens": 0,
                          "completion_tokens": n_tokens,
                          "total_tokens": n_tokens},
            }, headers=_echo_headers(request))
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            **_echo_headers(request),
        })
        await resp.prepare(request)
        await resp.write(_sse(_chunk(request_id, model, None,
                                     role="assistant")))
        for i, word in enumerate(words):
            if state.fault == "abort_mid_stream" and i >= 2:
                _finish_span("abort")
                if request.transport is not None:
                    request.transport.close()
                return resp
            await asyncio.sleep(1.0 / state.speed)
            await resp.write(_sse(_chunk(request_id, model, word)))
        await resp.write(_sse(_chunk(request_id, model, None,
                                     finish="stop")))
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        state.total_served += 1
        _finish_span("stop")
        return resp
    finally:
        state.running -= 1


async def resume(request: web.Request) -> web.StreamResponse:
    """POST /v1/resume stub (docs/crash_recovery.md): regenerate the
    deterministic token text from the descriptor, skip what the router
    already delivered, and stream the rest — no role chunk, same
    response id — so the concatenated client stream matches an
    uninterrupted run. Keeps the checkpoint cadence (and the crash
    fault) active, so a resumed stream can crash and resume again."""
    state: FakeEngineState = request.app["state"]
    state.requests_received += 1
    fault_resp = await _apply_api_fault(state, request)
    if fault_resp is not None:
        return fault_resp
    body = await request.json()
    desc = body.get("descriptor") or {}
    if not desc.get("fake"):
        return web.json_response(
            {"error": {"message": "descriptor did not come from a "
                                  "fake engine"}}, status=400)
    delivered = int(body.get("delivered_text_chars") or 0)
    n_tokens = int(desc.get("n_tokens") or state.max_tokens_default)
    model = desc.get("model", state.model)
    request_id = (desc.get("response_id")
                  or f"chatcmpl-{uuid.uuid4().hex[:16]}")
    words = [f"tok{i} " for i in range(n_tokens)]
    state.stream_resumes += 1
    state.running += 1
    tracer, arrival = state.tracer, time.time()
    if tracer is not None:
        tracer.start(request_id,
                     request_id=request.headers.get("x-request-id"),
                     prompt_tokens=8)
        tracer.event(request_id, "resume_restore",
                     prior_tokens=int(desc.get("output_tokens") or 0))
    try:
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            **_echo_headers(request),
        })
        await resp.prepare(request)
        pos = 0
        emitted = 0
        for i, word in enumerate(words):
            end = pos + len(word)
            if end <= delivered:
                pos = end
                continue
            text = word if pos >= delivered else word[delivered - pos:]
            pos = end
            if (state.fault == "crash"
                    and emitted >= state.crash_after_tokens):
                _sigkill_self()
            if state.fault == "hang_step":
                await asyncio.sleep(3600)
            await asyncio.sleep(1.0 / state.speed)
            await resp.write(_sse(_chunk(request_id, model, text)))
            emitted += 1
            if (state.checkpoint_interval > 0
                    and (i + 1) % state.checkpoint_interval == 0):
                await resp.write(_ckpt_frame(request_id, model,
                                             n_tokens, i + 1))
                if state.migrate_drain and i + 1 < n_tokens:
                    # Same migrate cut as chat_completions: a resumed
                    # stream can migrate onward mid-roll.
                    if tracer is not None:
                        tracer.event(request_id, "migrate_ship",
                                     tokens_done=i + 1)
                        tracer.finish(request_id, reason="migrate",
                                      arrival_ts=arrival,
                                      prompt_tokens=8,
                                      output_tokens=i + 1)
                    # Same in-band migration marker as the original
                    # stream leg.
                    await resp.write(b": migrating\n\n")
                    if request.transport is not None:
                        request.transport.close()
                    return resp
        await resp.write(_sse(_chunk(request_id, model, None,
                                     finish="stop")))
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        state.total_served += 1
        if tracer is not None:
            tracer.finish(request_id, reason="stop",
                          arrival_ts=arrival,
                          first_scheduled_ts=arrival,
                          first_token_ts=arrival,
                          finish_ts=time.time(),
                          prompt_tokens=8, output_tokens=n_tokens)
        return resp
    finally:
        state.running -= 1


async def models(request: web.Request) -> web.Response:
    state: FakeEngineState = request.app["state"]
    return web.json_response({
        "object": "list",
        "data": [{
            "id": state.model, "object": "model",
            "created": int(time.time()), "owned_by": "fake-engine",
        }],
    })


async def health(request: web.Request) -> web.Response:
    state: FakeEngineState = request.app["state"]
    if state.fault in ("error500", "unhealthy"):
        return web.json_response({"status": "injected fault"}, status=500)
    if state.fault == "hang_step":
        # Same contract as the real server's --step-watchdog-s trip:
        # the prober rotates the wedged replica out on this 503.
        return web.json_response({
            "status": "watchdog",
            "stuck_step_s": 3600.0,
            "role": state.role,
            "draining": state.draining,
            "active_requests": state.running,
            "build_id": state.build_id,
        }, status=503)
    if state.fault == "hang":
        await asyncio.sleep(3600)
    return web.json_response({
        "status": "ok",
        "role": state.role,
        "draining": state.draining,
        "active_requests": state.running,
        "build_id": state.build_id,
    })


async def drain(request: web.Request) -> web.Response:
    """POST /drain: same contract as the real engine server — reject
    new admissions 503+Retry-After, finish in-flight streams, and with
    ``{"exit": true}`` exit the process once idle."""
    state: FakeEngineState = request.app["state"]
    body: dict = {}
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            body = {}
    state.draining = True
    if body.get("migrate"):
        state.migrate_drain = True
    if body.get("exit"):
        async def exit_when_idle():
            import os
            import signal
            while state.running > 0:
                await asyncio.sleep(0.02)
            os.kill(os.getpid(), signal.SIGTERM)
        asyncio.ensure_future(exit_when_idle())
    return web.json_response({
        "status": "draining",
        "active_requests": state.running,
        "running": state.running,
        "waiting": state.waiting,
    })


async def set_gauges(request: web.Request) -> web.Response:
    """POST /gauges: deterministic load-gauge injection for autoscaler
    tests — drive the SLO signals the fleet manager scrapes without
    generating real load. {"waiting": 12, "cache_usage": 0.95};
    null/absent clears an override."""
    state: FakeEngineState = request.app["state"]
    body = await request.json()
    if "waiting" in body:
        state.waiting = int(body["waiting"] or 0)
    if "cache_usage" in body:
        state.cache_usage = (None if body["cache_usage"] is None
                             else float(body["cache_usage"]))
    return web.json_response({
        "waiting": state.waiting,
        "cache_usage": state.cache_usage,
    })


async def set_fault(request: web.Request) -> web.Response:
    """Runtime fault control: POST /fault {"mode": "error500" | null}."""
    state: FakeEngineState = request.app["state"]
    body = await request.json()
    mode = body.get("mode")
    if mode is not None and mode not in FAULT_MODES:
        return web.json_response(
            {"error": f"unknown fault mode {mode!r}; "
                      f"one of {list(FAULT_MODES)}"},
            status=400,
        )
    state.fault = mode
    if "fault_ttft" in body:
        state.fault_ttft = float(body["fault_ttft"])
    if "slow_ttft_s" in body:
        state.slow_ttft_s = float(body["slow_ttft_s"])
    if "slow_itl_s" in body:
        state.slow_itl_s = float(body["slow_itl_s"])
    return web.json_response({"fault": state.fault})


async def debug_trace(request: web.Request) -> web.Response:
    """GET /debug/trace/{request_id}: same flight-recorder lookup the
    real engine server exposes (docs/observability.md)."""
    state: FakeEngineState = request.app["state"]
    if state.tracer is None:
        return web.json_response(
            {"error": {"message": "tracing disabled"}}, status=404)
    found = state.tracer.lookup(request.match_info["request_id"])
    if found is None:
        return web.json_response(
            {"error": {"message": "no trace for that id"}}, status=404)
    return web.json_response(found)


async def kv_summary(request: web.Request) -> web.Response:
    """GET /kv/summary: same schema as the real engine server
    (docs/kv_economy.md), derived from the fake's capped hot set —
    or from a POST /kv/summary override."""
    state: FakeEngineState = request.app["state"]
    return web.json_response(state.kv_summary_payload())


async def set_kv_summary(request: web.Request) -> web.Response:
    """POST /kv/summary: pin the summary payload for router tests
    ({"hot_chains": [[hash, hits], ...], "free_pages": N,
    "total_pages": N, "kv_dtype": "bf16"}); null body/empty object
    clears the override back to derived state."""
    state: FakeEngineState = request.app["state"]
    body = await request.json()
    state.kv_summary_override = body or None
    return web.json_response(state.kv_summary_payload())


async def set_autotune_knobs(request: web.Request) -> web.Response:
    """POST /autotune/knobs: plant the self-tuning state this fake
    reports — {"mode": "on", "knobs": {"spec_k": 4}, "frozen":
    {"spec_k": true}, "decisions": {"spec_k": 12}} — each key optional,
    merged into current state; {"clear": true} resets everything.
    Echoes the resulting state (same shape as GET /autotune/status)."""
    state: FakeEngineState = request.app["state"]
    body = await request.json()
    if body.get("clear"):
        state.autotune_mode = "off"
        state.autotune_knobs = {}
        state.autotune_frozen = {}
        state.autotune_decisions = {}
    if "mode" in body:
        state.autotune_mode = str(body["mode"])
    for name, val in (body.get("knobs") or {}).items():
        state.autotune_knobs[str(name)] = float(val)
    for name, val in (body.get("frozen") or {}).items():
        state.autotune_frozen[str(name)] = bool(val)
    for name, val in (body.get("decisions") or {}).items():
        state.autotune_decisions[str(name)] = float(val)
    return await autotune_status(request)


async def autotune_status(request: web.Request) -> web.Response:
    """GET /autotune/status: same shape as the real server's handler,
    fed from the planted knob/frozen/decision state."""
    state: FakeEngineState = request.app["state"]
    return web.json_response({
        "mode": state.autotune_mode,
        "interval_s": 2.0,
        "active_controllers": state.autotune_active(),
        "controllers": [
            {"name": name,
             "knob": state.autotune_knobs[name],
             "lo": 0.0, "hi": 0.0,
             "frozen": bool(state.autotune_frozen.get(name)),
             "decisions": int(state.autotune_decisions.get(name, 0)),
             "applied": int(state.autotune_decisions.get(name, 0))}
            for name in sorted(state.autotune_knobs)
        ],
    })


async def autotune_reset(request: web.Request) -> web.Response:
    """POST /autotune/reset: operator unfreeze, same contract as the
    real server — optional {"controller": name} limits the reset."""
    state: FakeEngineState = request.app["state"]
    target = None
    if request.can_read_body:
        try:
            target = (await request.json()).get("controller")
        except Exception:
            target = None
    if target is None:
        cleared = [k for k, v in sorted(state.autotune_frozen.items())
                   if v]
        state.autotune_frozen = {}
    else:
        cleared = ([target]
                   if state.autotune_frozen.pop(target, False) else [])
    return web.json_response({"reset": cleared})


async def metrics(request: web.Request) -> web.Response:
    state: FakeEngineState = request.app["state"]
    cache_usage = (state.cache_usage if state.cache_usage is not None
                   else min(1.0, state.running / 16))
    kvs = state.kv_summary_payload()
    text = "\n".join([
        "# TYPE vllm:num_requests_running gauge",
        f"vllm:num_requests_running {float(state.running)}",
        "# TYPE vllm:num_requests_waiting gauge",
        f"vllm:num_requests_waiting {float(state.waiting)}",
        "# TYPE vllm:num_requests_total counter",
        f"vllm:num_requests_total {float(state.total_served)}",
        "# TYPE vllm:gpu_prefix_cache_hit_rate gauge",
        "vllm:gpu_prefix_cache_hit_rate "
        f"{float(state.prefix_hit_rate())}",
        "# TYPE vllm:gpu_cache_usage_perc gauge",
        f"vllm:gpu_cache_usage_perc {float(cache_usage)}",
        # Cluster KV economy (docs/kv_economy.md): mirrors the real
        # server's summary gauges; the cluster counters stay 0 (the
        # fake has no offload tier) to keep the scrape surface stable.
        "# TYPE vllm:kv_summary_hot_chains gauge",
        f"vllm:kv_summary_hot_chains {float(len(kvs['hot_chains']))}",
        "# TYPE vllm:kv_free_page_headroom gauge",
        f"vllm:kv_free_page_headroom {float(kvs['free_pages'])}",
        "# TYPE vllm:kv_total_pages gauge",
        f"vllm:kv_total_pages {float(kvs['total_pages'])}",
        "# TYPE vllm:kv_cluster_hits_total counter",
        "vllm:kv_cluster_hits_total 0.0",
        "# TYPE vllm:kv_cluster_misses_total counter",
        "vllm:kv_cluster_misses_total 0.0",
        "# TYPE vllm:kv_cluster_admissions_total counter",
        "vllm:kv_cluster_admissions_total 0.0",
        "# TYPE vllm:kv_cluster_rejections_total counter",
        "vllm:kv_cluster_rejections_total 0.0",
        "# TYPE vllm:engine_draining gauge",
        f"vllm:engine_draining {float(state.draining)}",
        # Self-tuning (docs/autotuning.md): planted via
        # POST /autotune/knobs — same families as the real server.
        "# TYPE vllm:autotune_active_controllers gauge",
        "vllm:autotune_active_controllers "
        f"{float(state.autotune_active())}",
        "# TYPE vllm:autotune_frozen gauge",
        *(
            "vllm:autotune_frozen{controller=\"" f"{name}\"}} "
            f"{float(bool(frozen))}"
            for name, frozen in sorted(state.autotune_frozen.items())
        ),
        "# TYPE vllm:autotune_knob_value gauge",
        *(
            "vllm:autotune_knob_value{controller=\"" f"{name}\"}} "
            f"{float(value)}"
            for name, value in sorted(state.autotune_knobs.items())
        ),
        "# TYPE vllm:autotune_decisions_total counter",
        *(
            "vllm:autotune_decisions_total{controller=\"" f"{name}\"}} "
            f"{float(count)}"
            for name, count in sorted(state.autotune_decisions.items())
        ),
        "# TYPE vllm:qos_shed_total counter",
        *(
            "vllm:qos_shed_total{class=\"" f"{cls}\"}} {float(count)}"
            for cls, count in sorted(state.qos_shed_counts.items())
        ),
        # Device performance observatory (docs/observability.md):
        # static deterministic values so router-side scrape/re-export
        # tests run without JAX.
        "# TYPE vllm:engine_compile_events_total counter",
        'vllm:engine_compile_events_total{kind="step"} 3.0',
        'vllm:engine_compile_events_total{kind="unified"} 1.0',
        "# TYPE vllm:engine_compile_seconds_total counter",
        'vllm:engine_compile_seconds_total{kind="step"} 1.25',
        'vllm:engine_compile_seconds_total{kind="unified"} 0.5',
        "# TYPE vllm:engine_executable_cache_size gauge",
        'vllm:engine_executable_cache_size{kind="step"} 3.0',
        'vllm:engine_executable_cache_size{kind="unified"} 1.0',
        "# TYPE vllm:engine_hbm_bytes gauge",
        'vllm:engine_hbm_bytes{category="weights"} 1048576.0',
        'vllm:engine_hbm_bytes{category="kv_pages"} 524288.0',
        'vllm:engine_hbm_bytes{category="kv_scales"} 0.0',
        'vllm:engine_hbm_bytes{category="step_buffers"} 65536.0',
        "# TYPE vllm:engine_step_device_seconds_total counter",
        'vllm:engine_step_device_seconds_total{kind="decode"} 2.5',
        # Step-time medians (drift sentinel, obs/drift.py): static
        # values matching observability/perf_baseline.json, so an
        # unmodified fake reads as "no drift".
        "# TYPE vllm:engine_step_time_median_seconds gauge",
        'vllm:engine_step_time_median_seconds{kind="decode"} 0.025',
        'vllm:engine_step_time_median_seconds{kind="prefill"} 0.5',
        "# TYPE vllm:engine_mfu gauge",
        "vllm:engine_mfu 0.37",
        "# TYPE vllm:engine_attention_impl gauge",
        'vllm:engine_attention_impl{phase="decode",impl="xla"} 1.0',
        'vllm:engine_attention_impl{phase="prefill",impl="xla"} 1.0',
        "",
    ])
    return web.Response(text=text, content_type="text/plain")


async def cluster_status(request: web.Request) -> web.Response:
    """GET /cluster/status: a /cluster/status-shaped snapshot with
    this fake as the only server — built through the same
    obs.cluster_status rollup the router uses, so stacktop render
    tests exercise the real payload shape without a router."""
    from types import SimpleNamespace

    from production_stack_tpu.obs.cluster_status import build_snapshot

    state: FakeEngineState = request.app["state"]
    kvs = state.kv_summary_payload()
    cache_usage = (state.cache_usage if state.cache_usage is not None
                   else min(1.0, state.running / 16))
    stats = SimpleNamespace(
        num_running_requests=state.running,
        num_queuing_requests=state.waiting,
        kv_usage_perc=float(cache_usage),
        kv_cache_hit_rate=state.prefix_hit_rate(),
        engine_draining=float(state.draining),
        kv_summary_hot_chains=float(len(kvs["hot_chains"])),
        kv_free_page_headroom=float(kvs["free_pages"]),
        kv_total_pages=float(kvs["total_pages"]),
        kv_summary_time=time.time(),
        qos_shed_by_class=dict(state.qos_shed_counts),
        compile_events_by_kind={"step": 3.0, "unified": 1.0},
        engine_mfu=0.37,
        hbm_bytes_by_category={"weights": 1048576.0,
                               "kv_pages": 524288.0,
                               "kv_scales": 0.0,
                               "step_buffers": 65536.0},
        step_time_median_by_kind={"decode": 0.025, "prefill": 0.5},
        autotune_active_controllers=float(state.autotune_active()),
        autotune_frozen_by_controller={
            k: float(bool(v))
            for k, v in state.autotune_frozen.items()},
        autotune_knob_by_controller=dict(state.autotune_knobs),
    )
    url = f"http://{request.host}"
    ep = SimpleNamespace(url=url, model_name=state.model,
                         role=state.role)
    return web.json_response(
        build_snapshot({url: stats}, endpoints=[ep],
                       healthy={url: state.fault not in
                                ("error500", "unhealthy")}))


async def debug_compiles(request: web.Request) -> web.Response:
    """GET /debug/compiles[?limit=N]: deterministic compile-ledger
    payload matching the real server's shape (engine/server.py
    debug_compiles)."""
    try:
        limit = int(request.query.get("limit", "32"))
    except ValueError:
        return web.json_response(
            {"error": {"message": "limit must be an integer"}},
            status=400)
    recent = [
        {"kind": "step", "key": [4, 16], "seconds": 0.4,
         "cache_size": 1, "ts": 0.0},
        {"kind": "step", "key": [4, 32], "seconds": 0.45,
         "cache_size": 2, "ts": 1.0},
        {"kind": "step", "key": [8, 32], "seconds": 0.4,
         "cache_size": 3, "ts": 2.0},
        {"kind": "unified", "key": [12, 32], "seconds": 0.5,
         "cache_size": 1, "ts": 3.0},
    ]
    return web.json_response({
        "events": {"step": 3, "unified": 1},
        "seconds": {"step": 1.25, "unified": 0.5},
        "executable_cache_sizes": {"step": 3, "unified": 1},
        "recent": recent[-limit:] if limit >= 0 else recent,
        "timings": {},
    })


async def version(request: web.Request) -> web.Response:
    """GET /version: same shape as the real server (the package
    version — the fake IS this package), plus the deployed build id
    for rollout membership checks."""
    state: FakeEngineState = request.app["state"]
    return web.json_response({"version": __version__,
                              "build_id": state.build_id})


async def debug_steps(request: web.Request) -> web.Response:
    """GET /debug/steps[?limit=N]: the fake's flight recorder (same
    EngineTracer class as the real engine), same 404/400 contract as
    engine/server.py debug_steps."""
    state: FakeEngineState = request.app["state"]
    if state.tracer is None:
        return web.json_response(
            {"error": {"message": "tracing disabled"}}, status=404)
    try:
        limit = int(request.query.get("limit", "100"))
    except ValueError:
        return web.json_response(
            {"error": {"message": "limit must be an integer"}},
            status=400)
    return web.json_response(
        {"steps": state.tracer.recent_steps(limit=limit)})


async def debug_memory(request: web.Request) -> web.Response:
    """GET /debug/memory: deterministic HBM-ledger payload matching
    the real server's shape (engine/server.py debug_memory)."""
    analytic = {"weights": 1048576, "kv_pages": 524288,
                "kv_scales": 0, "step_buffers": 65536}
    return web.json_response({
        "analytic": analytic,
        "total_analytic_bytes": sum(analytic.values()),
        "kv_cache_dtype": "bf16",
        "num_pages": 512,
        "page_size": 16,
        "param_count": 524288,
    })


def build_fake_engine(model: str = "fake/model", speed: float = 100.0,
                      ttft: float = 0.02, fault: Optional[str] = None,
                      fault_ttft: float = 5.0, role: str = "both",
                      span_log: Optional[str] = None,
                      trace_ring: int = 256,
                      priority_aware: bool = False,
                      max_concurrency: int = 0,
                      checkpoint_interval: int = 0,
                      crash_after_tokens: int = 4,
                      kv_hot_capacity: int = 128,
                      kv_total_pages: int = 512,
                      build_id: str = "") -> web.Application:
    state = FakeEngineState(model=model, speed=speed, ttft=ttft,
                            fault=fault, fault_ttft=fault_ttft,
                            role=role, priority_aware=priority_aware,
                            max_concurrency=max_concurrency,
                            checkpoint_interval=checkpoint_interval,
                            crash_after_tokens=crash_after_tokens,
                            kv_hot_capacity=kv_hot_capacity,
                            kv_total_pages=kv_total_pages,
                            build_id=build_id)
    if span_log or trace_ring > 0:
        # Same default as the real server: flight recorder on, span
        # log only when a path is given.
        state.tracer = EngineTracer(span_log_path=span_log,
                                    ring_size=max(1, trace_ring),
                                    role=role)
    app = web.Application()
    app["state"] = state
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/disagg/prefill", disagg_prefill)
    app.router.add_post("/v1/disagg/handoff", disagg_handoff)
    app.router.add_post("/v1/resume", resume)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/health", health)
    app.router.add_get("/version", version)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/kv/summary", kv_summary)
    app.router.add_post("/kv/summary", set_kv_summary)
    app.router.add_get("/autotune/status", autotune_status)
    app.router.add_post("/autotune/reset", autotune_reset)
    app.router.add_post("/autotune/knobs", set_autotune_knobs)
    app.router.add_get("/cluster/status", cluster_status)
    app.router.add_get("/debug/trace/{request_id}", debug_trace)
    app.router.add_get("/debug/steps", debug_steps)
    app.router.add_get("/debug/compiles", debug_compiles)
    app.router.add_get("/debug/memory", debug_memory)
    app.router.add_post("/fault", set_fault)
    app.router.add_post("/drain", drain)
    app.router.add_post("/gauges", set_gauges)
    return app


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Fake OpenAI engine")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9001)
    parser.add_argument("--model", default="fake/model")
    parser.add_argument("--speed", type=float, default=100.0,
                        help="tokens per second")
    parser.add_argument("--ttft", type=float, default=0.02,
                        help="seconds before first token")
    parser.add_argument("--fault", default=None, choices=FAULT_MODES,
                        help="start with this fault mode active")
    parser.add_argument("--fault-ttft", type=float, default=5.0,
                        help="slow_first_token injected delay (seconds)")
    parser.add_argument("--slow-ttft-s", type=float, default=0.75,
                        help="slow_ttft fault: extra first-token "
                             "delay (seconds)")
    parser.add_argument("--slow-itl-s", type=float, default=0.2,
                        help="slow_itl fault: per-token cadence "
                             "(seconds) replacing 1/speed")
    parser.add_argument("--role", default="both", choices=ENGINE_ROLES,
                        help="engine role reported in /health "
                             "(disaggregated-serving discovery)")
    parser.add_argument("--priority-aware", action="store_true",
                        help="honor the x-priority request header "
                             "(QoS tests; docs/qos.md) — the overload "
                             "fault then sheds only non-interactive "
                             "classes")
    parser.add_argument("--max-concurrency", type=int, default=0,
                        help="decode-slot capacity model: requests "
                             "beyond this many queue (TTFT inflates) "
                             "instead of running concurrently; 0 = "
                             "unlimited")
    parser.add_argument("--span-log", default=None,
                        help="Emit engine-span JSON lines to this "
                             "path ('-' = the process log), same "
                             "format as the real engine server's "
                             "--request-span-log")
    parser.add_argument("--checkpoint-interval-tokens", type=int,
                        default=0,
                        help="Attach a resume descriptor to streams "
                             "every N tokens, like the real engine's "
                             "flag (docs/crash_recovery.md)")
    parser.add_argument("--crash-after-tokens", type=int, default=4,
                        help="With the crash fault: SIGKILL self after "
                             "this many streamed tokens")
    parser.add_argument("--kv-hot-capacity", type=int, default=128,
                        help="Capped LRU hot-prefix set size behind "
                             "GET /kv/summary (docs/kv_economy.md) — "
                             "pinning more distinct prefixes than this "
                             "on one fake thrashes, like a real page "
                             "budget")
    parser.add_argument("--kv-total-pages", type=int, default=512,
                        help="total_pages reported by GET /kv/summary")
    parser.add_argument("--build-id", default="",
                        help="Build revision reported in /version and "
                             "/health, like the real engine's flag — "
                             "rollout tests assert revision membership "
                             "with it (docs/fleet.md)")
    args = parser.parse_args(argv)
    app = build_fake_engine(args.model, args.speed, args.ttft,
                            fault=args.fault, fault_ttft=args.fault_ttft,
                            role=args.role, span_log=args.span_log,
                            priority_aware=args.priority_aware,
                            max_concurrency=args.max_concurrency,
                            checkpoint_interval=(
                                args.checkpoint_interval_tokens),
                            crash_after_tokens=args.crash_after_tokens,
                            kv_hot_capacity=args.kv_hot_capacity,
                            kv_total_pages=args.kv_total_pages,
                            build_id=args.build_id)
    app["state"].slow_ttft_s = args.slow_ttft_s
    app["state"].slow_itl_s = args.slow_itl_s
    web.run_app(app, host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
