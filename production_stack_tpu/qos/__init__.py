"""Shared QoS types: priority classes, tenant identity, token buckets.

One vocabulary for both hops of the stack (docs/qos.md): the router
stamps a priority class on every request (``x-priority`` header,
defaulted per deployment), the engine scheduler admits waiting
sequences in priority-then-arrival order and picks the lowest-
priority, newest victim under page pressure, and the router's
fairness layer (router/qos.py) meters tenants with the token buckets
defined here. Stdlib-only so the engine hot path imports nothing
heavy.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, Optional

# Carried end-to-end: client -> router -> engine. The router forwards
# the client's header verbatim (or stamps its configured default), the
# engine server maps it to Sequence.priority.
PRIORITY_HEADER = "x-priority"

# Tenant identity for fairness accounting: the API key header when the
# client sends one, else the client IP (router/qos.py identify_tenant).
TENANT_HEADER = "x-api-key"

# Degradation-ladder hint (docs/qos.md): the router sets this on
# requests it admits in degraded mode; the engine skips speculative
# drafting for them so saturated pods spend no verify-step slack on
# throttled tenants.
SPEC_OFF_HEADER = "x-qos-spec-off"


class Priority(enum.IntEnum):
    """Request priority class. Lower value = more important, so tuples
    like ``(seq.priority, seq.arrival_time)`` sort admission order and
    ``max()`` over the same tuple picks the preemption victim."""

    INTERACTIVE = 0
    BATCH = 1
    BACKGROUND = 2


PRIORITY_NAMES = tuple(p.name.lower() for p in Priority)

# Unlabeled traffic lands in the middle class: sheddable under
# overload, but ahead of explicit background work. Interactive must be
# requested explicitly — a default-everyone-is-interactive policy
# would make the classes meaningless the first time load exceeds
# capacity.
DEFAULT_PRIORITY = Priority.BATCH


def parse_priority(name: str) -> Priority:
    """'interactive' | 'batch' | 'background' -> Priority.

    Raises ValueError on anything else (the server maps it to HTTP
    400; engine/config.py re-raises it at config time for
    --default-priority typos).
    """
    try:
        return Priority[str(name).strip().upper()]
    except KeyError:
        raise ValueError(
            f"invalid priority {name!r} (expected one of: "
            f"{', '.join(PRIORITY_NAMES)})")


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second, ``burst`` deep.

    Callers drive the clock explicitly (``now``) so policy code and
    tests are deterministic; router/qos.py passes event-loop time.
    """

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._last is not None and now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now

    def take(self, n: float, now: float) -> bool:
        """Consume ``n`` tokens if available; False = over budget."""
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def charge(self, n: float, now: float,
               max_debt: float = 0.0) -> None:
        """Consume ``n`` tokens unconditionally, letting the level go
        negative (debt, floored at ``-max_debt``). The degradation
        ladder charges served-but-degraded requests this way, so
        sustained overage accumulates measurable debt that ``deficit``
        reads and refill pays down at ``rate`` — while the floor bounds
        how long a tenant that stops hammering stays in the penalty
        box."""
        self._refill(now)
        self.tokens = max(self.tokens - n, -float(max_debt))

    def deficit(self, now: float) -> float:
        """Current token debt: how far below empty the bucket sits
        (0.0 while any credit remains). Grows one unit per charged
        over-budget request, drains at ``rate``."""
        self._refill(now)
        return max(0.0, -self.tokens)

    def retry_after_s(self, now: float, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available."""
        self._refill(now)
        short = n - self.tokens
        if short <= 0:
            return 0.0
        return short / self.rate


def jain_index(values: Iterable[float]) -> float:
    """Jain fairness index over per-tenant allocations: 1.0 =
    perfectly fair, 1/n = one tenant takes everything."""
    vals = [float(v) for v in values]
    if not vals:
        return 1.0
    total = sum(vals)
    if total == 0:
        return 1.0
    sq = sum(v * v for v in vals)
    return (total * total) / (len(vals) * sq)


def shed_retry_after_s(queue_depth: int, service_rate: float) -> int:
    """Honest Retry-After for a shed request: the time the current
    queue needs to drain at the observed/configured service rate,
    floored at 1s (docs/qos.md §retry-after-math)."""
    if service_rate <= 0:
        return 1
    return max(1, int(round(queue_depth / service_rate)))


def priority_name(priority: "Priority | int") -> str:
    return Priority(int(priority)).name.lower()


def classify_request(headers, remote: Optional[str] = None
                     ) -> "tuple[str, str]":
    """(priority-class name, tenant) for one request, never raising:
    a malformed ``x-priority`` falls back to the deployment default
    and the tenant falls back to the client address. This is the
    labeling helper the router uses even when its QoS fairness layer
    is off, so spans, request stats, and the SLO ledger always carry
    class/tenant attribution (docs/observability.md)."""
    raw = headers.get(PRIORITY_HEADER)
    try:
        pri = parse_priority(raw) if raw else DEFAULT_PRIORITY
    except ValueError:
        pri = DEFAULT_PRIORITY
    tenant = headers.get(TENANT_HEADER) or remote or "unknown"
    return priority_name(pri), str(tenant)


def shed_counter_dict() -> Dict[str, int]:
    """Zeroed per-class shed counter (stable label set for metrics)."""
    return {name: 0 for name in PRIORITY_NAMES}
