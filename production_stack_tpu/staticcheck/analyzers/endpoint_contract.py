"""Rule ``endpoint-contract``: the fake engine mirrors the real HTTP
surface — or says, explicitly, why not.

Router tests run against testing/fake_engine.py; every route the real
servers (engine/server.py, engine/cache_server.py) grow that the fake
does not is a slice of the system the router test suite silently
stopped exercising (this drifted every PR: /version, /debug/steps,
the profiler endpoints, and the pooling endpoints were all missing
when this rule landed). The contract, both directions:

- every ``app.router.add_<method>("<path>", ...)`` in the real server
  files is either registered in fake_engine.py too, or carried in
  fake_engine's ``FAKE_ENGINE_EXEMPT`` dict
  (``{"METHOD /path": "why the fake cannot/need not mirror it"}``);
- an exemption for a route the fake DOES implement is redundant and
  flagged (stale exemptions cannot accumulate);
- an exemption for a route no real server registers is stale and
  flagged;
- routes only the fake registers (fault injection hooks etc.) must be
  declared in fake_engine's ``FAKE_ONLY_ROUTES`` dict, same shape —
  an undeclared fake-only route is flagged (it usually means a real
  route was renamed and the fake kept the old one).

Routes are recognized as ``<...>.add_get/add_post/add_put/add_head/
add_delete("<literal>", handler)``; dynamic paths are invisible to
this rule by design (none exist today).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
    tail_name,
)

REAL_FILES = (
    "production_stack_tpu/engine/server.py",
    "production_stack_tpu/engine/cache_server.py",
)
FAKE_FILE = "production_stack_tpu/testing/fake_engine.py"

_ADD_METHODS = {
    "add_get": "GET",
    "add_post": "POST",
    "add_put": "PUT",
    "add_delete": "DELETE",
    "add_head": "HEAD",
}


def _routes(tree: ast.AST) -> Dict[str, int]:
    """{"METHOD /path": first line} for add_* calls with a literal
    path."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and tail_name(node.func) in _ADD_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            key = (f"{_ADD_METHODS[tail_name(node.func)]} "
                   f"{node.args[0].value}")
            out.setdefault(key, node.lineno)
    return out


def _marker_dict(tree: ast.AST, name: str) -> Dict[str, Tuple[int, str]]:
    """{route: (line, rationale)} from a module-level dict literal."""
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for target in stmt.targets:
            if (isinstance(target, ast.Name) and target.id == name
                    and isinstance(stmt.value, ast.Dict)):
                out = {}
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        rationale = (v.value if isinstance(v, ast.Constant)
                                     and isinstance(v.value, str) else "")
                        out[k.value] = (k.lineno, rationale)
                return out
    return {}


@rule("endpoint-contract",
      "every real server route is mirrored in testing/fake_engine.py "
      "or explicitly exempted (FAKE_ENGINE_EXEMPT), both directions")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    fake = project.source(FAKE_FILE)
    if fake is None or fake.tree is None:
        return [Finding(
            rule="endpoint-contract", path=FAKE_FILE, line=0,
            message="endpoint-contract surface file missing — if the "
                    "fake engine moved, update "
                    "staticcheck/analyzers/endpoint_contract.py")]
    fake_routes = _routes(fake.tree)
    exempt = _marker_dict(fake.tree, "FAKE_ENGINE_EXEMPT")
    fake_only = _marker_dict(fake.tree, "FAKE_ONLY_ROUTES")

    real_routes: Dict[str, Tuple[str, int]] = {}
    for relpath in REAL_FILES:
        sf = project.source(relpath)
        if sf is None or sf.tree is None:
            findings.append(Finding(
                rule="endpoint-contract", path=relpath, line=0,
                message="endpoint-contract surface file missing — if "
                        "the server moved, update "
                        "staticcheck/analyzers/endpoint_contract.py"))
            continue
        for route, line in _routes(sf.tree).items():
            real_routes.setdefault(route, (relpath, line))

    for route, (relpath, line) in sorted(real_routes.items()):
        if route in fake_routes or route in exempt:
            continue
        sf = project.source(relpath)
        findings.append(sf.finding(
            "endpoint-contract", line,
            f"route '{route}' has no mirror in testing/fake_engine.py "
            "— router tests silently stopped covering it; add a fake "
            "handler or a FAKE_ENGINE_EXEMPT entry with a rationale"))

    for route, (line, rationale) in sorted(exempt.items()):
        if route in fake_routes:
            findings.append(fake.finding(
                "endpoint-contract", line,
                f"FAKE_ENGINE_EXEMPT lists '{route}' but the fake "
                "implements it — drop the redundant exemption"))
        elif route not in real_routes:
            findings.append(fake.finding(
                "endpoint-contract", line,
                f"FAKE_ENGINE_EXEMPT lists '{route}' which no real "
                "server registers — stale exemption"))
        elif not rationale.strip():
            findings.append(fake.finding(
                "endpoint-contract", line,
                f"FAKE_ENGINE_EXEMPT entry for '{route}' has an empty "
                "rationale — say why the fake cannot mirror it"))

    for route, line in sorted(fake_routes.items()):
        if route in real_routes or route in fake_only:
            continue
        findings.append(fake.finding(
            "endpoint-contract", line,
            f"fake-only route '{route}' is not declared in "
            "FAKE_ONLY_ROUTES — if the real route was renamed, rename "
            "the fake's too; if it is a test hook, declare it"))

    for route, (line, _rationale) in sorted(fake_only.items()):
        if route in real_routes:
            findings.append(fake.finding(
                "endpoint-contract", line,
                f"FAKE_ONLY_ROUTES lists '{route}' but a real server "
                "registers it — it is a mirrored route, drop the "
                "declaration"))
    return findings
