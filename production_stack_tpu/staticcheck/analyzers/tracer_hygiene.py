"""Rule ``tracer-hygiene``: recompile/host-sync hazards in traced
code (ops/ and engine/model_runner.py).

"Zero per-step recompiles" and "no host sync inside a step" are the
invariants the whole serving stack's latency story rests on
(docs/async_pipeline.md, CHANGES.md PRs 2-4). They regress invisibly:
a ``float(x)`` on a traced value either throws a ConcretizationError
in prod or — worse — silently forces a retrace per shape/value when
the argument happens to be weakly typed. Flags:

1. inside *traced functions* (see below):
   - ``.item()`` anywhere — a device->host sync (or trace error),
   - ``bool()/int()/float()/len()``-driven branching: one of these
     calls inside an ``if``/``while`` test or ternary condition,
   - ``if``/``while`` tests on ``.shape``/``.ndim`` — trace-time
     specialization; legitimate bucketing must carry a waiver so
     every retrace trigger is deliberate and reviewed,
   - any ``while`` loop whose test can read a *traced* value —
     Python loops on traced state either fail to trace or unroll
     unboundedly (use ``lax.while_loop``/``fori_loop``). Tracedness
     is decided by a taint dataflow over the CFG (staticcheck/cfg.py):
     function parameters and everything derived from them are
     tainted; a loop whose test reads only host-bounded locals (e.g.
     ``size = 8`` then ``while size < 4096: size *= 2`` — padding
     computation on constants) is fine and no longer needs a waiver;
2. at module scope of every file in scope: eager ``jnp.*`` calls —
   module import must not allocate on or talk to the accelerator
   (``jnp.dtype`` is exempt: it is host metadata).

*Traced functions* are found statically: functions decorated with
``jax.jit``/``functools.partial(jax.jit, ...)``, functions passed to
``jax.jit(...)`` by name (including ``self._fn`` method references
and either arm of a conditional expression), kernels passed to
``pl.pallas_call`` (including ``partial(kernel, ...)``), and every
``def`` nested inside one of those.

**Transitive variant** (interprocedural, PR 20): a helper that
host-syncs (``.item()``, ``jax.device_get``,
``.block_until_ready()``) — directly or deeper — is flagged at its
call site *inside the traced function*, with the full call chain, so
a sync hidden one call below the jit boundary is no longer invisible.
Only resolved call-graph edges propagate; an unresolved edge never
manufactures a finding.

Waiver: ``# lint: allow-tracer-hygiene`` on the flagged line.
"""

from __future__ import annotations

import ast
from typing import List, Set

from production_stack_tpu.staticcheck.cfg import CFG
from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    render_chain,
    rule,
    tail_name,
)
from production_stack_tpu.staticcheck import (
    callgraph,
    dataflow,
    summaries,
)

SCOPE = (
    "production_stack_tpu/ops/*.py",
    "production_stack_tpu/engine/model_runner.py",
)

_COERCIONS = {"bool", "int", "float", "len"}


def _is_jit_reference(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)``."""
    if tail_name(node) == "jit":
        return True
    if isinstance(node, ast.Call) and tail_name(node.func) == "partial":
        return bool(node.args) and tail_name(node.args[0]) == "jit"
    return False


def _target_tails(node: ast.AST) -> Set[str]:
    """Function names referenced by a jit/pallas_call argument:
    ``fn`` / ``self._fn`` / ``partial(fn, ...)`` / ``a if c else b``."""
    if isinstance(node, ast.IfExp):
        return _target_tails(node.body) | _target_tails(node.orelse)
    if isinstance(node, ast.Call) and tail_name(node.func) == "partial":
        return _target_tails(node.args[0]) if node.args else set()
    tail = tail_name(node)
    return {tail} if tail else set()


def traced_function_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_reference(dec):
                    names.add(node.name)
        elif isinstance(node, ast.Call):
            callee = tail_name(node.func)
            if callee == "jit" and node.args:
                names |= _target_tails(node.args[0])
            elif callee == "pallas_call" and node.args:
                names |= _target_tails(node.args[0])
    return names


def traced_functions(tree: ast.AST):
    """FunctionDef nodes that are traced, including defs nested in a
    traced function."""
    traced = traced_function_names(tree)

    def visit(node, inside):
        for child in ast.iter_child_nodes(node):
            is_fn = isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
            now_inside = inside or (is_fn and child.name in traced)
            if is_fn and now_inside:
                yield child
            yield from visit(child, now_inside)

    yield from visit(tree, False)


def _param_names(fn) -> Set[str]:
    args = fn.args
    names = {a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _taint_transfer(state, el, _kind):
    """Union-taint over locals: parameters (and anything computed
    from them) are traced values; literals and host arithmetic on
    untainted locals are not."""
    if not isinstance(el, ast.AST):
        return state

    def expr_tainted(expr) -> bool:
        return any(isinstance(n, ast.Name) and n.id in state
                   for n in ast.walk(expr))

    if isinstance(el, ast.Assign):
        targets = frozenset(n.id for t in el.targets
                            for n in ast.walk(t)
                            if isinstance(n, ast.Name))
        if expr_tainted(el.value):
            return state | targets
        return state - targets
    if isinstance(el, ast.AugAssign) and isinstance(el.target, ast.Name):
        if expr_tainted(el.value):
            return state | {el.target.id}
        return state  # x (op)= host-const keeps x's current status
    if isinstance(el, (ast.For, ast.AsyncFor)):
        targets = frozenset(n.id for n in ast.walk(el.target)
                            if isinstance(n, ast.Name))
        if expr_tainted(el.iter):
            return state | targets
        return state - targets
    return state


def _while_reads_traced(fn) -> dict:
    """{While node: bool(test can read a traced value)} for every
    while-loop in ``fn``, via the taint dataflow."""
    cfg = CFG(fn, raises=lambda _s, _t: False)
    block_in, _ = dataflow.solve(
        cfg, frozenset(_param_names(fn)), _taint_transfer,
        join="union")
    out = {}
    for block in cfg.reachable():
        if block.id not in block_in:
            continue
        state = block_in[block.id]
        for el in block.elements:
            if isinstance(el, ast.While):
                out[el] = any(
                    isinstance(n, ast.Name) and n.id in state
                    for n in ast.walk(el.test))
            state = _taint_transfer(state, el, None)
    return out


def _test_findings(sf, fn, test, kind: str) -> List[Finding]:
    out: List[Finding] = []
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            callee = sub.func
            if (isinstance(callee, ast.Name)
                    and callee.id in _COERCIONS
                    and sub.args
                    and not isinstance(sub.args[0], ast.Constant)):
                out.append(sf.finding(
                    "tracer-hygiene", sub,
                    f"{callee.id}()-driven {kind} in traced function "
                    f"{fn.name}: concretizes a traced value (host "
                    "sync / retrace); use lax.cond/select or keep it "
                    "device-side"))
            # .item() in a test is reported by the generic .item()
            # walk below — not doubled here.
        elif (isinstance(sub, ast.Attribute)
                and sub.attr in ("shape", "ndim")):
            out.append(sf.finding(
                "tracer-hygiene", sub,
                f"shape-dependent {kind} in traced function "
                f"{fn.name}: retraces per shape — waive if this "
                "bucketing is deliberate"))
    return out


def check_tree(sf) -> List[Finding]:
    """All tracer-hygiene findings for one parsed file."""
    tree = sf.tree
    if tree is None:
        return []
    findings: List[Finding] = []

    # (2) eager jnp work at module scope.
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for sub in ast.walk(stmt):
            if (isinstance(sub, ast.Call)
                    and recv_name(sub.func) == "jnp"
                    and tail_name(sub.func) != "dtype"):
                findings.append(sf.finding(
                    "tracer-hygiene", sub,
                    f"eager jnp.{tail_name(sub.func)}() at module "
                    "scope runs on the accelerator at import time — "
                    "build constants inside the traced function or "
                    "lazily"))

    # (1) hazards inside traced functions.
    for fn in traced_functions(tree):
        traced_whiles = _while_reads_traced(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                kind = ("while-loop test"
                        if isinstance(node, ast.While) else "branch")
                findings.extend(_test_findings(sf, fn, node.test, kind))
                # Whiles inside nested defs are judged in their own
                # function's taint context (traced_functions yields
                # nested defs separately).
                if (isinstance(node, ast.While)
                        and traced_whiles.get(node, False)):
                    findings.append(sf.finding(
                        "tracer-hygiene", node,
                        f"Python while-loop in traced function "
                        f"{fn.name}: its test can read a traced "
                        "value, so it traces unboundedly or fails — "
                        "use lax.while_loop/fori_loop"))
            elif isinstance(node, ast.IfExp):
                findings.extend(
                    _test_findings(sf, fn, node.test,
                                   "conditional expression"))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"):
                findings.append(sf.finding(
                    "tracer-hygiene", node,
                    f".item() in traced function {fn.name}: "
                    "device->host sync inside the step"))
    return findings


def _transitive_findings(project: Project, sf) -> List[Finding]:
    """Host syncs reached through helpers called from traced code."""
    if sf.tree is None:
        return []
    graph = callgraph.for_project(project)
    sums = summaries.for_project(project)
    findings: List[Finding] = []
    for fn in traced_functions(sf.tree):
        info = graph.function_at(sf.relpath, fn)
        if info is None:
            continue
        for edge in graph.resolved_edges_from(info.qual):
            summary = sums.get(edge.callee)
            if summary.may_host_sync is None:
                continue
            if summaries.host_sync_reason(edge.call):
                continue  # the direct walk already flagged it
            callee_info = graph.functions.get(edge.callee)
            chain = (
                (sf.relpath, edge.lineno, f"traced {fn.name}"),
                (sf.relpath, edge.lineno, callee_info.label()),
            ) + summary.may_host_sync
            findings.append(sf.finding(
                "tracer-hygiene", edge.call,
                f"call to {edge.target_text}() in traced function "
                f"{fn.name} reaches a device->host sync via "
                f"{render_chain(chain)} — host reads cannot live "
                "below a jit/pallas boundary",
                chain=chain))
    return findings


@rule("tracer-hygiene",
      "no recompile/host-sync hazards in jitted or pallas code, "
      "including through helpers (transitive)",
      interprocedural=True)
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        findings.extend(check_tree(sf))
        findings.extend(_transitive_findings(project, sf))
    return findings
