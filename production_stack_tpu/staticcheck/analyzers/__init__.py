"""Analyzer modules. Importing this package registers every rule in
``staticcheck.core.REGISTRY`` (each module's ``@rule`` decorator runs
at import). Add a new analyzer by dropping a module here and
importing it below — see docs/static_analysis.md.
"""

from production_stack_tpu.staticcheck.analyzers import (  # noqa: F401
    async_blocking,
    config_contract,
    dispatch_path,
    endpoint_contract,
    kv_parity,
    lock_discipline,
    metrics_contract,
    network_timeout,
    page_lifecycle,
    shape_flow,
    slo_contract,
    span_contract,
    state_machine,
    tracer_hygiene,
)
