"""Rule ``shape-flow``: a static recompile-budget proof for the jit
boundary.

The serving stack's "zero per-step recompiles" story rests on every
shape-determining Python scalar that reaches an ``InstrumentedJit`` /
``jax.jit`` call site being drawn from a *closed* value set: a bucket
lattice (``_bucket_for`` / ``_row_bucket_for`` / ``prefill_buckets``),
an init-fixed config constant, or a bool/string static flag. One
un-snapped ``len(rows)`` handed to a jitted program compiles a fresh
executable per distinct batch size — the latency cliff the bucket
lattices exist to prevent, and one a functional test never sees
(everything still returns the right tokens, 40 compiles later).

This rule makes the budget a static proof. It finds every jit
*handle* (``self._x = InstrumentedJit(...)`` / ``x = jax.jit(...)``)
and every call through one, then classifies each argument's
**value flow** interprocedurally over the call graph
(staticcheck/callgraph.py):

- **snapped** — literals, ``self.*``/attribute reads (init-fixed
  config), a call to a snap helper (``_bucket_for``,
  ``_row_bucket_for``, ``prefill_buckets``), or arithmetic/min/max
  over snapped values. A local whose every assignment is snapped is
  snapped — so the inline pow2 lattice idiom (``t = 16`` then
  ``t *= 2`` in a loop) proves itself: comparisons against raw data
  steer *which* lattice point is chosen but cannot leave the lattice.
- **raw** — ``len(...)`` (a data-dependent unbounded int) and
  anything arithmetic derives from one. A bare parameter traces to
  every *resolved* caller's actual argument; a call to a resolved
  helper traces into that helper's return expressions — both
  directions report the **full chain** from the jit call site to the
  raw origin.
- **opaque** — array-valued expressions (subscripts like
  ``payload["tokens"]``, ``jnp.asarray``/``_as_device`` wrappers,
  unresolved calls). Never flagged: device arrays carry their shapes
  from their (bucket-padded) construction sites, and an unresolved
  edge must never manufacture a finding (callgraph.py soundness
  stance). The proof obligation here is precisely the *Python
  scalars* crossing the boundary.

A deliberate un-snapped source carries ``# lint: shape-source`` on
its line (assignment or call-site argument) — the declaration is the
reviewable artifact: every recompile trigger is either lattice-
bounded by construction or explicitly signed off (see
CONTRIBUTING.md). ``# lint: allow-shape-flow`` on the call-site line
waives the whole site, same as every other rule.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    render_chain,
    rule,
    tail_name,
)
from production_stack_tpu.staticcheck import callgraph, summaries

Frame = Tuple[str, int, str]

# The bucket-lattice snap vocabulary (engine/model_runner.py).
SNAP_HELPERS = {"_bucket_for", "_row_bucket_for", "prefill_buckets"}

# Builtins that only *select or combine* — raw iff an input is raw.
_COMBINERS = {"min", "max", "abs", "round", "int", "sum", "pow",
              "divmod"}

_SHAPE_SOURCE_RE = re.compile(r"#\s*lint:\s*shape-source\b")

_MAX_DEPTH = 6


def _shape_source_lines(sf) -> Set[int]:
    cached = getattr(sf, "_shape_source_lines", None)
    if cached is None:
        cached = {i for i, line in enumerate(sf.lines, start=1)
                  if _SHAPE_SOURCE_RE.search(line)}
        sf._shape_source_lines = cached
    return cached


def jit_handles(tree: ast.AST) -> Set[str]:
    """Names bound to an InstrumentedJit / jax.jit result in this
    module: ``self._step_jit = InstrumentedJit(...)``,
    ``x = jax.jit(...)`` — the attr/local name is the handle."""
    handles: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        value = node.value
        is_jit = False
        if isinstance(value, ast.Call):
            t = tail_name(value.func)
            if t == "InstrumentedJit":
                is_jit = True
            elif t == "jit":
                # jax.jit(...) itself, not jax.jit(fn)(...) inline.
                is_jit = True
        if not is_jit:
            continue
        target = node.targets[0]
        if isinstance(target, ast.Attribute):
            handles.add(target.attr)
        elif isinstance(target, ast.Name):
            handles.add(target.id)
    return handles


class _FnCtx:
    """One function's classification context: its source file, the
    call edges keyed by call-node identity, the flow-insensitive
    local assignment map, and its parameter list."""

    def __init__(self, sf, info, graph):
        self.sf = sf
        self.info = info
        self.edges_by_call = {id(e.call): e
                              for e in graph.edges_from(info.qual)}
        args = info.node.args
        self.params = [a.arg for a in (args.posonlyargs + args.args
                                       + args.kwonlyargs)]
        # name -> [RHS exprs bound to it anywhere in the function]
        self.locals: Dict[str, List[ast.AST]] = {}
        for node in summaries.own_body_nodes(info.node):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.locals.setdefault(target.id, []).append(
                            node.value)
                    elif isinstance(target, ast.Tuple):
                        for elt in target.elts:
                            if isinstance(elt, ast.Name):
                                self.locals.setdefault(
                                    elt.id, []).append(node.value)
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                self.locals.setdefault(node.target.id, []).append(
                    node.value)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name) and node.value is not None:
                self.locals.setdefault(node.target.id, []).append(
                    node.value)


class _Classifier:
    """Interprocedural raw-int tracer. ``find_raw`` returns the chain
    of frames from the expression down to an un-snapped origin, or
    None when the expression provably stays inside the lattice (or is
    array-valued/opaque)."""

    def __init__(self, project: Project):
        self.project = project
        self.graph = callgraph.for_project(project)
        self.sums = summaries.for_project(project)
        self._ctx_cache: Dict[str, _FnCtx] = {}
        self._param_memo: Dict[Tuple[str, str],
                               Optional[Tuple[Frame, ...]]] = {}
        self._ret_memo: Dict[str, Optional[Tuple[Frame, ...]]] = {}

    def ctx_for(self, qual: str) -> Optional[_FnCtx]:
        ctx = self._ctx_cache.get(qual)
        if ctx is None:
            info = self.graph.functions.get(qual)
            if info is None:
                return None
            sf = self.project.source(info.path)
            if sf is None:
                return None
            ctx = _FnCtx(sf, info, self.graph)
            self._ctx_cache[qual] = ctx
        return ctx

    # ---- classification -------------------------------------------------

    def find_raw(self, expr: ast.AST, ctx: _FnCtx, depth: int,
                 visiting: Set[Tuple[str, str]]
                 ) -> Optional[Tuple[Frame, ...]]:
        if depth > _MAX_DEPTH:
            return None  # honest give-up: never guess a finding
        line = getattr(expr, "lineno", 0)
        if line in _shape_source_lines(ctx.sf):
            return None  # declared shape source
        if isinstance(expr, ast.Constant):
            return None
        if isinstance(expr, ast.Attribute):
            return None  # init-fixed config / device array
        if isinstance(expr, (ast.Subscript, ast.JoinedStr, ast.List,
                             ast.Tuple, ast.Set, ast.Dict,
                             ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp, ast.Lambda)):
            return None  # array/container-valued: opaque
        if isinstance(expr, ast.Call):
            return self._classify_call(expr, ctx, depth, visiting)
        if isinstance(expr, ast.BinOp):
            return (self.find_raw(expr.left, ctx, depth, visiting)
                    or self.find_raw(expr.right, ctx, depth,
                                     visiting))
        if isinstance(expr, ast.UnaryOp):
            return self.find_raw(expr.operand, ctx, depth, visiting)
        if isinstance(expr, (ast.BoolOp,)):
            for value in expr.values:
                chain = self.find_raw(value, ctx, depth, visiting)
                if chain:
                    return chain
            return None
        if isinstance(expr, ast.IfExp):
            return (self.find_raw(expr.body, ctx, depth, visiting)
                    or self.find_raw(expr.orelse, ctx, depth,
                                     visiting))
        if isinstance(expr, ast.Starred):
            return self.find_raw(expr.value, ctx, depth, visiting)
        if isinstance(expr, ast.Name):
            return self._classify_name(expr, ctx, depth, visiting)
        return None

    def _classify_call(self, call: ast.Call, ctx: _FnCtx, depth: int,
                       visiting: Set[Tuple[str, str]]
                       ) -> Optional[Tuple[Frame, ...]]:
        tname = tail_name(call.func)
        if tname in SNAP_HELPERS:
            return None  # the snap IS the proof, whatever feeds it
        if isinstance(call.func, ast.Name) and call.func.id == "len":
            return ((ctx.sf.relpath, call.lineno,
                     "len(…) — un-snapped data-dependent int"),)
        if isinstance(call.func, ast.Name) and \
                call.func.id in _COMBINERS:
            for arg in call.args:
                chain = self.find_raw(arg, ctx, depth, visiting)
                if chain:
                    return chain
            return None
        edge = ctx.edges_by_call.get(id(call))
        if edge is None or edge.callee is None:
            return None  # builtin/unresolved: opaque, never a finding
        chain = self._return_raw(edge.callee, depth + 1, visiting)
        if chain:
            site: Frame = (ctx.sf.relpath, call.lineno,
                           f"{edge.target_text}()")
            return (site,) + chain
        return None

    def _classify_name(self, name: ast.Name, ctx: _FnCtx, depth: int,
                       visiting: Set[Tuple[str, str]]
                       ) -> Optional[Tuple[Frame, ...]]:
        key = (ctx.info.qual, name.id)
        if key in visiting:
            return None  # cycle (e.g. t *= 2): stays in its lattice
        rhss = ctx.locals.get(name.id)
        if rhss:
            visiting = visiting | {key}
            for rhs in rhss:
                if getattr(rhs, "lineno", 0) in \
                        _shape_source_lines(ctx.sf):
                    continue  # this binding is a declared source
                chain = self.find_raw(rhs, ctx, depth, visiting)
                if chain:
                    origin: Frame = (
                        ctx.sf.relpath, rhs.lineno,
                        f"{name.id} = …")
                    return (origin,) + chain if chain[0][1] != \
                        rhs.lineno else chain
            return None
        if name.id in ctx.params:
            return self._param_raw(ctx, name.id, depth, visiting)
        return None  # module constant / import: fixed at import time

    def _param_raw(self, ctx: _FnCtx, param: str, depth: int,
                   visiting: Set[Tuple[str, str]]
                   ) -> Optional[Tuple[Frame, ...]]:
        """Trace a parameter to every resolved caller's actual."""
        key = (ctx.info.qual, param)
        if key in self._param_memo:
            return self._param_memo[key]
        if key in visiting:
            return None
        visiting = visiting | {key}
        self._param_memo[key] = None  # provisional (recursion-safe)
        result: Optional[Tuple[Frame, ...]] = None
        for edge in self.graph.callers.get(ctx.info.qual, []):
            caller_ctx = self.ctx_for(edge.caller)
            if caller_ctx is None:
                continue
            actual = self._actual_for_param(edge, ctx, param)
            if actual is None:
                continue  # defaulted or unmappable: no flow
            chain = self.find_raw(actual, caller_ctx, depth + 1,
                                  visiting)
            if chain:
                site: Frame = (
                    caller_ctx.sf.relpath, edge.lineno,
                    f"{caller_ctx.info.label()} passes {param}")
                result = (site,) + chain
                break
        self._param_memo[key] = result
        return result

    def _actual_for_param(self, edge, callee_ctx: _FnCtx,
                          param: str) -> Optional[ast.AST]:
        params = callee_ctx.params
        if param not in params:
            return None
        for kw in edge.call.keywords:
            if kw.arg == param:
                return kw.value
        idx = params.index(param)
        if params and params[0] in ("self", "cls") and \
                isinstance(edge.call.func, ast.Attribute):
            idx -= 1
        if 0 <= idx < len(edge.call.args):
            arg = edge.call.args[idx]
            if isinstance(arg, ast.Starred):
                return None
            return arg
        return None

    def _return_raw(self, qual: str, depth: int,
                    visiting: Set[Tuple[str, str]]
                    ) -> Optional[Tuple[Frame, ...]]:
        """Does this callee's return value derive from a raw int?
        Parameters inside the callee are opaque here — the caller
        direction covers values threaded straight through."""
        if qual in self._ret_memo:
            return self._ret_memo[qual]
        if depth > _MAX_DEPTH:
            return None
        self._ret_memo[qual] = None  # provisional
        ctx = self.ctx_for(qual)
        result: Optional[Tuple[Frame, ...]] = None
        if ctx is not None:
            for node in summaries.own_body_nodes(ctx.info.node):
                if not isinstance(node, ast.Return) or \
                        node.value is None:
                    continue
                if isinstance(node.value, ast.Name) and \
                        node.value.id in ctx.params:
                    continue  # pass-through: caller side owns it
                chain = self.find_raw(node.value, ctx, depth,
                                      visiting)
                if chain:
                    ret: Frame = (ctx.sf.relpath, node.value.lineno,
                                  f"return in {ctx.info.label()}")
                    result = (ret,) + chain if chain[0][1] != \
                        node.value.lineno else chain
                    break
        self._ret_memo[qual] = result
        return result


@rule("shape-flow",
      "every Python scalar reaching an InstrumentedJit/jax.jit call "
      "site traces to a bucket snap, a fixed config constant, or a "
      "declared shape-source (transitive)",
      interprocedural=True)
def check(project: Project) -> List[Finding]:
    classifier = _Classifier(project)
    graph = classifier.graph
    findings: List[Finding] = []
    for sf in project.files(f"{callgraph.PACKAGE}/**/*.py"):
        if sf.tree is None:
            continue
        handles = jit_handles(sf.tree)
        if not handles:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            info = graph.function_at(sf.relpath, node)
            if info is None:
                continue
            ctx = classifier.ctx_for(info.qual)
            if ctx is None:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                fname = tail_name(call.func)
                if fname not in handles:
                    continue
                if not isinstance(call.func,
                                  (ast.Name, ast.Attribute)):
                    continue
                for arg in list(call.args) + \
                        [kw.value for kw in call.keywords]:
                    chain = classifier.find_raw(arg, ctx, 0, set())
                    if not chain:
                        continue
                    full = ((sf.relpath, call.lineno,
                             f"jit call {fname}(…) in "
                             f"{node.name}"),) + chain
                    findings.append(sf.finding(
                        "shape-flow", call,
                        f"argument to jitted {fname}() in "
                        f"{node.name} derives from an un-snapped "
                        "data-dependent int via "
                        f"{render_chain(full)} — snap it through "
                        "_bucket_for/_row_bucket_for/prefill_buckets "
                        "or declare it with '# lint: shape-source'",
                        chain=full))
    return findings
