"""Rule ``host-read``: no blocking host reads on the decode dispatch
path.

The overlapped async pipeline (docs/async_pipeline.md) only hides
host work if ``ModelRunner.dispatch_decode`` and everything it calls
stays purely dispatching: building a payload, one fused host->device
transfer, launching the jitted step. A single ``np.asarray(device
array)``, ``jax.device_get`` or ``.block_until_ready()`` anywhere on
that path silently re-serializes the pipeline — the step "works" but
the overlap is gone, which no functional test notices. Inside the
DISPATCH_PATH functions of engine/model_runner.py this flags:

- ``np.asarray(...)`` / ``np.array(...)`` — *unless* the argument is
  provably host-origin (see below): converting a Python list is a
  plain host op, not a device sync,
- ``jax.device_get(...)`` / ``device_get(...)``,
- ``<anything>.block_until_ready()`` and ``<array>.item()``.

Host-origin is decided flow-sensitively over the CFG
(staticcheck/cfg.py) with a must-analysis (staticcheck/dataflow.py,
intersection join): an argument is host-origin when it is a literal,
a known host-list attribute of a sequence (``seq.output_token_ids``,
``seq.prompt_token_ids``, ...), a ``list()``/``range()``/``sorted()``
result, or a local name assigned only such values on **every** path
reaching the call. Anything a device value could flow into stays
flagged. This is what used to require ``# lint: allow-host-read``
waivers on the penalty-payload asarray calls — the dataflow now
proves those reads safe instead.

``int(...)`` / ``float(...)`` of host scalars are fine and not
flagged. A deliberate device read still carries
``# lint: allow-host-read`` on the call line. The DISPATCH_PATH set
must track reality: a listed name missing from model_runner.py is
itself a finding, so a renamed function cannot silently fall out of
coverage.

**Transitive variant** (interprocedural, PR 20): a helper called
from a DISPATCH_PATH function whose summary says it may host-sync
(``.item()`` / ``device_get`` / ``.block_until_ready()`` anywhere in
its resolved call tree) is flagged at the dispatch-path call site
with the full chain — the blocking read re-serializes the pipeline
no matter how many frames down it hides. Resolved edges only; an
unresolved edge never manufactures a finding.

Migrated from tests/test_dispatch_path_lint.py (PR 3), now a thin
wrapper over this rule.
"""

from __future__ import annotations

import ast
from typing import FrozenSet, List

from production_stack_tpu.staticcheck.cfg import CFG
from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    render_chain,
    rule,
    tail_name,
)
from production_stack_tpu.staticcheck import (
    callgraph,
    dataflow,
    summaries,
)

RUNNER = "production_stack_tpu/engine/model_runner.py"

# Every function the async dispatch path runs through. run_decode /
# result() are NOT here: they are the sync completion side and their
# device_get is the one intended blocking read.
DISPATCH_PATH = {
    "dispatch_decode",
    "_staging_set",
    "_dispatch",
    "execute_payload",
    "_optional_device_inputs",
    "_penalty_payload",
    "_seed_payload",
    "_bias_payload",
    "_suppress_payload",
    "_guided_payload",
    "_next_rng",
    "_as_device",
}

# Attributes that are host Python lists/scalars by construction
# (engine/sequence.py): reading them never touches the device.
HOST_ATTRS = {
    "output_token_ids", "prompt_token_ids", "all_token_ids",
    "stop_token_ids", "pages", "num_computed_tokens",
    "num_prior_output_tokens", "seq_id", "sampling",
}

# Builtins whose result is host data when their inputs are.
_HOST_CALLS = {"list", "tuple", "range", "sorted", "len", "int",
               "float", "min", "max", "sum", "enumerate", "zip"}


def _is_host_expr(node: ast.AST, host_names: FrozenSet[str]) -> bool:
    """Conservative proof that ``node`` is host data (never a device
    array)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple, ast.Set, ast.ListComp,
                         ast.SetComp, ast.GeneratorExp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in host_names
    if isinstance(node, ast.Attribute):
        return node.attr in HOST_ATTRS
    if isinstance(node, ast.Subscript):
        return _is_host_expr(node.value, host_names)
    if isinstance(node, ast.BinOp):
        return (_is_host_expr(node.left, host_names)
                and _is_host_expr(node.right, host_names))
    if isinstance(node, ast.Call):
        return (isinstance(node.func, ast.Name)
                and node.func.id in _HOST_CALLS
                and all(_is_host_expr(a, host_names)
                        for a in node.args))
    return False


def _host_transfer(state: FrozenSet[str], el, _kind) -> FrozenSet[str]:
    if isinstance(el, ast.Assign):
        names = [t.id for t in el.targets if isinstance(t, ast.Name)]
        if names:
            if _is_host_expr(el.value, state):
                return state | frozenset(names)
            return state - frozenset(names)
    elif isinstance(el, ast.AugAssign) and isinstance(
            el.target, ast.Name):
        if not _is_host_expr(el.value, state):
            return state - {el.target.id}
    elif isinstance(el, (ast.For, ast.AsyncFor)):
        targets = frozenset(n.id for n in ast.walk(el.target)
                            if isinstance(n, ast.Name))
        if _is_host_expr(el.iter, state):
            return state | targets
        return state - targets
    return state


def is_blocking_call(call: ast.Call) -> bool:
    func = call.func
    name = tail_name(func)
    recv = recv_name(func)
    if recv == "np" and name in ("asarray", "array"):
        return True
    if name == "device_get":  # jax.device_get or bare import
        return True
    if isinstance(func, ast.Attribute) and name in (
            "block_until_ready", "item"):
        return True
    return False


def _host_exempt(call: ast.Call, host_names: FrozenSet[str]) -> bool:
    """np.asarray/np.array of provably-host data is a plain host op."""
    if recv_name(call.func) != "np":
        return False
    if tail_name(call.func) not in ("asarray", "array"):
        return False
    return bool(call.args) and _is_host_expr(call.args[0], host_names)


def dispatch_path_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in DISPATCH_PATH:
                yield node


def _transitive_findings(project: Project, sf, fn) -> List[Finding]:
    """Host syncs hidden below a dispatch-path function boundary."""
    graph = callgraph.for_project(project)
    sums = summaries.for_project(project)
    info = graph.function_at(sf.relpath, fn)
    if info is None:
        return []
    findings: List[Finding] = []
    for edge in graph.resolved_edges_from(info.qual):
        callee_info = graph.functions.get(edge.callee)
        if callee_info is None or callee_info.name in DISPATCH_PATH:
            continue  # covered by its own dispatch-path scan
        summary = sums.get(edge.callee)
        if summary.may_host_sync is None:
            continue
        if is_blocking_call(edge.call):
            continue  # the intraprocedural scan already flagged it
        chain = (
            (sf.relpath, edge.lineno, fn.name),
            (sf.relpath, edge.lineno, callee_info.label()),
        ) + summary.may_host_sync
        findings.append(sf.finding(
            "host-read", edge.call,
            f"call to {edge.target_text}() in dispatch-path "
            f"function {fn.name} reaches a blocking host read via "
            f"{render_chain(chain)} — it re-serializes the async "
            "pipeline (docs/async_pipeline.md)",
            chain=chain))
    return findings


@rule("host-read",
      "no blocking host reads inside the async dispatch path, "
      "including through helpers (transitive)",
      interprocedural=True)
def check(project: Project) -> List[Finding]:
    sf = project.source(RUNNER)
    if sf is None or sf.tree is None:
        return []
    findings: List[Finding] = []
    seen = set()
    for fn in dispatch_path_functions(sf.tree):
        seen.add(fn.name)
        findings.extend(_transitive_findings(project, sf, fn))
        cfg = CFG(fn, raises=lambda _s, _t: False)
        block_in, _ = dataflow.solve(
            cfg, frozenset(), _host_transfer, join="intersection")
        for block in cfg.reachable():
            if block.id not in block_in:
                continue
            state = block_in[block.id]
            for el in block.elements:
                if isinstance(el, ast.AST) and not isinstance(
                        el, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for node in ast.walk(el):
                        if (isinstance(node, ast.Call)
                                and is_blocking_call(node)
                                and not _host_exempt(node, state)):
                            findings.append(sf.finding(
                                "host-read", node,
                                f"blocking host read in {fn.name} "
                                "re-serializes the async pipeline — "
                                "move it to result()/completion "
                                "(docs/async_pipeline.md)"))
                state = _host_transfer(state, el, None)
    missing = DISPATCH_PATH - seen
    if missing:
        findings.append(Finding(
            rule="host-read", path=RUNNER, line=0,
            message="DISPATCH_PATH names not found in "
                    f"model_runner.py: {sorted(missing)} — update "
                    "staticcheck/analyzers/dispatch_path.py so the "
                    "lint tracks the real call graph"))
    return findings
