"""Rule ``host-read``: no blocking host reads on the decode dispatch
path.

The overlapped async pipeline (docs/async_pipeline.md) only hides
host work if ``ModelRunner.dispatch_decode`` and everything it calls
stays purely dispatching: building a payload, one fused host->device
transfer, launching the jitted step. A single ``np.asarray(device
array)``, ``jax.device_get`` or ``.block_until_ready()`` anywhere on
that path silently re-serializes the pipeline — the step "works" but
the overlap is gone, which no functional test notices. Inside the
DISPATCH_PATH functions of engine/model_runner.py this flags:

- ``np.asarray(...)`` / ``np.array(...)`` (device->host copy when fed
  a device array),
- ``jax.device_get(...)`` / ``device_get(...)``,
- ``<anything>.block_until_ready()`` and ``<array>.item()``.

``int(...)`` / ``float(...)`` of host scalars are fine and not
flagged. A deliberate host read carries ``# lint: allow-host-read``
on the call line. The DISPATCH_PATH set must track reality: a listed
name missing from model_runner.py is itself a finding, so a renamed
function cannot silently fall out of coverage.

Migrated from tests/test_dispatch_path_lint.py (PR 3), now a thin
wrapper over this rule.
"""

from __future__ import annotations

import ast
from typing import List

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    rule,
    tail_name,
)

RUNNER = "production_stack_tpu/engine/model_runner.py"

# Every function the async dispatch path runs through. run_decode /
# result() are NOT here: they are the sync completion side and their
# device_get is the one intended blocking read.
DISPATCH_PATH = {
    "dispatch_decode",
    "_staging_set",
    "_dispatch",
    "execute_payload",
    "_optional_device_inputs",
    "_penalty_payload",
    "_seed_payload",
    "_bias_payload",
    "_suppress_payload",
    "_guided_payload",
    "_next_rng",
    "_as_device",
}


def is_blocking_call(call: ast.Call) -> bool:
    func = call.func
    name = tail_name(func)
    recv = recv_name(func)
    if recv == "np" and name in ("asarray", "array"):
        return True
    if name == "device_get":  # jax.device_get or bare import
        return True
    if isinstance(func, ast.Attribute) and name in (
            "block_until_ready", "item"):
        return True
    return False


def dispatch_path_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in DISPATCH_PATH:
                yield node


@rule("host-read",
      "no blocking host reads inside the async dispatch path")
def check(project: Project) -> List[Finding]:
    sf = project.source(RUNNER)
    if sf is None or sf.tree is None:
        return []
    findings: List[Finding] = []
    seen = set()
    for fn in dispatch_path_functions(sf.tree):
        seen.add(fn.name)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and is_blocking_call(node):
                findings.append(sf.finding(
                    "host-read", node,
                    f"blocking host read in {fn.name} re-serializes "
                    "the async pipeline — move it to result()/"
                    "completion (docs/async_pipeline.md)"))
    missing = DISPATCH_PATH - seen
    if missing:
        findings.append(Finding(
            rule="host-read", path=RUNNER, line=0,
            message="DISPATCH_PATH names not found in "
                    f"model_runner.py: {sorted(missing)} — update "
                    "staticcheck/analyzers/dispatch_path.py so the "
                    "lint tracks the real call graph"))
    return findings
