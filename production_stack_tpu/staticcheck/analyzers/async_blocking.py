"""Rule ``async-blocking``: no blocking calls inside ``async def``
bodies across router/ and engine/server.py.

The router and engine server are single-event-loop aiohttp apps: one
``time.sleep`` or synchronous ``requests.get`` inside a coroutine
stalls EVERY in-flight request (and the health prober, and the
breaker timers) for its full duration. Blocking work belongs on
worker threads (the scraper/prober pattern) or behind
``loop.run_in_executor``/``asyncio.to_thread``. Flags, inside
``async def`` bodies only:

- ``time.sleep(...)`` (use ``await asyncio.sleep``),
- any ``requests.*`` call (use the shared aiohttp session),
- ``urllib.request.*`` / ``socket.*`` connect-ish calls,
- ``subprocess.run/call/check_call/check_output`` and ``os.system``,
- synchronous ``open(...)`` (use aiofiles; small-config reads may be
  waived).

Nested *sync* ``def``s inside a coroutine are skipped: they are
values, commonly handed to ``run_in_executor``; if one is called
inline the call site itself is still scanned. Waive a justified case
with ``# lint: allow-async-blocking`` on the call line.

**Transitive variant** (interprocedural, PR 20): a *sync* helper
that blocks — directly or through further resolved calls — is
flagged at the ``async def`` call site with the full call chain
(``async def poll → utils.py:read_config → open()``), because the
handler is where the event loop stalls. The same helper reached only
from sync code is not flagged; an async callee that blocks is
reported inside itself, not re-reported at every caller; and a
helper reference merely *passed* to ``run_in_executor``/
``asyncio.to_thread`` produces no call edge, so the sanctioned
pattern stays clean. Unresolved call edges never produce findings
(summaries.py soundness stance).

Generalizes the PR1 timeout lint / PR3 dispatch lint approach to the
whole async surface.
"""

from __future__ import annotations

import ast
from typing import List

from production_stack_tpu.staticcheck import callgraph, summaries
from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    render_chain,
    rule,
    tail_name,
)

SCOPE = (
    "production_stack_tpu/router/**/*.py",
    "production_stack_tpu/engine/server.py",
)

_SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output"}


def blocking_reason(call: ast.Call) -> str:
    """Why this call blocks the event loop ('' if it doesn't)."""
    func = call.func
    name = tail_name(func)
    recv = recv_name(func)
    if name == "sleep" and recv in ("time", ""):
        if recv == "time" or isinstance(func, ast.Name):
            return ("time.sleep blocks the event loop — "
                    "await asyncio.sleep")
    if recv == "requests":
        return ("synchronous requests.* blocks the event loop — use "
                "the shared aiohttp session")
    if recv in ("urlopen", "urllib") or name == "urlopen":
        return "urllib blocks the event loop"
    if recv == "socket" and name in ("create_connection",
                                     "getaddrinfo", "gethostbyname"):
        return "blocking socket call on the event loop"
    if recv == "subprocess" and name in _SUBPROCESS_CALLS:
        return ("subprocess.* blocks the event loop — use "
                "asyncio.create_subprocess_exec")
    if recv == "os" and name == "system":
        return "os.system blocks the event loop"
    if isinstance(func, ast.Name) and func.id == "open":
        return ("synchronous open() on the event loop — use aiofiles "
                "(waivable for small local config reads)")
    return ""


def _walk_async_body(node: ast.AST):
    """Statements reachable on the coroutine's own frame: descend
    everything except nested function/class definitions (nested sync
    defs are values, often executor targets; nested coroutines get
    their own visit)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            continue
        yield child
        yield from _walk_async_body(child)


def async_blocking_calls(tree: ast.AST):
    """(async_fn, call, reason) triples for a module tree."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in [stmt, *_walk_async_body(stmt)]:
                if isinstance(sub, ast.Call):
                    reason = blocking_reason(sub)
                    if reason:
                        yield node, sub, reason


@rule("async-blocking",
      "no blocking calls (sleep/requests/sync IO) in async def "
      "bodies, including through sync helpers (transitive)",
      interprocedural=True)
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue
        for fn, call, reason in async_blocking_calls(sf.tree):
            findings.append(sf.finding(
                "async-blocking", call,
                f"in async def {fn.name}: {reason}"))
    findings.extend(_transitive_findings(project))
    return findings


def _transitive_findings(project: Project) -> List[Finding]:
    """Blocking work reached through sync helpers, flagged where the
    event loop actually stalls: the call site inside the coroutine."""
    graph = callgraph.for_project(project)
    sums = summaries.for_project(project)
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            info = graph.function_at(sf.relpath, node)
            if info is None:
                continue
            for edge in graph.resolved_edges_from(info.qual):
                callee_info = graph.functions.get(edge.callee)
                if callee_info is None or callee_info.is_async:
                    continue  # async callees report themselves
                summary = sums.get(edge.callee)
                if summary.may_block is None:
                    continue
                if blocking_reason(edge.call):
                    continue  # the direct walk already flagged it
                chain = (
                    (sf.relpath, edge.lineno,
                     f"async def {node.name}"),
                    (sf.relpath, edge.lineno, callee_info.label()),
                ) + summary.may_block
                findings.append(sf.finding(
                    "async-blocking", edge.call,
                    f"in async def {node.name}: call to "
                    f"{edge.target_text}() blocks the event loop "
                    f"via {render_chain(chain)} — move the blocking "
                    "work to run_in_executor/asyncio.to_thread or "
                    "make the helper async",
                    chain=chain))
    return findings
