"""Rule ``async-blocking``: no blocking calls inside ``async def``
bodies across router/ and engine/server.py.

The router and engine server are single-event-loop aiohttp apps: one
``time.sleep`` or synchronous ``requests.get`` inside a coroutine
stalls EVERY in-flight request (and the health prober, and the
breaker timers) for its full duration. Blocking work belongs on
worker threads (the scraper/prober pattern) or behind
``loop.run_in_executor``/``asyncio.to_thread``. Flags, inside
``async def`` bodies only:

- ``time.sleep(...)`` (use ``await asyncio.sleep``),
- any ``requests.*`` call (use the shared aiohttp session),
- ``urllib.request.*`` / ``socket.*`` connect-ish calls,
- ``subprocess.run/call/check_call/check_output`` and ``os.system``,
- synchronous ``open(...)`` (use aiofiles; small-config reads may be
  waived).

Nested *sync* ``def``s inside a coroutine are skipped: they are
values, commonly handed to ``run_in_executor``; if one is called
inline the call site itself is still scanned. Waive a justified case
with ``# lint: allow-async-blocking`` on the call line.

Generalizes the PR1 timeout lint / PR3 dispatch lint approach to the
whole async surface.
"""

from __future__ import annotations

import ast
from typing import List

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    rule,
    tail_name,
)

SCOPE = (
    "production_stack_tpu/router/**/*.py",
    "production_stack_tpu/engine/server.py",
)

_SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output"}


def blocking_reason(call: ast.Call) -> str:
    """Why this call blocks the event loop ('' if it doesn't)."""
    func = call.func
    name = tail_name(func)
    recv = recv_name(func)
    if name == "sleep" and recv in ("time", ""):
        if recv == "time" or isinstance(func, ast.Name):
            return ("time.sleep blocks the event loop — "
                    "await asyncio.sleep")
    if recv == "requests":
        return ("synchronous requests.* blocks the event loop — use "
                "the shared aiohttp session")
    if recv in ("urlopen", "urllib") or name == "urlopen":
        return "urllib blocks the event loop"
    if recv == "socket" and name in ("create_connection",
                                     "getaddrinfo", "gethostbyname"):
        return "blocking socket call on the event loop"
    if recv == "subprocess" and name in _SUBPROCESS_CALLS:
        return ("subprocess.* blocks the event loop — use "
                "asyncio.create_subprocess_exec")
    if recv == "os" and name == "system":
        return "os.system blocks the event loop"
    if isinstance(func, ast.Name) and func.id == "open":
        return ("synchronous open() on the event loop — use aiofiles "
                "(waivable for small local config reads)")
    return ""


def _walk_async_body(node: ast.AST):
    """Statements reachable on the coroutine's own frame: descend
    everything except nested function/class definitions (nested sync
    defs are values, often executor targets; nested coroutines get
    their own visit)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            continue
        yield child
        yield from _walk_async_body(child)


def async_blocking_calls(tree: ast.AST):
    """(async_fn, call, reason) triples for a module tree."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in [stmt, *_walk_async_body(stmt)]:
                if isinstance(sub, ast.Call):
                    reason = blocking_reason(sub)
                    if reason:
                        yield node, sub, reason


@rule("async-blocking",
      "no blocking calls (sleep/requests/sync IO) in async def bodies")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue
        for fn, call, reason in async_blocking_calls(sf.tree):
            findings.append(sf.finding(
                "async-blocking", call,
                f"in async def {fn.name}: {reason}"))
    return findings
