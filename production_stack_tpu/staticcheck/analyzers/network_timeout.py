"""Rule ``no-timeout``: every outbound network call under
``production_stack_tpu/router/`` must carry an explicit timeout.

The resilience layer's bounded-wait guarantee (docs/resilience.md)
regresses silently otherwise. Flags:

- ``requests.<verb>(...)`` without a ``timeout=`` keyword,
- ``aiohttp.ClientSession(...)`` / ``ClientSession(...)`` constructors
  without a ``timeout=`` keyword (session default),
- ``<anything named *session*>.<verb>(...)`` without ``timeout=``.

Waive an intentionally unbounded call with ``# lint: allow-no-timeout``
on the call line (rare; justify in review).

Migrated from tests/test_network_timeout_lint.py (PR 1), which is now
a thin wrapper over this rule.
"""

from __future__ import annotations

import ast
from typing import List

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
    tail_name,
)

_HTTP_VERBS = {"get", "post", "put", "patch", "delete", "head",
               "request"}

SCOPE = ("production_stack_tpu/router/**/*.py",)


def has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords) or any(
        kw.arg is None for kw in call.keywords  # **kwargs: trust it
    )


def is_network_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id == "ClientSession"
    if not isinstance(func, ast.Attribute):
        return False
    recv = tail_name(func.value)
    if recv == "requests" and func.attr in _HTTP_VERBS:
        return True
    if recv == "aiohttp" and func.attr == "ClientSession":
        return True
    if "session" in recv.lower() and func.attr in _HTTP_VERBS:
        return True
    return False


@rule("no-timeout",
      "outbound network calls in router/ need an explicit timeout=")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not is_network_call(node) or has_timeout_kw(node):
                continue
            findings.append(sf.finding(
                "no-timeout", node,
                "network call without explicit timeout= (bounded-wait "
                "guarantee, docs/resilience.md)"))
    return findings
