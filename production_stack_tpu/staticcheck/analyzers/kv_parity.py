"""Rule ``kv-parity``: every attention impl has bf16-vs-int8 parity
coverage.

The int8 KV cache (docs/kv_quantization.md) dequantizes inside each
attention implementation — XLA reference and both Pallas kernels. A
new impl that skips the QuantKV branch passes every full-precision
test and silently serves garbage under ``--kv-cache-dtype int8``.
Checks, statically:

- the ``ATTENTION_IMPLS`` registry literal in ops/attention.py
  exists, and for each registered ``(module, func)`` there is at
  least one test function under tests/ with ``int8``/``quant`` in
  its name that references ``func`` (name, attribute or string —
  covers getattr-by-name and parametrize ids);
- every ``ops/*attention*.py`` module defining a top-level
  ``paged_*`` entry point is registered — a new kernel module cannot
  dodge the lint by not registering (ring_attention consumes raw
  q/k/v, defines no ``paged_*``, and is gated off from int8 at
  config level).

The importlib half of the old lint (registry entries resolve to real
callables) stays in tests/test_kv_parity_coverage_lint.py — it needs
imports, which staticcheck deliberately never does.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    referenced_names,
    rule,
)

REGISTRY_FILE = "production_stack_tpu/ops/attention.py"
OPS_PATTERN = "production_stack_tpu/ops/*.py"
TEST_PATTERN = "tests/test_*.py"


def registry_entries(tree: ast.AST) -> Dict[str, Tuple[str, str]]:
    """The ATTENTION_IMPLS literal: {key: (module, func)}."""
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for target in stmt.targets:
            if (isinstance(target, ast.Name)
                    and target.id == "ATTENTION_IMPLS"):
                try:
                    value = ast.literal_eval(stmt.value)
                except (ValueError, TypeError):
                    return {}
                if isinstance(value, dict):
                    return {k: tuple(v) for k, v in value.items()}
    return {}


def int8_test_pools(project: Project) -> List[Tuple[str, set]]:
    """(test id, reference pool) for every int8/quant-named test."""
    out = []
    for sf in project.files(TEST_PATTERN):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_"):
                continue
            if "int8" not in node.name and "quant" not in node.name:
                continue
            out.append((f"{sf.relpath}::{node.name}",
                        referenced_names(node)))
    return out


@rule("kv-parity",
      "every registered attention impl has an int8 parity test")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    sf = project.source(REGISTRY_FILE)
    if sf is None or sf.tree is None:
        return [Finding(
            rule="kv-parity", path=REGISTRY_FILE, line=0,
            message="ops/attention.py missing — update "
                    "staticcheck/analyzers/kv_parity.py if the "
                    "registry moved")]
    impls = registry_entries(sf.tree)
    if not impls:
        findings.append(Finding(
            rule="kv-parity", path=REGISTRY_FILE, line=0,
            message="ATTENTION_IMPLS registry literal not found — "
                    "the int8 parity lint has nothing to walk"))
        return findings

    tests = int8_test_pools(project)
    if not tests:
        findings.append(Finding(
            rule="kv-parity", path="tests", line=0,
            message="no int8/quant-named test functions found under "
                    "tests/"))
    for key, (module, func_name) in sorted(impls.items()):
        if not any(func_name in refs for _, refs in tests):
            findings.append(Finding(
                rule="kv-parity", path=REGISTRY_FILE, line=0,
                message=f"{key} ({module}.{func_name}): no test "
                        "function with int8/quant in its name "
                        f"references {func_name} — add a parity test "
                        "over QuantKV pages"))

    registered_stems = {m.rsplit(".", 1)[-1] for m, _ in impls.values()}
    for ops_sf in project.files(OPS_PATTERN):
        if "attention" not in ops_sf.relpath or ops_sf.tree is None:
            continue
        stem = ops_sf.relpath.rsplit("/", 1)[-1][:-3]
        paged = any(isinstance(n, ast.FunctionDef)
                    and n.name.startswith("paged_")
                    for n in ops_sf.tree.body)
        if paged and stem not in registered_stems:
            findings.append(Finding(
                rule="kv-parity", path=ops_sf.relpath, line=0,
                message=f"ops/{stem}.py defines a paged_* entry "
                        "point but is not in ATTENTION_IMPLS — "
                        "register it so the int8 parity lint covers "
                        "it"))
    return findings
