"""Rule ``span-contract``: the engine span-event vocabulary is closed
and documented.

Engine spans (engine/tracing.py) are a string-keyed timeline: every
producer — the engine, the scheduler, the fake engine — names its
events with bare string literals, and every consumer (traceview, the
flight-recorder endpoints, dashboards grepping span logs) matches on
those names. Nothing at runtime rejects a typo'd or novel name; it
just becomes an event no tool recognizes. Checks:

- every string literal passed as the event name to an
  ``*.event(...)`` call anywhere in the package is a member of the
  ``SPAN_EVENTS`` tuple in engine/tracing.py;
- every ``SPAN_EVENTS`` name appears (backticked) inside the
  ``<!-- span-events:begin -->`` / ``<!-- span-events:end -->`` block
  of docs/observability.md, and every documented name is in
  ``SPAN_EVENTS`` — the docs table and the vocabulary cannot drift
  apart in either direction;
- the router span's JSON field set (the dict-literal keys in
  ``RequestSpan.to_json``, router/tracing.py) matches the
  ``<!-- router-span-fields:begin/end -->`` table in the same doc,
  both directions — span-log consumers (traceview, the slow archive,
  jq pipelines) key on those names.

Event-name call sites are recognized positionally: ``EngineSpan.event``
takes the name first, ``EngineTracer.event`` takes it second (after
the seq id), so the first string literal among a call's first two
positional arguments is taken as the name. Dynamic names (a variable)
are invisible to this rule by design — the one dynamic site is the
tracer's own pass-through.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set, Tuple

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
)

TRACING_FILE = "production_stack_tpu/engine/tracing.py"
ROUTER_TRACING_FILE = "production_stack_tpu/router/tracing.py"
DOCS_FILE = "docs/observability.md"

_BLOCK_RE = re.compile(
    r"<!--\s*span-events:begin\s*-->(.*?)<!--\s*span-events:end\s*-->",
    re.DOTALL)
_ROUTER_FIELDS_BLOCK_RE = re.compile(
    r"<!--\s*router-span-fields:begin\s*-->(.*?)"
    r"<!--\s*router-span-fields:end\s*-->",
    re.DOTALL)
_DOC_NAME_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`", re.MULTILINE)


def _event_name_sites(tree: ast.AST) -> List[Tuple[int, str]]:
    """(line, name) for each ``*.event(...)`` call whose event name is
    a string literal (first literal among the first two positional
    args)."""
    sites: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "event"):
            continue
        for arg in node.args[:2]:
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str):
                sites.append((node.lineno, arg.value))
                break
    return sites


def _router_span_fields(tree: ast.AST) -> Set[str]:
    """Dict-literal keys emitted by ``RequestSpan.to_json``."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name == "RequestSpan"):
            continue
        for fn in node.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "to_json"):
                continue
            keys: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Dict):
                    keys |= {k.value for k in sub.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str)}
            return keys
    return set()


def _span_events(tree: ast.AST) -> Set[str]:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if (isinstance(target, ast.Name)
                        and target.id == "SPAN_EVENTS"
                        and isinstance(stmt.value, (ast.Tuple, ast.List))):
                    return {el.value for el in stmt.value.elts
                            if isinstance(el, ast.Constant)
                            and isinstance(el.value, str)}
    return set()


@rule("span-contract",
      "span event names are in SPAN_EVENTS and documented in "
      "docs/observability.md")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    def missing(path):
        return Finding(
            rule="span-contract", path=path, line=0,
            message="span-contract surface file missing — if the "
                    "layer moved, update "
                    "staticcheck/analyzers/span_contract.py")

    tracing = project.source(TRACING_FILE)
    docs = project.source(DOCS_FILE)
    if tracing is None or tracing.tree is None:
        findings.append(missing(TRACING_FILE))
    if docs is None:
        findings.append(missing(DOCS_FILE))
    if findings:
        return findings

    vocab = _span_events(tracing.tree)
    if not vocab:
        return [Finding(
            rule="span-contract", path=TRACING_FILE, line=0,
            message="SPAN_EVENTS tuple not found (or empty) — the "
                    "span vocabulary must be a module-level literal")]

    for sf in project.files("production_stack_tpu/**/*.py"):
        if sf.tree is None:
            continue  # parse-error rule reports it
        for line, name in _event_name_sites(sf.tree):
            if name not in vocab:
                findings.append(sf.finding(
                    "span-contract", line,
                    f"span event '{name}' is not in SPAN_EVENTS "
                    "(engine/tracing.py) — add it to the vocabulary "
                    "and the docs/observability.md event table, or "
                    "fix the typo"))

    router_tracing = project.source(ROUTER_TRACING_FILE)
    if router_tracing is None or router_tracing.tree is None:
        findings.append(missing(ROUTER_TRACING_FILE))
    else:
        fields = _router_span_fields(router_tracing.tree)
        if not fields:
            findings.append(Finding(
                rule="span-contract", path=ROUTER_TRACING_FILE, line=0,
                message="RequestSpan.to_json dict literal not found — "
                        "the router span field set must be a literal "
                        "dict for the contract to see it"))
        else:
            fblock = _ROUTER_FIELDS_BLOCK_RE.search(docs.text)
            if fblock is None:
                findings.append(Finding(
                    rule="span-contract", path=DOCS_FILE, line=0,
                    message="docs/observability.md is missing the "
                            "<!-- router-span-fields:begin/end --> "
                            "marker block the router span field table "
                            "lives in"))
            else:
                doc_fields = set(_DOC_NAME_RE.findall(fblock.group(1)))
                for name in sorted(fields - doc_fields):
                    findings.append(Finding(
                        rule="span-contract", path=DOCS_FILE, line=0,
                        message=f"router span field '{name}' is "
                                "emitted by RequestSpan.to_json but "
                                "undocumented — add a row to the "
                                "router-span-fields table"))
                for name in sorted(doc_fields - fields):
                    findings.append(Finding(
                        rule="span-contract", path=DOCS_FILE, line=0,
                        message="docs/observability.md documents "
                                f"router span field '{name}' which "
                                "RequestSpan.to_json does not emit — "
                                "stale row or renamed field"))

    block = _BLOCK_RE.search(docs.text)
    if block is None:
        findings.append(Finding(
            rule="span-contract", path=DOCS_FILE, line=0,
            message="docs/observability.md is missing the "
                    "<!-- span-events:begin/end --> marker block the "
                    "event table lives in"))
        return findings
    documented = set(_DOC_NAME_RE.findall(block.group(1)))
    for name in sorted(vocab - documented):
        findings.append(Finding(
            rule="span-contract", path=DOCS_FILE, line=0,
            message=f"span event '{name}' is in SPAN_EVENTS but "
                    "undocumented — add a row to the span-events "
                    "table in docs/observability.md"))
    for name in sorted(documented - vocab):
        findings.append(Finding(
            rule="span-contract", path=DOCS_FILE, line=0,
            message=f"docs/observability.md documents span event "
                    f"'{name}' which is not in SPAN_EVENTS — stale "
                    "row or renamed event"))
    return findings
