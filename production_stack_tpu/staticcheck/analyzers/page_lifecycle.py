"""Rule ``page-lifecycle``: KV page allocations and AWAITING_KV parks
pair with their release on EVERY path, including exception edges.

This is the hazard class PRs 6, 7, 10, 12 and 13 each re-pinned with
a bespoke runtime ``test_*_leak`` regression: a scheduler/engine path
allocates KV pages (or parks a sequence in ``AWAITING_KV``) and an
early ``return``/``raise`` leaks the pages or strands the sequence.
Runtime tests only cover the paths someone thought to exercise; this
rule walks all of them over the CFG (staticcheck/cfg.py) with a
forward may-analysis (staticcheck/dataflow.py).

Two fact families, per function in engine/scheduler.py and
engine/engine.py:

- **orphan allocation**: ``x = <...>.allocate_pages(...)`` (or
  ``x = list(<...>.allocate_pages(...))``) binds fresh pages to a
  local. Direct attribute transfer (``seq.pages = ...``,
  ``seq.pages.extend(...)``) is immediately owned and never tracked.
  The fact dies at the first statement that *uses* the local — by
  then the pages are visible to whatever cleanup that code path owns
  (this deliberately checks "alloc reaches SOME consumer on every
  path", the pattern every historical leak violated, not full
  ownership transfer). A fact alive at the normal or exceptional exit
  is a leak finding at the allocation line.

- **orphan park**: a sequence enters ``AWAITING_KV`` (``.state =`` /
  ``.transition(...)`` / ``Sequence(state=...)``) and must reach a
  queue or terminal sink — ``add_sequence``, ``appendleft``/
  ``append``, ``abort_sequence``/``_finish``/``finish_handoff``,
  registration in an engine container, or ``pop``/``remove`` on the
  failure path — before every exit. Unlike allocations, only those
  sinks kill the fact: a tracer event reading ``seq.seq_id`` is not
  custody.

Exception edges use a narrow raises-predicate: ``raise``/``assert``,
any call inside a ``try`` body, and calls to the APIs that actually
throw on these paths (``allocate_pages``, ``add_sequence``) — so a
``logger.warning`` cannot manufacture a phantom leak path, and
``try/except OutOfPagesError`` cleanup is modeled exactly.

Waive a deliberate orphan with ``# lint: allow-page-lifecycle`` on
the allocation/park line.
"""

from __future__ import annotations

import ast
from typing import FrozenSet, List, Set, Tuple

from production_stack_tpu.staticcheck.cfg import (
    CFG,
    WithEnter,
    WithExit,
    contains_call,
    function_defs,
)
from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    rule,
    tail_name,
)
from production_stack_tpu.staticcheck import dataflow

SCOPE = (
    "production_stack_tpu/engine/scheduler.py",
    "production_stack_tpu/engine/engine.py",
)

# Calls that genuinely raise on the allocation/admission paths; plus
# raise/assert and anything already under a try, these are the only
# sources of exception edges for this rule.
RAISING_CALLS = {"allocate_pages", "add_sequence"}

# Custody sinks for a parked sequence (see module docstring).
PARK_SINKS = {"add_sequence", "append", "appendleft", "pop", "remove",
              "_finish", "abort_sequence", "finish_handoff"}

Fact = Tuple[str, str, int]  # ("alloc"|"park", var, lineno)


def _raises(stmt: ast.AST, in_try: bool) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if not contains_call(stmt):
        return False
    if in_try:
        return True
    return any(isinstance(n, ast.Call)
               and tail_name(n.func) in RAISING_CALLS
               for n in ast.walk(stmt))


def _alloc_target(stmt: ast.AST) -> str:
    """Name bound to a fresh allocation by this statement, or ''.
    Matches ``x = <...>.allocate_pages(...)`` and
    ``x = list/tuple(<...>.allocate_pages(...))``."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return ""
    target = stmt.targets[0]
    if not isinstance(target, ast.Name):
        return ""
    value = stmt.value
    if (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("list", "tuple") and value.args):
        value = value.args[0]
    if (isinstance(value, ast.Call)
            and tail_name(value.func) == "allocate_pages"):
        return target.id
    return ""


def _is_awaiting_kv(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and node.attr == "AWAITING_KV"
            and tail_name(node.value) == "SequenceState")


def _park_target(stmt: ast.AST) -> str:
    """Variable whose sequence this statement parks in AWAITING_KV,
    or ''."""
    # x.state = SequenceState.AWAITING_KV  /  x.transition(AWAITING_KV)
    if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Attribute)
            and stmt.targets[0].attr == "state"
            and isinstance(stmt.targets[0].value, ast.Name)
            and _is_awaiting_kv(stmt.value)):
        return stmt.targets[0].value.id
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        if (tail_name(call.func) == "transition"
                and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.args and _is_awaiting_kv(call.args[0])):
            return call.func.value.id
    # x = Sequence(..., state=SequenceState.AWAITING_KV, ...)
    if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)
            and tail_name(stmt.value.func) == "Sequence"):
        for kw in stmt.value.keywords:
            if kw.arg == "state" and _is_awaiting_kv(kw.value):
                return stmt.targets[0].id
    return ""


def _root_name(node: ast.AST) -> str:
    """Leftmost Name of an attribute/subscript chain ('' otherwise)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _names_read(el) -> Set[str]:
    """Names this CFG element *uses* — the allocation-consumption net.
    Loop heads are restricted to their iterable (a ``while x:`` test
    alone doesn't take custody of x)."""
    if isinstance(el, (WithEnter, WithExit)):
        return {n.id for n in ast.walk(el.node)
                if isinstance(n, ast.Name)}
    if isinstance(el, ast.While):
        return set()
    if isinstance(el, (ast.For, ast.AsyncFor)):
        return {n.id for n in ast.walk(el.iter)
                if isinstance(n, ast.Name)}
    if isinstance(el, ast.AST):
        return {n.id for n in ast.walk(el) if isinstance(n, ast.Name)}
    return set()


def _park_sunk_vars(el) -> Set[str]:
    """Variables a custody sink consumes in this element."""
    out: Set[str] = set()
    if not isinstance(el, ast.AST):
        return out
    for node in ast.walk(el):
        if (isinstance(node, ast.Call)
                and tail_name(node.func) in PARK_SINKS):
            for arg in node.args:
                root = _root_name(arg)
                if root:
                    out.add(root)
        # self.sequences[x.seq_id] = x : container registration.
        elif (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Name)
                and any(isinstance(t, (ast.Subscript, ast.Attribute))
                        for t in node.targets)):
            out.add(node.value.id)
    return out


def _transfer(state: FrozenSet[Fact], el, _kind) -> FrozenSet[Fact]:
    reads = _names_read(el)
    sunk = _park_sunk_vars(el)
    alloc_var = _alloc_target(el) if isinstance(el, ast.AST) else ""
    park_var = _park_target(el) if isinstance(el, ast.AST) else ""
    out = set()
    for fact in state:
        kind, var, _line = fact
        if kind == "alloc":
            if var in reads:
                continue  # consumed (or rebound) here
        else:  # park
            if var in sunk:
                continue
            if _rebinds(el, var) and park_var != var:
                continue  # rebound to something else
        out.add(fact)
    if alloc_var:
        out.add(("alloc", alloc_var, el.lineno))
    if park_var:
        out.add(("park", park_var, el.lineno))
    return frozenset(out)


def _rebinds(el, var: str) -> bool:
    if not isinstance(el, ast.Assign):
        return False
    return any(isinstance(t, ast.Name) and t.id == var
               for t in el.targets)


@rule("page-lifecycle",
      "KV page allocations / AWAITING_KV parks reach their paired "
      "release or queue sink on every path (incl. exception edges)")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue  # parse-error rule reports it
        for fn in function_defs(sf.tree):
            # Cheap prefilter: only functions that allocate or park.
            if not any(_alloc_target(s) or _park_target(s)
                       for s in ast.walk(fn)
                       if isinstance(s, ast.stmt)):
                continue
            cfg = CFG(fn, raises=_raises)
            exits = dataflow.facts_at_exit(
                cfg, frozenset(), _transfer, join="union")
            leaked: Set[Tuple[Fact, str]] = set()
            for exit_name, facts in exits.items():
                for fact in facts:
                    leaked.add((fact, exit_name))
            reported = set()
            for (kind, var, line), exit_name in sorted(leaked):
                if (kind, var, line) in reported:
                    continue  # one finding per site, not per exit
                reported.add((kind, var, line))
                how = ("function exit" if exit_name == "exit"
                       else "exception path")
                if kind == "alloc":
                    findings.append(sf.finding(
                        "page-lifecycle", line,
                        f"KV pages allocated into '{var}' in {fn.name} "
                        f"can leak: a {how} is reachable before "
                        "anything consumes them — free_sequence them "
                        "or transfer ownership on that path"))
                else:
                    findings.append(sf.finding(
                        "page-lifecycle", line,
                        f"sequence '{var}' parked in AWAITING_KV in "
                        f"{fn.name} can be stranded: a {how} is "
                        "reachable before any queue/abort sink takes "
                        "custody — the request would never complete"))
    return findings
