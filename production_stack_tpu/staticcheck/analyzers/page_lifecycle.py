"""Rule ``page-lifecycle``: KV page allocations and AWAITING_KV parks
pair with their release on EVERY path, including exception edges.

This is the hazard class PRs 6, 7, 10, 12 and 13 each re-pinned with
a bespoke runtime ``test_*_leak`` regression: a scheduler/engine path
allocates KV pages (or parks a sequence in ``AWAITING_KV``) and an
early ``return``/``raise`` leaks the pages or strands the sequence.
Runtime tests only cover the paths someone thought to exercise; this
rule walks all of them over the CFG (staticcheck/cfg.py) with a
forward may-analysis (staticcheck/dataflow.py).

Two fact families, per function in engine/scheduler.py and
engine/engine.py:

- **orphan allocation**: ``x = <...>.allocate_pages(...)`` (or
  ``x = list(<...>.allocate_pages(...))``, or a call to a helper the
  summary engine proves *returns* a fresh allocation) binds fresh
  pages to a local. Direct attribute transfer (``seq.pages = ...``,
  ``seq.pages.extend(...)``) is immediately owned and never tracked.
  The fact dies at the first statement that *takes custody* of the
  local. Custody used to be "any read"; since the interprocedural
  layer (PR 20) a read that provably cannot retain the pages — a
  ``len()``-class builtin, or a bare name passed to a *resolved*
  callee whose summary says that parameter never escapes — keeps the
  fact alive, so "the callee consumed it" is now proved, not
  assumed. An unresolved callee still counts as custody
  (conservative: it can never manufacture a finding). A fact alive
  at the normal or exceptional exit is a leak finding at the
  allocation line.

- **orphan park**: a sequence enters ``AWAITING_KV`` (``.state =`` /
  ``.transition(...)`` / ``Sequence(state=...)``) and must reach a
  queue or terminal sink — ``add_sequence``, ``appendleft``/
  ``append``, ``abort_sequence``/``_finish``/``finish_handoff``,
  registration in an engine container, ``pop``/``remove`` on the
  failure path — or a resolved callee that takes custody of the
  sequence, before every exit. A tracer event reading ``seq.seq_id``
  is still not custody.

Exception edges: ``raise``/``assert``, any call inside a ``try``
body, calls to the known-raising cache APIs (``allocate_pages``,
``add_sequence``), **and any call whose resolved callee's may-raise
summary is nonempty** — so a helper that raises three frames down
creates the exception path it really has, while a ``logger.warning``
(unresolved) still cannot manufacture a phantom leak path.

Waive a deliberate orphan with ``# lint: allow-page-lifecycle`` on
the allocation/park line.
"""

from __future__ import annotations

import ast
import collections
from typing import Dict, FrozenSet, List, Set, Tuple

from production_stack_tpu.staticcheck.cfg import (
    CFG,
    WithEnter,
    WithExit,
    contains_call,
    function_defs,
)
from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    recv_name,
    rule,
    tail_name,
)
from production_stack_tpu.staticcheck import (
    callgraph,
    dataflow,
    summaries,
)

SCOPE = (
    "production_stack_tpu/engine/scheduler.py",
    "production_stack_tpu/engine/engine.py",
)

# Calls that genuinely raise on the allocation/admission paths even
# when the callee cannot be resolved (cache-object methods).
RAISING_CALLS = {"allocate_pages", "add_sequence"}

# Custody sinks for a parked sequence (see module docstring).
PARK_SINKS = {"add_sequence", "append", "appendleft", "pop", "remove",
              "_finish", "abort_sequence", "finish_handoff"}

Fact = Tuple[str, str, int]  # ("alloc"|"park", var, lineno)


class _FnContext:
    """Everything the transfer/raises closures need for one function:
    its call edges keyed by call-node identity, plus the summary
    table."""

    def __init__(self, project: Project, sf, fn):
        graph = callgraph.for_project(project)
        self.sums = summaries.for_project(project)
        info = graph.function_at(sf.relpath, fn)
        self.edges_by_call: Dict[int, callgraph.CallEdge] = (
            {id(e.call): e for e in graph.edges_from(info.qual)}
            if info is not None else {})

    def callee_summary(self, call: ast.Call):
        edge = self.edges_by_call.get(id(call))
        if edge is None or edge.callee is None:
            return None, None
        return edge, self.sums.get(edge.callee)

    def call_may_raise(self, call: ast.Call) -> bool:
        _edge, summ = self.callee_summary(call)
        return summ is not None and bool(summ.may_raise)

    def noncustodial_names(self, el) -> Set[str]:
        """Names whose every occurrence in ``el`` is a provably
        non-custodial read: an argument of a read-only builtin, or a
        bare name passed to a resolved callee whose summary says that
        parameter never escapes the callee's frame."""
        if not isinstance(el, ast.AST):
            return set()
        total = collections.Counter(
            n.id for n in ast.walk(el) if isinstance(n, ast.Name))
        safe: collections.Counter = collections.Counter()
        for call in ast.walk(el):
            if not isinstance(call, ast.Call):
                continue
            edge = self.edges_by_call.get(id(call))
            if edge is None:
                continue
            if edge.kind == "builtin" and \
                    edge.target_text in summaries.READONLY_BUILTINS:
                for arg in call.args:
                    if isinstance(arg, ast.Name):
                        safe[arg.id] += 1
                continue
            if edge.callee is None:
                continue
            callee_sum = self.sums.get(edge.callee)
            for pos, arg in enumerate(call.args):
                if not isinstance(arg, ast.Name):
                    continue
                param = self.sums.callee_param_for_arg(edge, pos,
                                                       None)
                if param is not None and \
                        param not in callee_sum.consumed_params:
                    safe[arg.id] += 1
            for kw in call.keywords:
                if not isinstance(kw.value, ast.Name) or \
                        kw.arg is None:
                    continue
                param = self.sums.callee_param_for_arg(edge, 0,
                                                       kw.arg)
                if param is not None and \
                        param not in callee_sum.consumed_params:
                    safe[kw.value.id] += 1
        return {name for name, count in total.items()
                if safe.get(name, 0) >= count}

    def custody_transfers(self, el) -> Set[str]:
        """Names handed to a resolved callee that (possibly) takes
        custody — kills park facts the way an explicit sink does."""
        out: Set[str] = set()
        if not isinstance(el, ast.AST):
            return out
        for call in ast.walk(el):
            if not isinstance(call, ast.Call):
                continue
            edge = self.edges_by_call.get(id(call))
            if edge is None or edge.callee is None:
                continue
            callee_sum = self.sums.get(edge.callee)
            for pos, arg in enumerate(call.args):
                if not isinstance(arg, ast.Name):
                    continue
                param = self.sums.callee_param_for_arg(edge, pos,
                                                       None)
                if param is not None and \
                        param in callee_sum.consumed_params:
                    out.add(arg.id)
        return out

    def alloc_via_callee(self, value: ast.Call) -> bool:
        """Is this call a helper the summaries prove returns a fresh
        allocation?"""
        _edge, summ = self.callee_summary(value)
        return summ is not None and summ.returns_alloc


def _raises_for(ctx: _FnContext):
    def _raises(stmt: ast.AST, in_try: bool) -> bool:
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            return True
        if not contains_call(stmt):
            return False
        if in_try:
            return True
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if tail_name(node.func) in RAISING_CALLS:
                return True
            if ctx.call_may_raise(node):
                return True
        return False
    return _raises


def _alloc_target(stmt: ast.AST, ctx: _FnContext = None) -> str:
    """Name bound to a fresh allocation by this statement, or ''.
    Matches ``x = <...>.allocate_pages(...)``,
    ``x = list/tuple(<...>.allocate_pages(...))`` and — with a
    context — ``x = self._helper(...)`` where the helper's summary
    says it returns a fresh allocation."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return ""
    target = stmt.targets[0]
    if not isinstance(target, ast.Name):
        return ""
    value = stmt.value
    if (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("list", "tuple") and value.args):
        value = value.args[0]
    if not isinstance(value, ast.Call):
        return ""
    if tail_name(value.func) == "allocate_pages":
        return target.id
    if ctx is not None and ctx.alloc_via_callee(value):
        return target.id
    return ""


def _is_awaiting_kv(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and node.attr == "AWAITING_KV"
            and tail_name(node.value) == "SequenceState")


def _park_target(stmt: ast.AST) -> str:
    """Variable whose sequence this statement parks in AWAITING_KV,
    or ''."""
    # x.state = SequenceState.AWAITING_KV  /  x.transition(AWAITING_KV)
    if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Attribute)
            and stmt.targets[0].attr == "state"
            and isinstance(stmt.targets[0].value, ast.Name)
            and _is_awaiting_kv(stmt.value)):
        return stmt.targets[0].value.id
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        if (tail_name(call.func) == "transition"
                and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.args and _is_awaiting_kv(call.args[0])):
            return call.func.value.id
    # x = Sequence(..., state=SequenceState.AWAITING_KV, ...)
    if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)
            and tail_name(stmt.value.func) == "Sequence"):
        for kw in stmt.value.keywords:
            if kw.arg == "state" and _is_awaiting_kv(kw.value):
                return stmt.targets[0].id
    return ""


def _root_name(node: ast.AST) -> str:
    """Leftmost Name of an attribute/subscript chain ('' otherwise)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _names_read(el) -> Set[str]:
    """Names this CFG element *uses* — the allocation-consumption net.
    Loop heads are restricted to their iterable (a ``while x:`` test
    alone doesn't take custody of x)."""
    if isinstance(el, (WithEnter, WithExit)):
        return {n.id for n in ast.walk(el.node)
                if isinstance(n, ast.Name)}
    if isinstance(el, ast.While):
        return set()
    if isinstance(el, (ast.For, ast.AsyncFor)):
        return {n.id for n in ast.walk(el.iter)
                if isinstance(n, ast.Name)}
    if isinstance(el, ast.AST):
        return {n.id for n in ast.walk(el) if isinstance(n, ast.Name)}
    return set()


def _park_sunk_vars(el) -> Set[str]:
    """Variables a custody sink consumes in this element."""
    out: Set[str] = set()
    if not isinstance(el, ast.AST):
        return out
    for node in ast.walk(el):
        if (isinstance(node, ast.Call)
                and tail_name(node.func) in PARK_SINKS):
            for arg in node.args:
                root = _root_name(arg)
                if root:
                    out.add(root)
        # self.sequences[x.seq_id] = x : container registration.
        elif (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Name)
                and any(isinstance(t, (ast.Subscript, ast.Attribute))
                        for t in node.targets)):
            out.add(node.value.id)
    return out


def _transfer_for(ctx: _FnContext):
    def _transfer(state: FrozenSet[Fact], el, _kind
                  ) -> FrozenSet[Fact]:
        reads = _names_read(el)
        if reads:
            reads = reads - ctx.noncustodial_names(el)
        sunk = _park_sunk_vars(el) | ctx.custody_transfers(el)
        alloc_var = _alloc_target(el, ctx) if isinstance(el, ast.AST) \
            else ""
        park_var = _park_target(el) if isinstance(el, ast.AST) else ""
        out = set()
        for fact in state:
            kind, var, _line = fact
            if kind == "alloc":
                if var in reads:
                    continue  # custody taken (or rebound) here
            else:  # park
                if var in sunk:
                    continue
                if _rebinds(el, var) and park_var != var:
                    continue  # rebound to something else
            out.add(fact)
        if alloc_var:
            out.add(("alloc", alloc_var, el.lineno))
        if park_var:
            out.add(("park", park_var, el.lineno))
        return frozenset(out)
    return _transfer


def _rebinds(el, var: str) -> bool:
    if not isinstance(el, ast.Assign):
        return False
    return any(isinstance(t, ast.Name) and t.id == var
               for t in el.targets)


@rule("page-lifecycle",
      "KV page allocations / AWAITING_KV parks reach their paired "
      "release or queue sink on every path (incl. exception edges); "
      "callee custody proved via summaries (transitive)",
      interprocedural=True)
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue  # parse-error rule reports it
        for fn in function_defs(sf.tree):
            ctx = _FnContext(project, sf, fn)
            # Cheap prefilter: only functions that allocate or park.
            if not any(_alloc_target(s, ctx) or _park_target(s)
                       for s in ast.walk(fn)
                       if isinstance(s, ast.stmt)):
                continue
            cfg = CFG(fn, raises=_raises_for(ctx))
            exits = dataflow.facts_at_exit(
                cfg, frozenset(), _transfer_for(ctx), join="union")
            leaked: Set[Tuple[Fact, str]] = set()
            for exit_name, facts in exits.items():
                for fact in facts:
                    leaked.add((fact, exit_name))
            reported = set()
            for (kind, var, line), exit_name in sorted(leaked):
                if (kind, var, line) in reported:
                    continue  # one finding per site, not per exit
                reported.add((kind, var, line))
                how = ("function exit" if exit_name == "exit"
                       else "exception path")
                if kind == "alloc":
                    findings.append(sf.finding(
                        "page-lifecycle", line,
                        f"KV pages allocated into '{var}' in {fn.name} "
                        f"can leak: a {how} is reachable before "
                        "anything takes custody of them — "
                        "free_sequence them or transfer ownership on "
                        "that path"))
                else:
                    findings.append(sf.finding(
                        "page-lifecycle", line,
                        f"sequence '{var}' parked in AWAITING_KV in "
                        f"{fn.name} can be stranded: a {how} is "
                        "reachable before any queue/abort sink takes "
                        "custody — the request would never complete"))
    return findings
