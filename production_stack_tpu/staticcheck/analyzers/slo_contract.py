"""Rule ``slo-contract``: every SLO spec field is documented.

The SLO ledger's spec (``--slo-spec``, obs/slo.py) is the operator's
declarative surface for "what counts as good": per-class and
per-model latency targets plus objective fractions. Like the
config-contract rule for engine/fleet knobs, a spec field an
operator cannot find in the docs is a knob that effectively does not
exist — and a doc row for a removed field is a trap. Checks that
every dataclass field of ``SLOTarget`` and ``SLOSpec`` appears
backticked somewhere in docs/observability.md.
"""

from __future__ import annotations

import ast
from typing import List, Set

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
)

SLO_FILE = "production_stack_tpu/obs/slo.py"
DOCS_FILE = "docs/observability.md"
SPEC_CLASSES = ("SLOTarget", "SLOSpec")


def _dataclass_fields(tree: ast.AST, class_name: str) -> Set[str]:
    """Annotated field names of one dataclass."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {stmt.target.id for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)}
    return set()


@rule("slo-contract",
      "every SLOSpec / SLOTarget field is documented in "
      "docs/observability.md")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    def missing(path):
        return Finding(
            rule="slo-contract", path=path, line=0,
            message="slo-contract surface file missing — if the layer "
                    "moved, update "
                    "staticcheck/analyzers/slo_contract.py")

    slo = project.source(SLO_FILE)
    docs = project.source(DOCS_FILE)
    if slo is None or slo.tree is None:
        findings.append(missing(SLO_FILE))
    if docs is None:
        findings.append(missing(DOCS_FILE))
    if findings:
        return findings

    for cls in SPEC_CLASSES:
        fields = _dataclass_fields(slo.tree, cls)
        if not fields:
            findings.append(Finding(
                rule="slo-contract", path=SLO_FILE, line=0,
                message=f"dataclass {cls} not found (or has no "
                        "annotated fields) — the SLO spec surface "
                        "must stay in obs/slo.py"))
            continue
        for name in sorted(fields):
            if f"`{name}`" not in docs.text:
                findings.append(Finding(
                    rule="slo-contract", path=DOCS_FILE, line=0,
                    message=f"SLO spec field {cls}.{name} is not "
                            "documented in docs/observability.md — "
                            "every --slo-spec field must appear "
                            "backticked in the SLO ledger section"))
    return findings
