"""Rule ``metrics-contract``: engine metrics must round-trip through
the router, or be dropped *explicitly*.

The metric pipeline crosses three layers that only agree by string
convention: the engine renders Prometheus text (engine/metrics.py +
engine/server.py /metrics), the router scraper parses the names it
knows (router/stats/engine_stats.py ``_METRIC_MAP``), and the metrics
service re-exports the scraped values as labeled gauges
(router/services/metrics_service.py ``refresh_gauges``). PRs 2-4 each
added engine gauges by hand in all three places; one forgotten edit
means a dashboard silently reads 0 forever. Checks:

- every ``vllm:*`` name the engine emits is either a ``_METRIC_MAP``
  key / specially-parsed name in engine_stats.py, or listed in its
  ``_ROUTER_UNSCRAPED`` set (the explicit "cluster Prometheus reads
  this directly, the router does not" marker);
- every name the scraper reads is actually emitted by the engine
  (no scraping ghosts);
- every ``_METRIC_MAP`` target attribute is a real ``EngineStats``
  field;
- every ``EngineStats`` field is consumed somewhere in
  metrics_service.py (scraped-but-never-re-exported drift).

These are cross-file contract findings (line 0 on the file that must
change); the fix is code or an explicit marker, not a waiver comment.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
    string_constants,
)

ENGINE_FILES = (
    "production_stack_tpu/engine/metrics.py",
    "production_stack_tpu/engine/server.py",
)
SCRAPER_FILE = "production_stack_tpu/router/stats/engine_stats.py"
SERVICE_FILE = "production_stack_tpu/router/services/metrics_service.py"

_NAME_RE = re.compile(r"vllm:[A-Za-z0-9_]+")


def _metric_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for s in string_constants(tree):
        names.update(_NAME_RE.findall(s))
    return names


def _assigned_literal(tree: ast.AST, name: str):
    """The ast node assigned to module-level ``name`` (None if
    absent)."""
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if isinstance(target, ast.Name) and target.id == name:
                    return stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            if (isinstance(stmt.target, ast.Name)
                    and stmt.target.id == name):
                return stmt.value
    return None


def _dict_str_entries(node) -> dict:
    """{key: value} for the string-literal entries of a dict node."""
    out = {}
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, str):
                    out[k.value] = v.value
    return out


def _str_elements(node) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(
                    el.value, str):
                out.add(el.value)
    elif isinstance(node, ast.Call):  # frozenset({...}) / set([...])
        for arg in node.args:
            out |= _str_elements(arg)
    return out


def _class_fields(tree: ast.AST, class_name: str) -> Set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {stmt.target.id for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)}
    return set()


def _attribute_tails(tree: ast.AST) -> Set[str]:
    return {node.attr for node in ast.walk(tree)
            if isinstance(node, ast.Attribute)}


@rule("metrics-contract",
      "engine metrics round-trip scraper and re-export, or are "
      "dropped explicitly")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    def missing(path):
        return Finding(
            rule="metrics-contract", path=path, line=0,
            message="metrics-contract surface file missing — if the "
                    "layer moved, update "
                    "staticcheck/analyzers/metrics_contract.py")

    emitted: Set[str] = set()
    for path in ENGINE_FILES:
        sf = project.source(path)
        if sf is None or sf.tree is None:
            findings.append(missing(path))
            continue
        emitted |= _metric_names(sf.tree)

    scraper = project.source(SCRAPER_FILE)
    service = project.source(SERVICE_FILE)
    if scraper is None or scraper.tree is None:
        findings.append(missing(SCRAPER_FILE))
    if service is None or service.tree is None:
        findings.append(missing(SERVICE_FILE))
    if findings:
        return findings

    metric_map = _dict_str_entries(
        _assigned_literal(scraper.tree, "_METRIC_MAP"))
    unscraped = _str_elements(
        _assigned_literal(scraper.tree, "_ROUTER_UNSCRAPED"))
    # Names the scraper handles outside _METRIC_MAP (e.g. the labeled
    # kv-dtype gauge special-cased in from_prometheus_text) still
    # appear as string literals in the module.
    scraped = _metric_names(scraper.tree)
    stats_fields = _class_fields(scraper.tree, "EngineStats")

    for name in sorted(emitted - scraped - unscraped):
        findings.append(Finding(
            rule="metrics-contract", path=SCRAPER_FILE, line=0,
            message=f"engine emits {name} but the router scraper "
                    "neither reads it (_METRIC_MAP / "
                    "from_prometheus_text) nor lists it in "
                    "_ROUTER_UNSCRAPED — add it to one so the drop "
                    "is a decision, not drift"))
    for name in sorted(scraped - emitted - unscraped):
        findings.append(Finding(
            rule="metrics-contract", path=SCRAPER_FILE, line=0,
            message=f"router scraper references {name} but no engine "
                    "file emits it — stale map entry or renamed "
                    "metric"))
    for name, attr in sorted(metric_map.items()):
        if attr not in stats_fields:
            findings.append(Finding(
                rule="metrics-contract", path=SCRAPER_FILE, line=0,
                message=f"_METRIC_MAP maps {name} to EngineStats."
                        f"{attr}, which is not a declared field"))
    consumed = _attribute_tails(service.tree)
    for attr in sorted(stats_fields - consumed):
        findings.append(Finding(
            rule="metrics-contract", path=SERVICE_FILE, line=0,
            message=f"EngineStats.{attr} is scraped but never "
                    "consumed in metrics_service.py — the value dies "
                    "in the router instead of being re-exported"))
    return findings
