"""Rule ``state-machine``: the SequenceState lifecycle is a declared
table, not folklore.

``SEQUENCE_TRANSITIONS`` in engine/sequence.py is the single source
of truth for how a sequence may move between states; the runtime
guard is ``Sequence.transition`` (raises on an untabled pair). This
rule makes the table enforceable at lint time and keeps the docs
honest, both directions, like ``span-contract``:

- engine/sequence.py must define ``SEQUENCE_TRANSITIONS`` as a
  module-level literal of ``(from, to, rationale)`` rows (``"new"``
  rows declare sanctioned constructor states) and the
  ``Sequence.transition`` method;
- any direct ``<x>.state = SequenceState.<S>`` write anywhere in the
  package outside ``Sequence.transition`` itself bypasses the runtime
  validation and is a finding;
- ``Sequence(..., state=SequenceState.<S>)`` constructor calls must
  use a state with a ``("new", <S>)`` row;
- ``.transition(SequenceState.<S>)`` calls must target a state that
  appears as a destination in the table (the exact edge is checked at
  runtime; lint catches states that are never a legal destination);
- every table row is rendered (backticked ``| `from` | `to` |``) in
  the ``<!-- sequence-states:begin/end -->`` block of
  docs/sequence_states.md, and every documented row is in the table.

Waiver: ``# lint: allow-state-machine`` on the flagged line (e.g. a
test helper that deliberately corrupts state).
"""

from __future__ import annotations

import ast
import re
from typing import List, Set, Tuple

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    tail_name,
    rule,
)

SEQUENCE_FILE = "production_stack_tpu/engine/sequence.py"
DOCS_FILE = "docs/sequence_states.md"

_BLOCK_RE = re.compile(
    r"<!--\s*sequence-states:begin\s*-->(.*?)"
    r"<!--\s*sequence-states:end\s*-->",
    re.DOTALL)
_DOC_ROW_RE = re.compile(
    r"^\|\s*`([a-z_]+)`\s*\|\s*`([a-z_]+)`", re.MULTILINE)


def _transition_table(tree: ast.AST) -> Set[Tuple[str, str]]:
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for target in stmt.targets:
            if (isinstance(target, ast.Name)
                    and target.id == "SEQUENCE_TRANSITIONS"
                    and isinstance(stmt.value, (ast.Tuple, ast.List))):
                rows = set()
                for el in stmt.value.elts:
                    if (isinstance(el, (ast.Tuple, ast.List))
                            and len(el.elts) >= 2
                            and all(isinstance(e, ast.Constant)
                                    for e in el.elts[:2])):
                        rows.add((el.elts[0].value, el.elts[1].value))
                return rows
    return set()


def _enum_values(tree: ast.AST) -> dict:
    """{member name: value} of the SequenceState enum."""
    out = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef) and stmt.name == "SequenceState":
            for sub in stmt.body:
                if (isinstance(sub, ast.Assign)
                        and isinstance(sub.targets[0], ast.Name)
                        and isinstance(sub.value, ast.Constant)):
                    out[sub.targets[0].id] = sub.value.value
    return out


def _in_transition_method(tree: ast.AST, lineno: int) -> bool:
    for node in ast.walk(tree):
        if (isinstance(node, ast.FunctionDef)
                and node.name == "transition"):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                return True
    return False


def _state_member(node: ast.AST) -> str:
    """'X' for a ``SequenceState.X`` reference, else ''."""
    if (isinstance(node, ast.Attribute)
            and tail_name(node.value) == "SequenceState"):
        return node.attr
    return ""


@rule("state-machine",
      "SequenceState changes go through Sequence.transition and match "
      "the declared SEQUENCE_TRANSITIONS table (docs in sync)")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    seq_sf = project.source(SEQUENCE_FILE)
    docs = project.source(DOCS_FILE)
    if seq_sf is None or seq_sf.tree is None:
        return [Finding(
            rule="state-machine", path=SEQUENCE_FILE, line=0,
            message="state-machine surface file missing — if the "
                    "sequence module moved, update "
                    "staticcheck/analyzers/state_machine.py")]
    table = _transition_table(seq_sf.tree)
    if not table:
        return [Finding(
            rule="state-machine", path=SEQUENCE_FILE, line=0,
            message="SEQUENCE_TRANSITIONS table not found (or empty) "
                    "— the lifecycle must be a module-level literal "
                    "of (from, to, rationale) rows")]
    enum_values = _enum_values(seq_sf.tree)
    value_of = {name: val for name, val in enum_values.items()}
    initial = {dst for src, dst in table if src == "new"}
    destinations = {dst for _src, dst in table}

    # Rows must name real states (typo in the table itself).
    known = set(enum_values.values()) | {"new"}
    for src, dst in sorted(table):
        for name in (src, dst):
            if name not in known:
                findings.append(Finding(
                    rule="state-machine", path=SEQUENCE_FILE, line=0,
                    message=f"SEQUENCE_TRANSITIONS row ('{src}', "
                            f"'{dst}') names '{name}', which is not a "
                            "SequenceState value"))

    if not any(isinstance(n, ast.FunctionDef) and n.name == "transition"
               for n in ast.walk(seq_sf.tree)):
        findings.append(Finding(
            rule="state-machine", path=SEQUENCE_FILE, line=0,
            message="Sequence.transition method not found — the "
                    "runtime half of the state-machine contract is "
                    "missing"))

    for sf in project.files("production_stack_tpu/**/*.py"):
        if sf.tree is None:
            continue  # parse-error rule reports it
        for node in ast.walk(sf.tree):
            # Direct .state = SequenceState.X writes.
            if isinstance(node, ast.Assign):
                member = ""
                if _state_member(node.value):
                    member = _state_member(node.value)
                elif isinstance(node.value, ast.IfExp):
                    # x.state = A if cond else B
                    if (_state_member(node.value.body)
                            or _state_member(node.value.orelse)):
                        member = (_state_member(node.value.body)
                                  or _state_member(node.value.orelse))
                if member:
                    for target in node.targets:
                        if (isinstance(target, ast.Attribute)
                                and target.attr == "state"):
                            if (sf.relpath == SEQUENCE_FILE
                                    and _in_transition_method(
                                        sf.tree, node.lineno)):
                                continue
                            findings.append(sf.finding(
                                "state-machine", node,
                                "direct .state write bypasses "
                                "Sequence.transition() and its "
                                "SEQUENCE_TRANSITIONS validation — "
                                "call transition() instead"))
            # Sequence(state=...) constructor states.
            elif (isinstance(node, ast.Call)
                    and tail_name(node.func) == "Sequence"):
                for kw in node.keywords:
                    member = _state_member(kw.value) if kw.arg == "state" \
                        else ""
                    if member and value_of.get(member) not in initial:
                        findings.append(sf.finding(
                            "state-machine", node,
                            f"Sequence constructed in state "
                            f"'{value_of.get(member, member)}' which "
                            "has no ('new', ...) row in "
                            "SEQUENCE_TRANSITIONS — not a sanctioned "
                            "initial state"))
            # transition(SequenceState.X) destinations.
            elif (isinstance(node, ast.Call)
                    and tail_name(node.func) == "transition"
                    and node.args):
                member = _state_member(node.args[0])
                if member and value_of.get(member) not in destinations:
                    findings.append(sf.finding(
                        "state-machine", node,
                        f"transition to '{value_of.get(member, member)}'"
                        " which is never a destination in "
                        "SEQUENCE_TRANSITIONS — untabled move (would "
                        "raise at runtime)"))

    # Docs contract, both directions.
    if docs is None:
        findings.append(Finding(
            rule="state-machine", path=DOCS_FILE, line=0,
            message="docs/sequence_states.md missing — the transition "
                    "table must be rendered for humans too"))
        return findings
    block = _BLOCK_RE.search(docs.text)
    if block is None:
        findings.append(Finding(
            rule="state-machine", path=DOCS_FILE, line=0,
            message="docs/sequence_states.md is missing the "
                    "<!-- sequence-states:begin/end --> marker block "
                    "the transition table lives in"))
        return findings
    documented = set(_DOC_ROW_RE.findall(block.group(1)))
    for src, dst in sorted(table - documented):
        findings.append(Finding(
            rule="state-machine", path=DOCS_FILE, line=0,
            message=f"transition ('{src}' -> '{dst}') is in "
                    "SEQUENCE_TRANSITIONS but undocumented — add a "
                    "row to the table in docs/sequence_states.md"))
    for src, dst in sorted(documented - table):
        findings.append(Finding(
            rule="state-machine", path=DOCS_FILE, line=0,
            message=f"docs/sequence_states.md documents transition "
                    f"('{src}' -> '{dst}') which is not in "
                    "SEQUENCE_TRANSITIONS — stale row or missing "
                    "table entry"))
    return findings
