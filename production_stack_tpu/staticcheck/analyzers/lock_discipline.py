"""Rule ``lock-discipline``: shared mutable state touched from async
paths is actually guarded — and guards don't block the loop.

The engine server and the router are single-process asyncio programs
whose handlers interleave at every ``await``. Two hazard classes,
both found over the CFG (staticcheck/cfg.py) with a lock-held
lattice (facts = names of locks currently held, gen on ``with``-entry
/ ``.acquire()``, kill on ``with``-exit / ``.release()``):

- **await under a sync lock**: an ``await`` while a *synchronous*
  lock (``with self._lock:``, ``threading.Lock``) is held parks the
  entire event loop on whatever the awaited task needs — classic
  asyncio deadlock/latency bomb. ``async with`` locks are fine and
  not flagged.

- **unguarded cross-handler read-modify-write**: an instance
  attribute that ≥2 ``async def`` methods of the same class
  read-modify-write (``self.x += ...`` or ``self.x = f(self.x)``)
  without one lock held in common at every such site. Plain
  assignments and single-method mutations are not flagged —
  ``self.x = val`` is atomic under asyncio; it is the
  read-then-write-back pattern that loses updates when the methods
  interleave.

A lock is recognized lexically: the guarded expression's dotted tail
contains ``lock`` (``self._lock``, ``write_lock``, ``self.mu.lock``).
Waive a reviewed site with ``# lint: allow-lock-discipline``.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Set, Tuple

from production_stack_tpu.staticcheck.cfg import (
    CFG,
    WithEnter,
    WithExit,
    contains_await,
)
from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
    tail_name,
)
from production_stack_tpu.staticcheck import dataflow

SCOPE = (
    "production_stack_tpu/engine/server.py",
    "production_stack_tpu/router/*.py",
    "production_stack_tpu/router/**/*.py",
)


def _lock_name(expr: ast.AST) -> str:
    """Dotted name of a lock expression ('' if not lock-like). The
    with-item may be a call (``self._lock.acquire_timeout(...)``) —
    the receiver chain is what names the lock."""
    node = expr
    if isinstance(node, ast.Call):
        node = node.func
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    dotted = ".".join(reversed(parts))
    return dotted if "lock" in dotted.lower() else ""


# Lock fact: (dotted lock name, "sync"|"async")
Fact = Tuple[str, str]


def _transfer(state: FrozenSet[Fact], el, _kind) -> FrozenSet[Fact]:
    if isinstance(el, WithEnter):
        name = _lock_name(el.node)
        if name:
            return state | {(name, "async" if el.is_async else "sync")}
        return state
    if isinstance(el, WithExit):
        name = _lock_name(el.node)
        if name:
            return frozenset(f for f in state if f[0] != name)
        return state
    if isinstance(el, ast.AST):
        for node in ast.walk(el):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                if node.func.attr == "acquire":
                    name = _lock_name(node.func.value)
                    if name:
                        return state | {(name, "sync")}
                elif node.func.attr == "release":
                    name = _lock_name(node.func.value)
                    if name:
                        return frozenset(
                            f for f in state if f[0] != name)
    return state


def _no_raises(_stmt, _in_try) -> bool:
    # Lock findings are per-statement (not at exits), so exception
    # edges add blocks without adding signal; with/try routing still
    # releases locks on every path.
    return False


def _rmw_attrs(el) -> Set[str]:
    """self-attributes this element read-modify-writes."""
    out: Set[str] = set()
    if not isinstance(el, ast.AST):
        return out
    for node in ast.walk(el):
        if isinstance(node, ast.AugAssign):
            target = node.target
            if isinstance(target, ast.Subscript):
                target = target.value
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                out.add(target.attr)
        elif isinstance(node, ast.Assign):
            written = {t.attr for t in node.targets
                       if isinstance(t, ast.Attribute)
                       and isinstance(t.value, ast.Name)
                       and t.value.id == "self"}
            if written:
                read = {n.attr for n in ast.walk(node.value)
                        if isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"}
                out |= written & read
    return out


def _walk_blocks(cfg: CFG, block_in):
    """(element, state-before-element) pairs over reachable blocks."""
    for block in cfg.reachable():
        if block.id not in block_in:
            continue
        state = block_in[block.id]
        for el in block.elements:
            yield el, state
            state = _transfer(state, el, None)


@rule("lock-discipline",
      "no await under a held sync lock; shared attributes "
      "read-modify-written from several async handlers share a lock")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files(*SCOPE):
        if sf.tree is None:
            continue  # parse-error rule reports it
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            # attr -> [(method, line, locks-held-at-site)]
            mutations: Dict[str, List[Tuple[str, int,
                                            FrozenSet[str]]]] = {}
            for fn in cls.body:
                if not isinstance(fn, ast.AsyncFunctionDef):
                    continue
                cfg = CFG(fn, raises=_no_raises)
                block_in, _ = dataflow.solve(
                    cfg, frozenset(), _transfer, join="intersection")
                for el, state in _walk_blocks(cfg, block_in):
                    held_sync = sorted(
                        n for n, k in state if k == "sync")
                    if (held_sync and isinstance(el, ast.AST)
                            and not isinstance(
                                el, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                            and contains_await(el)):
                        findings.append(sf.finding(
                            "lock-discipline", el,
                            f"await in {cls.name}.{fn.name} while "
                            f"sync lock {held_sync[0]} is held — "
                            "parks the event loop; use asyncio.Lock "
                            "with 'async with', or release first"))
                    for attr in _rmw_attrs(el):
                        if "lock" in attr.lower():
                            continue
                        mutations.setdefault(attr, []).append(
                            (fn.name, getattr(el, "lineno", 0),
                             frozenset(n for n, _k in state)))
            for attr, sites in sorted(mutations.items()):
                methods = {m for m, _l, _h in sites}
                if len(methods) < 2:
                    continue
                common = frozenset.intersection(
                    *[h for _m, _l, h in sites])
                if common:
                    continue
                for method, line, held in sorted(sites):
                    if held:
                        continue  # this site is guarded; flag the bare ones
                    findings.append(sf.finding(
                        "lock-discipline", line,
                        f"self.{attr} is read-modify-written from "
                        f"async handlers {sorted(methods)} of "
                        f"{cls.name} with no common lock — "
                        "interleaved handlers lose updates; guard "
                        "every site with one asyncio.Lock"))
    return findings
