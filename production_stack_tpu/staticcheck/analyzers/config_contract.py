"""Rule ``config-contract``: EngineConfig, the CLI and the docs must
agree; feature exclusivity is rejected at config time and tested.

Operators drive the engine through ``tpu-engine`` flags; the config
dataclasses are the source of truth; docs are the contract surface.
These drift independently (a field added without a flag is
unreachable in deployment; a flag without docs is unused; an
exclusivity check without a test rots). Checks, all static:

1. every dataclass field reachable from ``EngineConfig`` maps to a
   CLI flag in engine/server.py ``parse_args`` — by naming convention
   (``scheduler.max_num_seqs`` -> ``--max-num-seqs``), via the
   ``CLI_FLAG_ALIASES`` marker in engine/config.py, or is listed in
   the ``INTERNAL_FIELDS`` marker (derived / HF-config-owned values);
2. the markers themselves are honest: aliases point at real flags,
   ``INTERNAL_FIELDS``/alias keys name real fields;
3. every entry in ``EXCLUSIVITY_RULES`` (feature-gate pairs like
   int8 KV x pipeline parallelism) has (a) a config-time
   ``raise ValueError`` in engine/config.py whose message contains
   the rule's token and (b) a test in tests/ that exercises
   ``pytest.raises`` and references both the token and the second
   field — so the rejection can never be deleted silently;
4. every ``--flag`` appears in the docs (docs/**/*.md or README.md);
   docs/engine_flags.md is the canonical flag table — this covers the
   fleet-manager CLI (fleet/__main__.py) as well as the engine server;
5. the fleet spec (fleet/spec.py) honours the same contract: every
   field of FleetSpec/PoolSpec/AutoscalerSpec is parsed from its JSON
   key in spec.py and documented in docs/fleet.md, or listed in the
   ``FLEET_INTERNAL_FIELDS`` marker (which must itself be honest).

Cross-file contract findings (line 0); fixed by code/markers/docs,
not waiver comments.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from production_stack_tpu.staticcheck.core import (
    Finding,
    Project,
    rule,
    string_constants,
    referenced_names,
    tail_name,
)

CONFIG_FILE = "production_stack_tpu/engine/config.py"
SERVER_FILE = "production_stack_tpu/engine/server.py"
TOPOLOGY_FILE = "production_stack_tpu/parallel/topology.py"
MESH_FILE = "production_stack_tpu/parallel/mesh.py"
PARALLELISM_DOC = "docs/parallelism.md"
FLEET_SPEC_FILE = "production_stack_tpu/fleet/spec.py"
FLEET_CLI_FILE = "production_stack_tpu/fleet/__main__.py"
FLEET_DOC_FILE = "docs/fleet.md"
DOC_PATTERNS = ("docs/**/*.md", "*.md")
TEST_PATTERN = "tests/test_*.py"

# EngineConfig sections whose dataclass fields are operator surface.
_SECTION_CLASSES = {
    "model": "ModelConfig",
    "cache": "CacheConfig",
    "scheduler": "SchedulerConfig",
    "parallel": "ParallelConfig",
    "lora": "LoRAConfig",
    "offload": "OffloadConfig",
    "qos": "QoSConfig",
    "kvecon": "KVEconConfig",
    "autotune": "AutotuneConfig",
}

# Fleet-spec classes whose dataclass fields are operator surface,
# keyed by how the field path reads in a spec file.
_FLEET_SECTION_CLASSES = {
    "": "FleetSpec",
    "pools[].": "PoolSpec",
    "pools[].autoscaler.": "AutoscalerSpec",
    "pools[].revision.": "RevisionSpec",
    "pools[].rollout.": "RolloutSpec",
}


def _module_literal(tree: ast.AST, name: str):
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if isinstance(target, ast.Name) and target.id == name:
                    return stmt.value
    return None


def _literal_value(node):
    try:
        return ast.literal_eval(node) if node is not None else None
    except (ValueError, TypeError):
        return None


def _dataclass_fields(tree: ast.AST) -> Dict[str, Set[str]]:
    """{class name: field names} for every class in the module."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out[node.name] = {
                stmt.target.id for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)}
    return out


def _cli_flags(tree: ast.AST) -> Set[str]:
    flags: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and tail_name(node.func) == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("--")):
            flags.add(node.args[0].value)
    return flags


def _raise_messages(tree: ast.AST) -> List[str]:
    """Joined string constants of every ``raise ValueError(...)``."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Raise) and node.exc is not None
                and isinstance(node.exc, ast.Call)
                and tail_name(node.exc.func) == "ValueError"):
            out.append(" ".join(string_constants(node.exc)))
    return out


def _raises_test_pools(project: Project) -> List[Tuple[str, str]]:
    """(test id, joined reference pool) for every test function that
    uses pytest.raises."""
    pools = []
    for sf in project.files(TEST_PATTERN):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_"):
                continue
            refs = referenced_names(node)
            if "raises" not in refs:
                continue
            pools.append((f"{sf.relpath}::{node.name}",
                          " ".join(sorted(refs))))
    return pools


def _finding(path: str, message: str) -> Finding:
    return Finding(rule="config-contract", path=path, line=0,
                   message=message)


@rule("config-contract",
      "EngineConfig fields <-> CLI flags <-> docs; exclusivity pairs "
      "rejected and tested")
def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    config = project.source(CONFIG_FILE)
    server = project.source(SERVER_FILE)
    for path, sf in ((CONFIG_FILE, config), (SERVER_FILE, server)):
        if sf is None or sf.tree is None:
            findings.append(_finding(
                path, "config-contract surface file missing — if the "
                      "layer moved, update "
                      "staticcheck/analyzers/config_contract.py"))
    if findings:
        return findings

    classes = _dataclass_fields(config.tree)
    fields: Set[str] = set()
    for section, cls in _SECTION_CLASSES.items():
        for field in classes.get(cls, set()):
            fields.add(f"{section}.{field}")
    for field in classes.get("EngineConfig", set()):
        if field not in _SECTION_CLASSES:
            fields.add(field)

    flags = _cli_flags(server.tree)
    aliases = _literal_value(
        _module_literal(config.tree, "CLI_FLAG_ALIASES")) or {}
    internal = _literal_value(
        _module_literal(config.tree, "INTERNAL_FIELDS")) or set()
    exclusivity = _literal_value(
        _module_literal(config.tree, "EXCLUSIVITY_RULES")) or ()

    # (1) field -> flag | alias | internal marker.
    for field in sorted(fields):
        guess = "--" + field.rsplit(".", 1)[-1].replace("_", "-")
        if guess in flags:
            continue
        if field in aliases:
            if aliases[field] not in flags:
                findings.append(_finding(
                    CONFIG_FILE,
                    f"CLI_FLAG_ALIASES maps {field} to "
                    f"{aliases[field]}, which parse_args does not "
                    "define"))
            continue
        if field in internal:
            continue
        findings.append(_finding(
            CONFIG_FILE,
            f"config field {field} has no CLI flag ({guess} not in "
            "parse_args), no CLI_FLAG_ALIASES entry and no "
            "INTERNAL_FIELDS marker — operators cannot reach it, "
            "and nothing says that is intentional"))

    # (2) honest markers.
    for field in sorted(set(internal) | set(aliases)):
        if field not in fields:
            findings.append(_finding(
                CONFIG_FILE,
                f"marker references unknown config field {field} — "
                "stale INTERNAL_FIELDS/CLI_FLAG_ALIASES entry"))

    # (3) exclusivity pairs: config-time rejection + a test.
    messages = _raise_messages(config.tree)
    pools = _raises_test_pools(project)
    for entry in exclusivity:
        try:
            field_a, field_b, token = entry
        except (TypeError, ValueError):
            findings.append(_finding(
                CONFIG_FILE,
                f"malformed EXCLUSIVITY_RULES entry {entry!r} — "
                "expected (field_a, field_b, token)"))
            continue
        for f in (field_a, field_b):
            if f not in fields:
                findings.append(_finding(
                    CONFIG_FILE,
                    f"EXCLUSIVITY_RULES references unknown field {f}"))
        if not any(token in msg for msg in messages):
            findings.append(_finding(
                CONFIG_FILE,
                f"exclusivity {field_a} x {field_b}: no config-time "
                f"raise ValueError mentioning '{token}' in "
                "engine/config.py — the combination is no longer "
                "rejected"))
        tail_b = field_b.rsplit(".", 1)[-1]
        if not any(token in pool and tail_b in pool
                   for _, pool in pools):
            findings.append(_finding(
                CONFIG_FILE,
                f"exclusivity {field_a} x {field_b}: no pytest.raises "
                f"test referencing both '{token}' and '{tail_b}' "
                "under tests/ — the rejection is untested"))

    # (4) every flag documented — engine server and fleet CLI alike.
    doc_text = "\n".join(
        sf.text for sf in project.files(*DOC_PATTERNS))
    flag_sources = [(SERVER_FILE, flags)]
    fleet_cli = project.source(FLEET_CLI_FILE)
    if fleet_cli is None or fleet_cli.tree is None:
        findings.append(_finding(
            FLEET_CLI_FILE,
            "config-contract surface file missing — if the fleet CLI "
            "moved, update staticcheck/analyzers/config_contract.py"))
    else:
        flag_sources.append((FLEET_CLI_FILE, _cli_flags(fleet_cli.tree)))
    for path, source_flags in flag_sources:
        for flag in sorted(source_flags):
            if not re.search(re.escape(flag) + r"(?![\w-])", doc_text):
                findings.append(_finding(
                    path,
                    f"CLI flag {flag} appears in no markdown doc "
                    "(docs/**/*.md, README.md) — add it to "
                    "docs/engine_flags.md"))

    # (5) fleet spec fields parsed + documented (or marked internal).
    findings.extend(_check_fleet_spec(project))

    # (6) MeshPlan fields threaded through build_mesh + documented.
    findings.extend(_check_mesh_plan(project))
    return findings


def _check_mesh_plan(project: Project) -> List[Finding]:
    """The topology-aware mesh surface (docs/parallelism.md): every
    ``MeshPlan`` dataclass field must be reachable from
    ``parallel/mesh.py build_mesh`` (a keyword in a MeshPlan(...)
    call, or named as a string literal for dict-threaded kwargs) and
    documented in docs/parallelism.md — a plan knob nobody can set,
    or set but nobody can read about, is drift."""
    findings: List[Finding] = []
    topology = project.source(TOPOLOGY_FILE)
    mesh = project.source(MESH_FILE)
    for path, sf in ((TOPOLOGY_FILE, topology), (MESH_FILE, mesh)):
        if sf is None or sf.tree is None:
            findings.append(_finding(
                path, "config-contract surface file missing — if the "
                      "parallel layer moved, update "
                      "staticcheck/analyzers/config_contract.py"))
    if findings:
        return findings
    plan_fields = _dataclass_fields(topology.tree).get("MeshPlan")
    if not plan_fields:
        return [_finding(
            TOPOLOGY_FILE,
            "MeshPlan class not found in parallel/topology.py — if "
            "the mesh plan moved, update "
            "staticcheck/analyzers/config_contract.py")]
    reachable: Set[str] = set()
    for node in ast.walk(mesh.tree):
        if (isinstance(node, ast.Call)
                and tail_name(node.func) == "MeshPlan"):
            reachable.update(kw.arg for kw in node.keywords
                             if kw.arg is not None)
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                         str):
            reachable.add(node.value)
    doc = project.source(PARALLELISM_DOC)
    doc_text = doc.text if doc is not None else ""
    if not doc_text:
        findings.append(_finding(
            PARALLELISM_DOC,
            "docs/parallelism.md missing — the MeshPlan surface has "
            "no documented contract"))
    for field in sorted(plan_fields):
        if field not in reachable:
            findings.append(_finding(
                TOPOLOGY_FILE,
                f"MeshPlan field {field} is not threaded through "
                "parallel/mesh.py build_mesh — operators cannot set "
                "it from the engine config"))
        if doc_text and not re.search(
                r"(?<!\w)" + re.escape(field) + r"(?![\w-])",
                doc_text):
            findings.append(_finding(
                TOPOLOGY_FILE,
                f"MeshPlan field {field} is not documented in "
                "docs/parallelism.md"))
    return findings


def _check_fleet_spec(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    spec = project.source(FLEET_SPEC_FILE)
    if spec is None or spec.tree is None:
        return [_finding(
            FLEET_SPEC_FILE,
            "config-contract surface file missing — if the fleet layer "
            "moved, update staticcheck/analyzers/config_contract.py")]
    classes = _dataclass_fields(spec.tree)
    internal = _literal_value(
        _module_literal(spec.tree, "FLEET_INTERNAL_FIELDS")) or ()
    literals: Set[str] = set()
    for node in ast.walk(spec.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            literals.add(node.value)
    doc = project.source(FLEET_DOC_FILE)
    doc_text = doc.text if doc is not None else ""
    if not doc_text:
        findings.append(_finding(
            FLEET_DOC_FILE,
            "docs/fleet.md missing — the fleet spec has no documented "
            "contract surface"))

    fields: Set[Tuple[str, str]] = set()
    for prefix, cls in _FLEET_SECTION_CLASSES.items():
        for field in classes.get(cls, set()):
            fields.add((prefix + field, field))
    paths = {path for path, _ in fields}
    for path, name in sorted(fields):
        if path in internal:
            continue
        if name not in literals:
            findings.append(_finding(
                FLEET_SPEC_FILE,
                f"fleet spec field {path} is never parsed — no '{name}' "
                "string key in fleet/spec.py, so a spec file cannot set "
                "it and nothing says that is intentional (add it to "
                "from_dict or to FLEET_INTERNAL_FIELDS)"))
        if doc_text and not re.search(
                r"(?<!\w)" + re.escape(name) + r"(?![\w-])", doc_text):
            findings.append(_finding(
                FLEET_SPEC_FILE,
                f"fleet spec field {path} is not documented in "
                "docs/fleet.md"))
    for path in sorted(internal):
        if path not in paths:
            findings.append(_finding(
                FLEET_SPEC_FILE,
                f"FLEET_INTERNAL_FIELDS references unknown fleet spec "
                f"field {path} — stale marker entry"))
    return findings
