import sys

from production_stack_tpu.staticcheck.cli import main

sys.exit(main())
