"""AST-based static analyzers for the stack's structural invariants.

``python -m production_stack_tpu.staticcheck`` runs the suite;
docs/static_analysis.md is the rule catalog. Import surface for
tests and tooling:

- ``Project`` / ``run_rules`` / ``Finding`` / ``REGISTRY`` (core)
- ``baseline`` module (fingerprint ledger)
"""

from production_stack_tpu.staticcheck.core import (  # noqa: F401
    Finding,
    Project,
    REGISTRY,
    rule,
    run_rules,
)
