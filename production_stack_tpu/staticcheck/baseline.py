"""Checked-in baseline of legacy findings.

New analyzers land with teeth immediately: findings already in the
tree when a rule is introduced are recorded here by fingerprint, and
only findings *outside* the baseline fail the CLI. The workflow
(docs/static_analysis.md):

- fix or waive findings where possible — the baseline is a debt
  ledger, not a waiver mechanism;
- ``python -m production_stack_tpu.staticcheck --update-baseline``
  rewrites the file from the current tree (review the diff: a grown
  baseline is a regression you are choosing to accept);
- ``--prune-baseline`` drops entries whose finding no longer fires
  without accepting any new debt — the shrink-only counterpart;
- CI runs with ``--fail-stale-baseline``: a stale entry (fingerprint
  that no longer fires) fails the job, so paid-down debt is removed
  from the ledger in the same PR that paid it.
"""

from __future__ import annotations

import json
import pathlib
from typing import Iterable, List, Set

from production_stack_tpu.staticcheck.core import Finding

BASELINE_RELPATH = "production_stack_tpu/staticcheck/baseline.json"


def baseline_path(root) -> pathlib.Path:
    return pathlib.Path(root) / BASELINE_RELPATH


def load_entries(root) -> List[dict]:
    path = baseline_path(root)
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    return list(data.get("findings", []))


def load_fingerprints(root) -> Set[str]:
    return {entry["fingerprint"] for entry in load_entries(root)}


def stale_entries(root, findings: Iterable[Finding]) -> List[dict]:
    """Baseline entries whose fingerprint no longer fires anywhere in
    the tree — paid-down debt that should leave the ledger."""
    live = {f.fingerprint() for f in findings}
    return [e for e in load_entries(root)
            if e["fingerprint"] not in live]


def prune(root, findings: Iterable[Finding]) -> List[dict]:
    """Drop stale entries, rewrite the file, return what was dropped.
    Shrink-only: never records new findings."""
    live = {f.fingerprint() for f in findings}
    entries = load_entries(root)
    kept = [e for e in entries if e["fingerprint"] in live]
    dropped = [e for e in entries if e["fingerprint"] not in live]
    if dropped:
        baseline_path(root).write_text(json.dumps(
            {"version": 1, "findings": kept}, indent=2) + "\n")
    return dropped


def split_new(findings: Iterable[Finding],
              fingerprints: Set[str]):
    """(new, baselined) partition of ``findings``."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.fingerprint() in fingerprints else new).append(f)
    return new, old


def write(root, findings: Iterable[Finding]) -> pathlib.Path:
    path = baseline_path(root)
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
        }
        for f in sorted(findings,
                        key=lambda f: (f.path, f.line, f.rule))
    ]
    path.write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=2) + "\n")
    return path
