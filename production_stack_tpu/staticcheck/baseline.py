"""Checked-in baseline of legacy findings.

New analyzers land with teeth immediately: findings already in the
tree when a rule is introduced are recorded here by fingerprint, and
only findings *outside* the baseline fail the CLI. The workflow
(docs/static_analysis.md):

- fix or waive findings where possible — the baseline is a debt
  ledger, not a waiver mechanism;
- ``python -m production_stack_tpu.staticcheck --update-baseline``
  rewrites the file from the current tree (review the diff: a grown
  baseline is a regression you are choosing to accept);
- an entry whose finding disappears is pruned on the next
  ``--update-baseline`` and never hides anything meanwhile.
"""

from __future__ import annotations

import json
import pathlib
from typing import Iterable, List, Set

from production_stack_tpu.staticcheck.core import Finding

BASELINE_RELPATH = "production_stack_tpu/staticcheck/baseline.json"


def baseline_path(root) -> pathlib.Path:
    return pathlib.Path(root) / BASELINE_RELPATH


def load_fingerprints(root) -> Set[str]:
    path = baseline_path(root)
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {entry["fingerprint"] for entry in data.get("findings", [])}


def split_new(findings: Iterable[Finding],
              fingerprints: Set[str]):
    """(new, baselined) partition of ``findings``."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.fingerprint() in fingerprints else new).append(f)
    return new, old


def write(root, findings: Iterable[Finding]) -> pathlib.Path:
    path = baseline_path(root)
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
        }
        for f in sorted(findings,
                        key=lambda f: (f.path, f.line, f.rule))
    ]
    path.write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=2) + "\n")
    return path
