"""``python -m production_stack_tpu.staticcheck`` — run the analyzers.

Exit-code contract (relied on by .github/workflows/ci.yml and the
pre-commit hook):

- 0: no findings outside the checked-in baseline (the tree is clean);
- 1: new findings — listed on stdout (human) or in the ``findings``
  array (``--json``);
- 2: usage or internal error (unknown rule, unreadable root, ...).

``--update-baseline`` rewrites baseline.json from the current tree
and exits 0; review that diff like code. ``--prune-baseline`` is the
shrink-only counterpart (drops stale entries, never adds), and
``--fail-stale-baseline`` turns a stale entry into exit 1 — CI runs
with it so paid-down debt leaves the ledger in the paying PR.

``--diff <git-ref>`` reports only findings whose file/line is touched
vs the ref (git diff -U0; exit semantics unchanged) so pre-commit
stays fast as the rule count grows. ``--sarif out.sarif`` writes a
SARIF 2.1.0 report for PR annotation alongside the normal output;
the ``--json`` payload is byte-stable and unaffected by either flag's
absence. ``--jobs N`` parses files and runs the rules on N threads
against one shared parsed-AST/call-graph cache — output is identical
to ``--jobs 1``, only faster (CI runs ``--jobs 4``).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from production_stack_tpu.staticcheck import baseline as baseline_mod
from production_stack_tpu.staticcheck import diff as diff_mod
from production_stack_tpu.staticcheck import sarif as sarif_mod
from production_stack_tpu.staticcheck.core import (
    REGISTRY,
    Project,
    run_rules,
)


def _default_root() -> pathlib.Path:
    # The repo root is two levels above this package.
    return pathlib.Path(__file__).resolve().parent.parent.parent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.staticcheck",
        description="AST analyzers enforcing the stack's structural "
                    "invariants (docs/static_analysis.md)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite baseline.json from the current "
                             "tree (then exit 0)")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop baseline entries that no longer "
                             "fire (shrink-only; then exit 0)")
    parser.add_argument("--fail-stale-baseline", action="store_true",
                        help="exit 1 if any baseline entry no longer "
                             "fires (CI ledger hygiene)")
    parser.add_argument("--diff", default=None, metavar="GIT_REF",
                        help="report only findings on lines changed "
                             "vs this git ref (analysis still runs "
                             "on the whole tree)")
    parser.add_argument("--sarif", default=None, metavar="PATH",
                        help="also write a SARIF 2.1.0 report of the "
                             "new findings to PATH")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="run per-file parsing and the rules on "
                             "N threads (findings identical to "
                             "--jobs 1)")
    args = parser.parse_args(argv)

    if args.jobs < 1:
        print("error: --jobs must be >= 1", file=sys.stderr)
        return 2

    # Side-effect import: registers every analyzer.
    from production_stack_tpu.staticcheck import analyzers  # noqa: F401

    if args.list_rules:
        for name in sorted(REGISTRY):
            mark = (" [interprocedural]"
                    if REGISTRY[name].interprocedural else "")
            print(f"{name}{mark}: {REGISTRY[name].description}")
        return 0

    root = pathlib.Path(args.root) if args.root else _default_root()
    if not (root / "production_stack_tpu").is_dir():
        print(f"error: {root} does not look like the repo root",
              file=sys.stderr)
        return 2

    try:
        project = Project.from_root(root)
        findings = run_rules(project, rules=args.rule,
                             jobs=args.jobs)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    if args.update_baseline:
        path = baseline_mod.write(root, findings)
        print(f"wrote {len(findings)} finding(s) to {path}")
        return 0

    if args.prune_baseline:
        dropped = baseline_mod.prune(root, findings)
        print(f"pruned {len(dropped)} stale baseline entr"
              f"{'y' if len(dropped) == 1 else 'ies'}")
        return 0

    fingerprints = baseline_mod.load_fingerprints(root)
    new, baselined = baseline_mod.split_new(findings, fingerprints)

    if args.diff is not None:
        try:
            changed = diff_mod.changed_lines(root, args.diff)
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        new = diff_mod.filter_findings(new, changed)

    if args.sarif:
        payload = sarif_mod.render(new, REGISTRY)
        pathlib.Path(args.sarif).write_text(
            json.dumps(payload, indent=2) + "\n")

    stale = (baseline_mod.stale_entries(root, findings)
             if args.fail_stale_baseline else [])

    if args.json:
        print(json.dumps({
            "version": 1,
            "root": str(root),
            "rules": sorted(args.rule) if args.rule else sorted(REGISTRY),
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        print(f"{len(new)} new finding(s), {len(baselined)} "
              "baselined")
    if stale:
        for entry in stale:
            print(f"stale baseline entry: {entry['fingerprint']} "
                  f"({entry['rule']}, {entry['path']}) no longer "
                  "fires — run --prune-baseline",
                  file=sys.stderr)
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
