"""``python -m production_stack_tpu.staticcheck`` — run the analyzers.

Exit-code contract (relied on by .github/workflows/ci.yml and the
pre-commit hook):

- 0: no findings outside the checked-in baseline (the tree is clean);
- 1: new findings — listed on stdout (human) or in the ``findings``
  array (``--json``);
- 2: usage or internal error (unknown rule, unreadable root, ...).

``--update-baseline`` rewrites baseline.json from the current tree
and exits 0; review that diff like code.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from production_stack_tpu.staticcheck import baseline as baseline_mod
from production_stack_tpu.staticcheck.core import (
    REGISTRY,
    Project,
    run_rules,
)


def _default_root() -> pathlib.Path:
    # The repo root is two levels above this package.
    return pathlib.Path(__file__).resolve().parent.parent.parent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.staticcheck",
        description="AST analyzers enforcing the stack's structural "
                    "invariants (docs/static_analysis.md)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite baseline.json from the current "
                             "tree (then exit 0)")
    args = parser.parse_args(argv)

    # Side-effect import: registers every analyzer.
    from production_stack_tpu.staticcheck import analyzers  # noqa: F401

    if args.list_rules:
        for name in sorted(REGISTRY):
            print(f"{name}: {REGISTRY[name].description}")
        return 0

    root = pathlib.Path(args.root) if args.root else _default_root()
    if not (root / "production_stack_tpu").is_dir():
        print(f"error: {root} does not look like the repo root",
              file=sys.stderr)
        return 2

    try:
        project = Project.from_root(root)
        findings = run_rules(project, rules=args.rule)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    if args.update_baseline:
        path = baseline_mod.write(root, findings)
        print(f"wrote {len(findings)} finding(s) to {path}")
        return 0

    fingerprints = baseline_mod.load_fingerprints(root)
    new, baselined = baseline_mod.split_new(findings, fingerprints)

    if args.json:
        print(json.dumps({
            "version": 1,
            "root": str(root),
            "rules": sorted(args.rule) if args.rule else sorted(REGISTRY),
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        print(f"{len(new)} new finding(s), {len(baselined)} "
              "baselined")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
