"""SARIF 2.1.0 rendering for ``--sarif out.sarif``.

The point is PR annotation: CI uploads the file via
``github/codeql-action/upload-sarif`` and findings appear inline on
the diff. Minimal valid subset — one run, the registered rules as the
driver's rule catalog, one result per finding with a physical
location and the staticcheck fingerprint carried in
``partialFingerprints`` so GitHub's alert dedup tracks ours.

Interprocedural findings (PR 20) carry their call chain as a SARIF
``codeFlow`` — one thread flow, one location per frame, already
capped at ``core.CHAIN_CAP`` frames by ``Finding`` itself — so the
PR annotation shows the same async-handler → helper → primitive path
the terminal message renders, and the report size stays bounded no
matter how deep the real chain was.

``--json`` stays the machine-readable contract (byte-stable); SARIF
is a second emitter over the same findings, never a replacement.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from production_stack_tpu.staticcheck.core import Finding, Rule

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _code_flow(f: Finding) -> dict:
    """One SARIF codeFlow from a finding's (already capped) call
    chain. The dropped-frame count is noted on the last location's
    message rather than re-expanding the chain."""
    locations = []
    frames = list(f.chain)
    for i, (path, line, label) in enumerate(frames):
        text = label
        if f.chain_dropped and i == len(frames) - 1:
            text = f"{label} (+{f.chain_dropped} more frames)"
        locations.append({
            "location": {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": path,
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {"startLine": max(line, 1)},
                },
                "message": {"text": text},
            },
        })
    return {"threadFlows": [{"locations": locations}]}


def render(findings: Iterable[Finding],
           rules: Dict[str, Rule]) -> dict:
    rule_ids = sorted(rules)
    index = {name: i for i, name in enumerate(rule_ids)}
    results: List[dict] = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "partialFingerprints": {
                "staticcheckFingerprint/v1": f.fingerprint(),
            },
        }
        if f.rule in index:
            result["ruleIndex"] = index[f.rule]
        if f.chain:
            result["codeFlows"] = [_code_flow(f)]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "production-stack-tpu-staticcheck",
                    "informationUri":
                        "docs/static_analysis.md",
                    "rules": [{
                        "id": name,
                        "shortDescription": {
                            "text": rules[name].description},
                    } for name in rule_ids],
                },
            },
            "results": results,
        }],
    }
