"""Staticcheck core: findings, projects, rules, waivers.

The stack's load-bearing invariants — zero per-step recompiles, no
host sync on the dispatch path, engine metrics round-tripping through
the router, mutually-exclusive feature combos rejected at config time
— are cheap to state over the AST and expensive (or impossible) to
cover with runtime tests. PRs 1-4 each hand-rolled a one-off AST lint;
this package is the shared framework they migrate into, so every new
invariant is ~one analyzer module instead of another bespoke walker.

Pieces:

- ``Finding``: one violation, with a line-number-independent
  fingerprint so the baseline survives unrelated edits.
- ``Project``: the file universe a run sees. ``Project.from_root``
  reads the repo; ``Project.from_sources`` builds a synthetic tree so
  tests can plant violations without touching disk.
- ``@rule(...)``: registers an analyzer. An analyzer is a function
  ``(project) -> list[Finding]``; per-file vs cross-file is its own
  business.
- Waivers: a ``# lint: allow-<rule>`` comment on the flagged line
  suppresses that rule there. Unknown rule names in a waiver are
  themselves findings (rule ``unknown-waiver``) so a typo fails
  loudly instead of silently disabling the check.
- Baseline (baseline.py): legacy findings checked in by fingerprint;
  only findings outside the baseline fail the CLI.

See docs/static_analysis.md for the rule catalog and how to add one.
"""

from __future__ import annotations

import ast
import concurrent.futures
import dataclasses
import datetime
import hashlib
import pathlib
import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_WAIVER_RE = re.compile(
    r"#\s*lint:\s*allow-([A-Za-z0-9_-]+)(?:\s+until=([^\s#]+))?")
_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}$")

# Rendered call chains are capped at this many frames; the tail is
# summarized as "… (+N frames)" so --json and SARIF stay bounded and
# byte-stable no matter how deep the interprocedural path goes.
CHAIN_CAP = 6


def cap_frames(frames: Iterable[Tuple[str, int, str]]
               ) -> Tuple[Tuple[Tuple[str, int, str], ...], int]:
    """(first CHAIN_CAP frames, count of dropped frames)."""
    frames = tuple(tuple(f) for f in frames)
    if len(frames) <= CHAIN_CAP:
        return frames, 0
    return frames[:CHAIN_CAP], len(frames) - CHAIN_CAP


def render_chain(frames: Iterable[Tuple[str, int, str]]) -> str:
    """``a → b → c … (+N frames)`` — labels only, capped."""
    kept, dropped = cap_frames(frames)
    text = " → ".join(label for _p, _l, label in kept)
    if dropped:
        text += f" … (+{dropped} frames)"
    return text


@dataclasses.dataclass
class Finding:
    """One rule violation at (path, line).

    ``chain`` is the interprocedural call path behind the finding
    (empty for intraprocedural findings): up to ``CHAIN_CAP``
    ``(path, line, label)`` frames, already capped by the creating
    analyzer via :func:`cap_frames`, with the overflow count in
    ``chain_dropped``. The chain is deliberately **excluded** from the
    fingerprint — renaming a mid-chain helper must not churn the
    baseline for a finding whose flagged line did not change."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int  # 1-based; 0 for file/project-level contract findings
    message: str
    snippet: str = ""
    chain: Tuple[Tuple[str, int, str], ...] = ()
    chain_dropped: int = 0

    def fingerprint(self) -> str:
        """Stable identity for baseline matching: rule + path + the
        normalized flagged source line (or the message for contract
        findings with no line). Deliberately excludes the line number
        so unrelated edits above a legacy finding don't make it
        'new'."""
        basis = self.snippet.strip() or self.message
        digest = hashlib.sha1(
            f"{self.rule}|{self.path}|{basis}".encode()).hexdigest()
        return digest[:12]

    def to_json(self) -> dict:
        out = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint(),
        }
        if self.chain:
            out["chain"] = [
                {"path": p, "line": line, "label": label}
                for p, line, label in self.chain]
            if self.chain_dropped:
                out["chain_dropped"] = self.chain_dropped
        return out

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.snippet.strip():
            out += f"\n    {self.snippet.strip()}"
        return out


class SourceFile:
    """One parsed file: text, lines, AST, waiver comments."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[str] = None
        self._waivers: Optional[Dict[int, set]] = None
        self._waiver_expiries: Optional[Dict[int, Dict[str, str]]] = \
            None

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.relpath)
            except SyntaxError as e:  # surfaced by run_rules
                self._parse_error = str(e)
        return self._tree

    @property
    def parse_error(self) -> Optional[str]:
        self.tree  # noqa: B018 - force the parse attempt
        return self._parse_error

    @property
    def waivers(self) -> Dict[int, set]:
        """{1-based line: {rule names waived on that line}}."""
        if self._waivers is None:
            self._waivers = {}
            for i, line in enumerate(self.lines, start=1):
                tokens = _WAIVER_RE.findall(line)
                if tokens:
                    self._waivers[i] = {name for name, _until in
                                        tokens}
        return self._waivers

    @property
    def waiver_expiries(self) -> Dict[int, Dict[str, str]]:
        """{1-based line: {rule: raw until= string}} for waivers that
        carry an expiry (``# lint: allow-<rule> until=YYYY-MM-DD``).
        The raw string is kept so the expiry check can parse strictly
        and fail loudly on a malformed date."""
        if self._waiver_expiries is None:
            self._waiver_expiries = {}
            for i, line in enumerate(self.lines, start=1):
                dated = {name: until for name, until in
                         _WAIVER_RE.findall(line) if until}
                if dated:
                    self._waiver_expiries[i] = dated
        return self._waiver_expiries

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node_or_line, message: str,
                chain: Iterable = ()) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line) or 0
        frames, dropped = cap_frames(chain)
        return Finding(rule=rule, path=self.relpath, line=line,
                       message=message, snippet=self.line_at(line),
                       chain=frames, chain_dropped=dropped)


def _glob_to_re(pattern: str) -> re.Pattern:
    """Translate a posix glob (with ** spanning directories) into a
    regex over relative paths."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 3] == "**/":
                out.append(r"(?:[^/]+/)*")
                i += 3
                continue
            if pattern[i:i + 2] == "**":
                out.append(r".*")
                i += 2
                continue
            out.append(r"[^/]*")
        elif c == "?":
            out.append(r"[^/]")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$")


class Project:
    """The set of files one staticcheck run analyzes.

    ``from_root`` loads the real tree (python under the package and
    tests, markdown docs); ``from_sources`` wraps an in-memory
    {relpath: text} mapping so analyzer self-tests can plant
    violations."""

    _DISK_PATTERNS = (
        "production_stack_tpu/**/*.py",
        "tests/*.py",
        "docs/**/*.md",
        "*.md",
    )

    def __init__(self, root: str, sources: Dict[str, str]):
        self.root = root
        self._sources = sources
        self._cache: Dict[str, SourceFile] = {}
        # Guards the memoized call graph / summaries when rules run
        # under --jobs (reentrant: summaries build the call graph).
        self._ipc_lock = threading.RLock()

    @classmethod
    def from_root(cls, root) -> "Project":
        root = pathlib.Path(root)
        sources: Dict[str, str] = {}
        for pattern in cls._DISK_PATTERNS:
            for path in sorted(root.glob(pattern)):
                if not path.is_file():
                    continue
                rel = path.relative_to(root).as_posix()
                if rel not in sources:
                    try:
                        sources[rel] = path.read_text()
                    except UnicodeDecodeError:
                        continue
        return cls(str(root), sources)

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "Project":
        return cls("<memory>", dict(sources))

    def paths(self, *patterns: str) -> List[str]:
        regexes = [_glob_to_re(p) for p in patterns]
        return sorted(p for p in self._sources
                      if any(r.match(p) for r in regexes))

    def files(self, *patterns: str) -> List[SourceFile]:
        return [self.source(p) for p in self.paths(*patterns)]

    def source(self, relpath: str) -> Optional[SourceFile]:
        if relpath not in self._sources:
            return None
        if relpath not in self._cache:
            with self._ipc_lock:
                if relpath not in self._cache:
                    self._cache[relpath] = SourceFile(
                        relpath, self._sources[relpath])
        return self._cache[relpath]

    def warm_parse_cache(self, jobs: int = 1) -> None:
        """Parse every python file up front (optionally in a thread
        pool) so rules running under ``--jobs`` share one AST per file
        instead of racing to parse."""
        sources = self.files("**/*.py")
        if jobs <= 1:
            for sf in sources:
                sf.tree  # noqa: B018 - force the parse
            return
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=jobs) as pool:
            list(pool.map(lambda sf: sf.tree, sources))


@dataclasses.dataclass
class Rule:
    name: str
    description: str
    run: Callable[[Project], List[Finding]]
    # True for rules that reason through the project call graph
    # (callgraph.py / summaries.py); surfaced by --list-rules.
    interprocedural: bool = False


REGISTRY: Dict[str, Rule] = {}


def rule(name: str, description: str, interprocedural: bool = False):
    """Register ``fn(project) -> list[Finding]`` as analyzer ``name``."""
    def decorator(fn):
        REGISTRY[name] = Rule(name=name, description=description,
                              run=fn, interprocedural=interprocedural)
        return fn
    return decorator


def _parse_waiver_date(raw: str) -> Optional[datetime.date]:
    """Strict ``YYYY-MM-DD`` parse; None for anything else (wrong
    shape, impossible date)."""
    if not _DATE_RE.match(raw):
        return None
    try:
        year, month, day = (int(part) for part in raw.split("-"))
        return datetime.date(year, month, day)
    except ValueError:
        return None


def _waived(project: Project, finding: Finding,
            today: Optional[datetime.date] = None) -> bool:
    sf = project.source(finding.path)
    if sf is None or finding.line == 0:
        return False
    if finding.rule not in sf.waivers.get(finding.line, set()):
        return False
    # A dated waiver stops suppressing the moment it expires (or if
    # its date never parsed) — the finding resurfaces alongside the
    # expired-waiver finding instead of staying silently waived.
    raw = sf.waiver_expiries.get(finding.line, {}).get(finding.rule)
    if raw is not None:
        until = _parse_waiver_date(raw)
        if until is None:
            return False
        if until < (today or datetime.date.today()):
            return False
    return True


def _waiver_findings(project: Project,
                     today: Optional[datetime.date] = None
                     ) -> List[Finding]:
    """A misspelled waiver silently disables nothing — it IS a
    finding, so the typo surfaces in the same run that was supposed
    to be suppressed. Dated waivers get the same loud-failure
    treatment: an expired or unparseable ``until=`` is an
    ``expired-waiver`` finding."""
    known = set(REGISTRY) | {"unknown-waiver", "expired-waiver"}
    today = today or datetime.date.today()
    out = []
    # Scope: package sources only. Test files quote waiver syntax in
    # fixture strings (including deliberate typos), which a raw-line
    # scan cannot tell from a real comment.
    for sf in project.files("production_stack_tpu/**/*.py"):
        for line, tokens in sf.waivers.items():
            for token in sorted(tokens - known):
                out.append(sf.finding(
                    "unknown-waiver", line,
                    f"waiver names unknown rule '{token}' (known: "
                    f"{', '.join(sorted(REGISTRY))}) — fix the "
                    "spelling or the waiver is dead weight"))
        for line, dated in sf.waiver_expiries.items():
            for token in sorted(dated):
                if token not in known:
                    continue  # already an unknown-waiver finding
                until = _parse_waiver_date(dated[token])
                if until is None:
                    out.append(sf.finding(
                        "expired-waiver", line,
                        f"waiver for '{token}' has unparseable "
                        f"until={dated[token]!r} (strict YYYY-MM-DD) "
                        "— the waiver is treated as expired"))
                elif until < today:
                    out.append(sf.finding(
                        "expired-waiver", line,
                        f"waiver for '{token}' expired on "
                        f"{until.isoformat()} — renew it with a new "
                        "date and rationale, or fix the finding"))
    return out


def run_rules(project: Project,
              rules: Optional[Iterable[str]] = None,
              jobs: int = 1) -> List[Finding]:
    """Run analyzers (all registered by default) plus the waiver
    spelling/expiry checks; waived findings are dropped, everything
    else is returned sorted.

    ``jobs > 1`` runs the analyzers in a thread pool after warming
    the shared parse cache (and the call-graph/summary memos, which
    every interprocedural rule shares); output is identical to a
    serial run — findings are sorted and rules are pure readers."""
    # Import for side effect: analyzer modules self-register.
    from production_stack_tpu.staticcheck import analyzers  # noqa: F401

    names = sorted(rules) if rules is not None else sorted(REGISTRY)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    findings: List[Finding] = []
    if jobs > 1:
        project.warm_parse_cache(jobs=jobs)
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=jobs) as pool:
            for result in pool.map(
                    lambda name: REGISTRY[name].run(project), names):
                findings.extend(result)
    else:
        for name in names:
            findings.extend(REGISTRY[name].run(project))
    findings.extend(_waiver_findings(project))
    # Files any analyzer failed to parse fail the run explicitly —
    # an unparseable file is unanalyzed, not clean.
    for sf in project.files("**/*.py"):
        if sf.parse_error is not None:
            findings.append(Finding(
                rule="parse-error", path=sf.relpath, line=0,
                message=f"file does not parse: {sf.parse_error}"))
    findings = [f for f in findings if not _waived(project, f)]
    return sorted(findings,
                  key=lambda f: (f.path, f.line, f.rule, f.message))


# ---- shared AST helpers used by several analyzers ----------------------


def tail_name(node: ast.AST) -> str:
    """Rightmost identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def recv_name(node: ast.AST) -> str:
    """Identifier of an Attribute's receiver ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return tail_name(node.value)
    return ""


def string_constants(node: ast.AST) -> List[str]:
    """Every string literal under ``node``, including the constant
    fragments of f-strings."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return out


def referenced_names(node: ast.AST) -> set:
    """Identifier pool of a subtree: bare names, attribute tails,
    keyword-argument names and string constants — the net used to
    decide whether a test 'references' a symbol."""
    names = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.add(sub.attr)
        elif isinstance(sub, ast.keyword) and sub.arg:
            names.add(sub.arg)
        elif isinstance(sub, ast.Constant) and isinstance(
                sub.value, str):
            names.add(sub.value)
    return names
