"""Project-wide call graph over stdlib ``ast``.

The intraprocedural rules (PR 5 patterns, PR 14 CFG/dataflow) stop at
function boundaries: a blocking or host-syncing helper one call deep
is invisible, and "this allocation is consumed" silently assumes the
callee does what its name suggests. This module gives the suite the
missing edge set so ``summaries.py`` can propagate facts bottom-up
and the analyzers can report the *full call chain* at the place the
invariant actually holds (the async handler, the traced function, the
jit call site).

Resolution is deliberately modest and **honest**:

- direct calls to module-level functions (``helper(...)``), including
  through ``from mod import helper [as h]`` / ``import mod [as m]``
  aliases for modules inside the project;
- ``self.method(...)`` / ``cls.method(...)`` against the enclosing
  class, then its base classes when those resolve to project classes
  (single pass up the chain, depth-bounded);
- calls through local bindings the tree actually uses:
  ``h = helper`` / ``h = functools.partial(helper, ...)`` then
  ``h(...)`` (flow-insensitive, last-binding-wins within a scope);
- nested ``def``s called by name from their enclosing function.

Everything else — ``obj.method(...)`` on an arbitrary receiver,
calls through containers, getattr, callbacks handed in as arguments —
becomes an **unresolved edge**: recorded with the best-effort callee
text, never guessed at. Analyzers must treat unresolved edges as
"unknown", which means transitive *findings* require a fully resolved
chain, while transitive *fact kills* (e.g. "callee consumed the
pages") stay conservative. An unresolved edge can therefore never
manufacture a finding; the cost is admitted, not hidden (see
docs/static_analysis.md, soundness caveats).

Function identity is the **qualified name** ``path.py::Class.method``
/ ``path.py::func`` / ``path.py::outer.<locals>.inner`` — stable
across line edits, so summaries and finding chains survive unrelated
refactors.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

PACKAGE = "production_stack_tpu"

# Builtin / stdlib callables we never try to resolve and never report
# as interesting unresolved edges (pure host work, no project code).
_BUILTIN_NAMES = frozenset({
    "len", "range", "list", "tuple", "set", "dict", "frozenset",
    "sorted", "reversed", "enumerate", "zip", "map", "filter", "sum",
    "min", "max", "abs", "round", "int", "float", "bool", "str",
    "bytes", "repr", "print", "isinstance", "issubclass", "getattr",
    "setattr", "hasattr", "iter", "next", "super", "type", "id",
    "hash", "vars", "dir", "any", "all", "divmod", "pow", "format",
    "open", "ValueError", "TypeError", "KeyError", "RuntimeError",
    "Exception", "StopIteration", "NotImplementedError",
})


@dataclasses.dataclass
class FunctionInfo:
    """One (async) function definition in the project."""

    qual: str                # "path.py::Class.method" etc.
    path: str                # repo-relative posix path
    node: object             # ast.FunctionDef | ast.AsyncFunctionDef
    class_name: Optional[str]
    is_async: bool

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def lineno(self) -> int:
        return self.node.lineno

    def label(self) -> str:
        """Human-readable frame label for chain rendering."""
        short = self.path.rsplit("/", 1)[-1]
        inner = self.qual.split("::", 1)[1]
        return f"{short}:{inner}"


@dataclasses.dataclass
class CallEdge:
    """One call site inside ``caller``. ``callee`` is a qualified
    name when resolution succeeded, else None (honest unknown)."""

    caller: str
    call: ast.Call
    callee: Optional[str]
    target_text: str         # best-effort callee rendering
    kind: str                # direct|method|alias|partial|unresolved|builtin

    @property
    def lineno(self) -> int:
        return self.call.lineno


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted rendering of a call target for messages."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return _dotted(node.func) + "(...)"
    return "<expr>"


def _module_to_path(module: str) -> Optional[str]:
    """``production_stack_tpu.engine.scheduler`` -> project relpath
    (None for anything outside the package)."""
    if not module or not module.startswith(PACKAGE):
        return None
    return module.replace(".", "/") + ".py"


class _Scope:
    """Name bindings visible at some definition nesting level:
    functions defined here, plus alias/partial bindings."""

    def __init__(self):
        # local callable name -> ("qual", qualname) | ("import", path, name)
        self.bindings: Dict[str, Tuple] = {}


class CallGraph:
    """Functions, edges, callers, SCCs for one :class:`Project`."""

    def __init__(self):
        self.functions: Dict[str, FunctionInfo] = {}
        self.edges: Dict[str, List[CallEdge]] = {}
        self.callers: Dict[str, List[CallEdge]] = {}
        # path -> {class name -> {method name -> qual}}
        self._classes: Dict[str, Dict[str, Dict[str, str]]] = {}
        # path -> {class name -> [base class names as written]}
        self._bases: Dict[str, Dict[str, List[str]]] = {}
        # path -> {module-level function name -> qual}
        self._module_funcs: Dict[str, Dict[str, str]] = {}
        # path -> {alias -> ("mod", module_path) | ("sym", path, name)}
        self._imports: Dict[str, Dict[str, Tuple]] = {}
        # (path, id(def node)) -> FunctionInfo, for function_at()
        self._by_node: Dict[Tuple[str, int], FunctionInfo] = {}

    # ---- construction ---------------------------------------------------

    @classmethod
    def build(cls, project) -> "CallGraph":
        graph = cls()
        files = [sf for sf in project.files(f"{PACKAGE}/**/*.py")
                 if sf.tree is not None]
        for sf in files:
            graph._collect_defs(sf)
        for sf in files:
            graph._collect_edges(sf)
        for edge_list in graph.edges.values():
            for edge in edge_list:
                if edge.callee is not None:
                    graph.callers.setdefault(edge.callee, []).append(edge)
        return graph

    def _collect_defs(self, sf) -> None:
        path = sf.relpath
        self._classes[path] = {}
        self._bases[path] = {}
        self._module_funcs[path] = {}
        self._imports[path] = {}
        self._collect_imports(sf.tree, path)

        def visit(node, prefix: str, class_name: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{path}::{prefix}{child.name}"
                    info = FunctionInfo(
                        qual=qual, path=path, node=child,
                        class_name=class_name,
                        is_async=isinstance(child,
                                            ast.AsyncFunctionDef))
                    self.functions[qual] = info
                    self._by_node[(path, id(child))] = info
                    if not prefix:
                        self._module_funcs[path][child.name] = qual
                    elif class_name and prefix == f"{class_name}.":
                        self._classes[path][class_name][
                            child.name] = qual
                    visit(child,
                          f"{prefix}{child.name}.<locals>.", class_name)
                elif isinstance(child, ast.ClassDef):
                    if not prefix:  # nested classes: skip method maps
                        self._classes[path][child.name] = {}
                        self._bases[path][child.name] = [
                            _dotted(b) for b in child.bases]
                        visit(child, f"{child.name}.", child.name)
                    else:
                        visit(child, f"{prefix}{child.name}.",
                              child.name)

        visit(sf.tree, "", None)

    def _collect_imports(self, tree, path: str) -> None:
        table = self._imports[path]
        pkg_dir = path.rsplit("/", 1)[0] if "/" in path else ""
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = _module_to_path(alias.name)
                    if target:
                        local = alias.asname or alias.name.split(".")[0]
                        table[local] = ("mod", target)
            elif isinstance(node, ast.ImportFrom):
                module = node.module or ""
                if node.level:  # relative import
                    base = pkg_dir
                    for _ in range(node.level - 1):
                        base = base.rsplit("/", 1)[0] if "/" in base \
                            else ""
                    module_base = (f"{base}/{module.replace('.', '/')}"
                                   if module else base)
                elif module.startswith(PACKAGE):
                    module_base = module.replace(".", "/")
                else:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    # `from pkg.mod import name`: name may be a symbol
                    # in mod.py or the submodule pkg/mod/name.py.
                    table[local] = ("sym", f"{module_base}.py",
                                    alias.name,
                                    f"{module_base}/{alias.name}.py")

    # ---- edge extraction ------------------------------------------------

    def _collect_edges(self, sf) -> None:
        path = sf.relpath

        def walk_function(info: FunctionInfo,
                          scope_bindings: Dict[str, Tuple]) -> None:
            bindings = dict(scope_bindings)
            # Pre-bind nested defs and local aliases (flow-insensitive;
            # a later rebinding wins for calls after it, which a single
            # top-to-bottom pass approximates well enough for a lint).
            for child in ast.iter_child_nodes(info.node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = (f"{info.qual}.<locals>.{child.name}")
                    if qual in self.functions:
                        bindings[child.name] = ("qual", qual)
            edges = self.edges.setdefault(info.qual, [])

            def visit(node):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue  # its calls belong to the nested fn
                    if isinstance(child, ast.Assign):
                        self._track_binding(child, bindings, path, info)
                    if isinstance(child, ast.Call):
                        edges.append(self._resolve_call(
                            child, info, bindings, path))
                    visit(child)

            visit(info.node)
            # Recurse into nested defs with the enclosing bindings.
            for child in ast.iter_child_nodes(info.node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{info.qual}.<locals>.{child.name}"
                    nested = self.functions.get(qual)
                    if nested is not None:
                        walk_function(nested, bindings)

        for qual, info in list(self.functions.items()):
            if info.path != path or "<locals>" in qual:
                continue  # nested defs walked from their parent
            walk_function(info, {})

    def _track_binding(self, assign: ast.Assign,
                       bindings: Dict[str, Tuple], path: str,
                       info: FunctionInfo) -> None:
        """``h = helper`` / ``h = functools.partial(helper, ...)``."""
        if len(assign.targets) != 1 or not isinstance(
                assign.targets[0], ast.Name):
            return
        name = assign.targets[0].id
        value = assign.value
        if isinstance(value, ast.Call) and \
                _tail(value.func) == "partial" and value.args:
            value = value.args[0]
        target = self._lookup(value, info, bindings, path)
        if target is not None:
            bindings[name] = ("qual", target)
        elif name in bindings:
            del bindings[name]  # rebound to something unknown

    def _lookup(self, func: ast.AST, info: FunctionInfo,
                bindings: Dict[str, Tuple],
                path: str) -> Optional[str]:
        """Resolve a callable reference to a qualified name, or None."""
        if isinstance(func, ast.Name):
            bound = bindings.get(func.id)
            if bound is not None and bound[0] == "qual":
                return bound[1]
            qual = self._module_funcs.get(path, {}).get(func.id)
            if qual is not None:
                return qual
            imp = self._imports.get(path, {}).get(func.id)
            if imp is not None and imp[0] == "sym":
                return self._module_funcs.get(imp[1], {}).get(imp[2])
            return None
        if isinstance(func, ast.Attribute):
            root = func.value
            if isinstance(root, ast.Name):
                if root.id in ("self", "cls") and info.class_name:
                    return self._method(path, info.class_name,
                                        func.attr)
                imp = self._imports.get(path, {}).get(root.id)
                if imp is not None and imp[0] == "mod":
                    return self._module_funcs.get(imp[1], {}).get(
                        func.attr)
                if imp is not None and imp[0] == "sym":
                    # `from pkg import mod` then `mod.fn(...)`.
                    sub_path = imp[3] if len(imp) > 3 else None
                    if sub_path:
                        return self._module_funcs.get(sub_path,
                                                      {}).get(func.attr)
        return None

    def _method(self, path: str, class_name: str,
                method: str, depth: int = 0) -> Optional[str]:
        """Look up a method on a class, then its project-resolvable
        bases (depth-bounded to keep cycles harmless)."""
        if depth > 8:
            return None
        qual = self._classes.get(path, {}).get(class_name, {}).get(
            method)
        if qual is not None:
            return qual
        for base in self._bases.get(path, {}).get(class_name, []):
            base_name = base.split(".")[-1]
            # Same module first, then imported symbol.
            if base_name in self._classes.get(path, {}):
                found = self._method(path, base_name, method,
                                     depth + 1)
                if found:
                    return found
            imp = self._imports.get(path, {}).get(base_name)
            if imp is not None and imp[0] == "sym" \
                    and base_name in self._classes.get(imp[1], {}):
                found = self._method(imp[1], base_name, method,
                                     depth + 1)
                if found:
                    return found
        return None

    def _resolve_call(self, call: ast.Call, info: FunctionInfo,
                      bindings: Dict[str, Tuple],
                      path: str) -> CallEdge:
        func = call.func
        text = _dotted(func)
        if isinstance(func, ast.Name) and func.id in _BUILTIN_NAMES \
                and func.id not in bindings \
                and func.id not in self._module_funcs.get(path, {}):
            return CallEdge(caller=info.qual, call=call, callee=None,
                            target_text=text, kind="builtin")
        target = self._lookup(func, info, bindings, path)
        if target is not None:
            kind = "method" if isinstance(func, ast.Attribute) \
                else "direct"
            if isinstance(func, ast.Name) and \
                    bindings.get(func.id, (None,))[0] == "qual":
                kind = "alias"
            return CallEdge(caller=info.qual, call=call,
                            callee=target, target_text=text,
                            kind=kind)
        return CallEdge(caller=info.qual, call=call, callee=None,
                        target_text=text, kind="unresolved")

    # ---- queries --------------------------------------------------------

    def function_at(self, path: str,
                    node) -> Optional[FunctionInfo]:
        """The FunctionInfo wrapping this exact def node, if known."""
        return self._by_node.get((path, id(node)))

    def edges_from(self, qual: str) -> List[CallEdge]:
        return self.edges.get(qual, [])

    def resolved_edges_from(self, qual: str) -> List[CallEdge]:
        return [e for e in self.edges.get(qual, [])
                if e.callee is not None]

    def sccs(self) -> List[List[str]]:
        """Strongly connected components over resolved edges, in
        reverse topological order (callees before callers) — the
        bottom-up order ``summaries.py`` wants. Iterative Tarjan."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        succ = {q: [e.callee for e in self.edges.get(q, [])
                    if e.callee is not None and e.callee in
                    self.functions]
                for q in self.functions}

        for root in sorted(self.functions):
            if root in index:
                continue
            work = [(root, iter(succ.get(root, [])))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(succ.get(nxt, []))))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        member = stack.pop()
                        on_stack.discard(member)
                        scc.append(member)
                        if member == node:
                            break
                    out.append(sorted(scc))
        return out


def _tail(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def for_project(project) -> CallGraph:
    """Build (once) and memoize the call graph on the project — every
    interprocedural rule in one run shares a single graph."""
    graph = getattr(project, "_callgraph", None)
    if graph is None:
        lock = getattr(project, "_ipc_lock", None)
        if lock is not None:
            with lock:
                graph = getattr(project, "_callgraph", None)
                if graph is None:
                    graph = CallGraph.build(project)
                    project._callgraph = graph
        else:
            graph = CallGraph.build(project)
            project._callgraph = graph
    return graph
