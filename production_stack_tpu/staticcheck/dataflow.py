"""Generic forward dataflow over ``cfg.CFG``.

One solver, many small lattices. An analysis supplies:

- ``initial``: the state at function entry (a frozenset of facts).
- ``transfer(state, element, incoming_kind) -> state``: the effect of
  one block element (an ``ast.stmt`` or a ``WithEnter``/``WithExit``
  marker). Pure; must return a frozenset.
- ``join``: ``"union"`` for may-analyses (a fact holds on SOME path —
  leak detection wants this: a page allocation live on any path to the
  exit is a leak) or ``"intersection"`` for must-analyses (a fact
  holds on ALL paths — "this value is definitely host-origin").

The solver iterates to a fixpoint with a worklist. States are
frozensets over a finite universe of per-function facts, so
termination is immediate (each block's in-state grows/shrinks
monotonically toward a bound).

Exception edges: the CFG builder isolates every potentially-raising
statement in its own block, so the EXC successor receives the state
*before* that statement's transfer — "the effects did not happen".
Concretely ``block_out`` maps each block to a dict ``{kind: state}``:
the NORMAL/BACK out-state has all transfers applied, the EXC
out-state is the block's in-state untouched.
"""

from __future__ import annotations

from typing import Callable, Dict, FrozenSet, List, Tuple

from .cfg import BACK, CFG, EXC, NORMAL, Block

State = FrozenSet
Transfer = Callable[[State, object, str], State]


def solve(cfg: CFG, initial: State, transfer: Transfer,
          join: str = "union") -> Tuple[Dict[int, State],
                                        Dict[int, Dict[str, State]]]:
    """Run ``transfer`` over ``cfg`` to fixpoint.

    Returns ``(block_in, block_out)`` keyed by block id. For
    intersection join, blocks never reached keep the universe-absent
    sentinel ``None`` internally and are excluded from the result.
    """
    assert join in ("union", "intersection")
    blocks = cfg.reachable()
    block_in: Dict[int, State] = {}
    block_out: Dict[int, Dict[str, State]] = {}

    # Predecessor map with edge kinds.
    preds: Dict[int, List[Tuple[Block, str]]] = {b.id: [] for b in blocks}
    ids = set(preds)
    for b in blocks:
        for dst, kind in b.succs:
            if dst.id in ids:
                preds[dst.id].append((b, kind))

    block_in[cfg.entry.id] = initial

    def apply_block(b: Block, state: State) -> Dict[str, State]:
        exc_state = state  # pre-statement state escapes on EXC edges
        for el in b.elements:
            state = transfer(state, el, NORMAL)
        return {NORMAL: state, BACK: state, EXC: exc_state}

    worklist = [b for b in blocks]
    in_list = {b.id for b in blocks}
    while worklist:
        b = worklist.pop(0)
        in_list.discard(b.id)
        if b.id == cfg.entry.id:
            new_in = initial
        else:
            incoming = [block_out[p.id][kind]
                        for p, kind in preds[b.id]
                        if p.id in block_out]
            if not incoming:
                continue  # no predecessor solved yet
            if join == "union":
                new_in = frozenset().union(*incoming)
            else:
                new_in = frozenset.intersection(*incoming)
        if b.id in block_in and block_in[b.id] == new_in \
                and b.id in block_out:
            continue
        block_in[b.id] = new_in
        block_out[b.id] = apply_block(b, new_in)
        for dst, _kind in b.succs:
            if dst.id in ids and dst.id not in in_list:
                in_list.add(dst.id)
                worklist.append(dst)
    return block_in, block_out


def facts_at_exit(cfg: CFG, initial: State, transfer: Transfer,
                  join: str = "union") -> Dict[str, State]:
    """Convenience: the joined state reaching the normal exit and the
    exceptional exit. Missing key means that exit is unreachable."""
    block_in, _ = solve(cfg, initial, transfer, join)
    out = {}
    if cfg.exit.id in block_in:
        out["exit"] = block_in[cfg.exit.id]
    if cfg.raise_exit.id in block_in:
        out["raise_exit"] = block_in[cfg.raise_exit.id]
    return out
