"""Intraprocedural control-flow graphs over stdlib ``ast``.

The per-node AST matching the suite started with (PR 5) cannot state
the invariants the engine keeps re-pinning with runtime regression
tests: "every path that allocates KV pages reaches the paired free",
"no ``await`` while a sync lock is held", "this branch only runs with
host data". Those are *path* properties. This module turns one
``FunctionDef``/``AsyncFunctionDef`` into a small CFG that the
worklist solver in ``dataflow.py`` runs lattices over.

Shape of the graph:

- ``Block``: a straight-line run of elements. Elements are either
  plain ``ast.stmt`` nodes or the synthetic ``WithEnter``/``WithExit``
  markers a ``with``/``async with`` desugars into (so a lock-held
  lattice sees acquisition and release as ordinary effects).
- Edges carry a kind: ``NORMAL`` (fallthrough/branch), ``BACK`` (loop
  back-edge — same semantics as NORMAL, labelled so tests and widening
  heuristics can see loops), and ``EXC`` (the statement raised).
- Two synthetic sinks: ``cfg.exit`` (return / fall-off-the-end) and
  ``cfg.raise_exit`` (an exception escaped the function).

Exception edges are the precision/noise dial. A statement gets EXC
edges when the caller-supplied ``raises(stmt, in_try)`` predicate says
so; the default is "contains a call, raise or assert". Analyzers pass
narrower predicates (e.g. page-lifecycle only treats ``raise``,
statements inside a ``try`` body, and calls to known-raising cache
APIs as throwing) so a ``logger.warning`` does not manufacture a
phantom leak path. An EXC edge means "the statement's effects did NOT
happen": the solver propagates the state from *before* the raising
statement, which the builder guarantees by placing every raising
statement in its own single-element block.

``try``/``finally`` is handled the way CPython compiles it: ``break``,
``continue`` and ``return`` that cross a ``finally`` re-emit (clone)
the finally body on that exit path, and exceptional paths route
through a once-built exceptional copy of the finally before escaping
outward. ``with`` bodies reuse the same machinery with a synthetic
``WithExit`` as their finally, so a lock held in a ``with`` is
provably released on every exit — including the exception edges.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

NORMAL = "normal"
BACK = "back"
EXC = "exc"


@dataclasses.dataclass
class WithEnter:
    """Synthetic element: control entered ``with <expr>`` (one marker
    per with-item). ``node`` is the ``withitem.context_expr``."""
    node: ast.expr
    is_async: bool
    lineno: int


@dataclasses.dataclass
class WithExit:
    """Synthetic element: the matching context manager exited (normal
    or exceptional path — __exit__ runs on both)."""
    node: ast.expr
    is_async: bool
    lineno: int


Element = object  # ast.stmt | WithEnter | WithExit


class Block:
    __slots__ = ("id", "elements", "succs")

    def __init__(self, block_id: int):
        self.id = block_id
        self.elements: List[Element] = []
        self.succs: List[Tuple["Block", str]] = []

    def edge(self, dst: "Block", kind: str = NORMAL) -> None:
        if (dst, kind) not in self.succs:
            self.succs.append((dst, kind))

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<Block {self.id} n={len(self.elements)}>"


def contains_call(stmt: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) for n in ast.walk(stmt))


def contains_await(stmt: ast.AST) -> bool:
    return any(isinstance(n, ast.Await) for n in ast.walk(stmt))


def default_raises(stmt: ast.AST, in_try: bool) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    return contains_call(stmt)


_CATCH_ALL_NAMES = {"Exception", "BaseException"}


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _CATCH_ALL_NAMES
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _CATCH_ALL_NAMES
                   for e in t.elts)
    return False


@dataclasses.dataclass
class _Frame:
    """One enclosing construct the builder must route exits through."""
    kind: str  # "loop" | "try" | "with"
    # loop targets
    head: Optional[Block] = None
    after: Optional[Block] = None
    # try: where a raise inside the body lands
    handler_entries: List[Block] = dataclasses.field(
        default_factory=list)
    # try: some handler is a catch-all (bare / Exception /
    # BaseException), so body exceptions cannot bypass the handlers.
    catches_all: bool = False
    # statements to re-emit when control leaves this frame early
    # (finally body, or the WithExit marker for a with).
    cleanup: List[Element] = dataclasses.field(default_factory=list)
    # exceptional continuation: block chain that runs the cleanup and
    # escapes outward. Built lazily, once per frame.
    exc_chain: Optional[Block] = None


class CFG:
    """Control-flow graph of one function body.

    ``raises(stmt, in_try)`` decides which statements get EXC edges.
    The builder guarantees every statement with EXC successors sits in
    a single-element block, so exception edges always observe the
    state *before* the statement (its effects did not happen).
    """

    def __init__(self, fn, raises: Callable[[ast.AST, bool], bool]
                 = default_raises):
        assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        self.fn = fn
        self._raises = raises
        self.blocks: List[Block] = []
        self.entry = self._new_block()
        self.exit = self._new_block()
        self.raise_exit = self._new_block()
        self._frames: List[_Frame] = []
        end = self._build_stmts(fn.body, self.entry)
        if end is not None:
            end.edge(self.exit)

    # ---- construction ---------------------------------------------------

    def _new_block(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def _in_try(self) -> bool:
        return any(f.kind == "try" for f in self._frames)

    def _exc_targets(self) -> List[Block]:
        """Where an exception raised *here* can land: every handler of
        the innermost try, plus the cleanup chain that escapes the
        innermost frame outward (unless a catch-all handler makes
        bypass impossible)."""
        for frame in reversed(self._frames):
            if frame.kind == "try" and frame.handler_entries:
                targets = list(frame.handler_entries)
                if not frame.catches_all:
                    targets.append(self._escape_chain(frame))
                return targets
            if frame.cleanup:
                return [self._escape_chain(frame)]
        return [self.raise_exit]

    def _escape_chain(self, frame: _Frame) -> Block:
        """Lazily build ``frame``'s exceptional continuation: run its
        cleanup, then keep escaping through the enclosing frames.

        Out-edges are NORMAL, not EXC: the raise already happened at
        the statement that routed here, and the cleanup elements in
        this block must take effect before the state reaches the next
        handler or the exceptional exit.
        """
        if frame.exc_chain is None:
            b = self._new_block()
            frame.exc_chain = b
            b.elements.extend(frame.cleanup)
            idx = self._frames.index(frame)
            outer = self._frames[:idx]
            target = self.raise_exit
            for out in reversed(outer):
                if out.kind == "try" and out.handler_entries:
                    # Escaping exception may be caught one level up.
                    for h in out.handler_entries:
                        b.edge(h)
                    if out.catches_all:
                        return frame.exc_chain
                if out.cleanup:
                    target = self._escape_chain(out)
                    break
            b.edge(target)
        return frame.exc_chain

    def _route_cleanups(self, src: Block, upto: Optional[_Frame],
                        target: Block, kind: str = NORMAL) -> None:
        """Early exit (break/continue/return): clone the cleanup
        elements of every frame between the current one and ``upto``
        (exclusive; None = all frames) onto the path ``src ->
        target``."""
        cleanups: List[Element] = []
        for frame in reversed(self._frames):
            if frame is upto:
                break
            cleanups.extend(frame.cleanup)
        if cleanups:
            chain = self._new_block()
            chain.elements.extend(cleanups)
            src.edge(chain)
            chain.edge(target, kind)
        else:
            src.edge(target, kind)

    def _emit(self, stmt: ast.stmt, cur: Block) -> Block:
        """Append a simple statement, isolating raisers in their own
        block so EXC edges see pre-statement state."""
        if self._raises(stmt, self._in_try()):
            box = self._new_block()
            cur.edge(box)
            box.elements.append(stmt)
            for t in self._exc_targets():
                box.edge(t, EXC)
            nxt = self._new_block()
            box.edge(nxt)
            return nxt
        cur.elements.append(stmt)
        return cur

    def _build_stmts(self, stmts: List[ast.stmt],
                     cur: Optional[Block]) -> Optional[Block]:
        """Returns the open fallthrough block, or None if control
        cannot reach past ``stmts``."""
        for stmt in stmts:
            if cur is None:
                return None  # unreachable code: stop building
            cur = self._build_stmt(stmt, cur)
        return cur

    def _build_stmt(self, stmt: ast.stmt,
                    cur: Block) -> Optional[Block]:
        if isinstance(stmt, (ast.If,)):
            body = self._new_block()
            cur.edge(body)
            body_end = self._build_stmts(stmt.body, body)
            after = self._new_block()
            if stmt.orelse:
                orelse = self._new_block()
                cur.edge(orelse)
                orelse_end = self._build_stmts(stmt.orelse, orelse)
                if orelse_end is not None:
                    orelse_end.edge(after)
            else:
                cur.edge(after)
            if body_end is not None:
                body_end.edge(after)
            return after

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._new_block()
            # Loop heads carry the test/iter statement itself so
            # analyzers can see the names it reads.
            head.elements.append(stmt)
            cur.edge(head)
            after = self._new_block()
            frame = _Frame(kind="loop", head=head, after=after)
            self._frames.append(frame)
            body = self._new_block()
            head.edge(body)
            body_end = self._build_stmts(stmt.body, body)
            self._frames.pop()
            if body_end is not None:
                body_end.edge(head, BACK)
            if stmt.orelse:
                orelse = self._new_block()
                head.edge(orelse)
                orelse_end = self._build_stmts(stmt.orelse, orelse)
                if orelse_end is not None:
                    orelse_end.edge(after)
            else:
                head.edge(after)
            return after

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            is_async = isinstance(stmt, ast.AsyncWith)
            enters = [WithEnter(item.context_expr, is_async,
                                stmt.lineno)
                      for item in stmt.items]
            exits = [WithExit(item.context_expr, is_async, stmt.lineno)
                     for item in reversed(stmt.items)]
            # Entering the context can raise (context_expr is a call).
            cur = self._emit(stmt_expr_of(stmt), cur)
            cur.elements.extend(enters)
            frame = _Frame(kind="with", cleanup=list(exits))
            self._frames.append(frame)
            body_end = self._build_stmts(stmt.body, cur)
            self._frames.pop()
            if body_end is None:
                return None
            body_end.elements.extend(exits)
            nxt = self._new_block()
            body_end.edge(nxt)
            return nxt

        if isinstance(stmt, ast.Try):
            handler_entries = [self._new_block()
                               for _ in stmt.handlers]
            frame = _Frame(kind="try", handler_entries=handler_entries,
                           catches_all=any(_is_catch_all(h)
                                           for h in stmt.handlers),
                           cleanup=list(stmt.finalbody))
            after = self._new_block()
            self._frames.append(frame)
            body = self._new_block()
            cur.edge(body)
            body_end = self._build_stmts(stmt.body, body)
            if body_end is not None and stmt.orelse:
                body_end = self._build_stmts(stmt.orelse, body_end)
            self._frames.pop()
            # Handlers run OUTSIDE the protected region (an exception
            # inside a handler escapes this try) but inside the
            # finally frame.
            fin_frame = None
            if stmt.finalbody:
                fin_frame = _Frame(kind="with",
                                   cleanup=list(stmt.finalbody))
                self._frames.append(fin_frame)
            handler_ends = []
            for h, entry in zip(stmt.handlers, handler_entries):
                handler_ends.append(
                    self._build_stmts(h.body, entry))
            if fin_frame is not None:
                self._frames.pop()
            # Normal completion and handler completion both run the
            # finally once, then continue to ``after``.
            tails = [e for e in ([body_end] + handler_ends)
                     if e is not None]
            if not tails and not stmt.finalbody:
                return None
            if stmt.finalbody:
                fin = self._new_block()
                for t in tails:
                    t.edge(fin)
                fin_end = self._build_stmts(stmt.finalbody, fin)
                if fin_end is None or not tails:
                    return None
                fin_end.edge(after)
            else:
                for t in tails:
                    t.edge(after)
            return after

        if isinstance(stmt, ast.Return):
            box = self._new_block()
            cur.edge(box)
            box.elements.append(stmt)
            self._route_cleanups(box, None, self.exit)
            return None

        if isinstance(stmt, ast.Break):
            frame = self._innermost_loop()
            if frame is not None:
                self._route_cleanups(cur, frame, frame.after)
            return None

        if isinstance(stmt, ast.Continue):
            frame = self._innermost_loop()
            if frame is not None:
                self._route_cleanups(cur, frame, frame.head, BACK)
            return None

        if isinstance(stmt, ast.Raise):
            box = self._new_block()
            cur.edge(box)
            box.elements.append(stmt)
            for t in self._exc_targets():
                box.edge(t, EXC)
            return None

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # Nested definitions are opaque single statements here;
            # analyze them with their own CFG if needed.
            cur.elements.append(stmt)
            return cur

        return self._emit(stmt, cur)

    def _innermost_loop(self) -> Optional[_Frame]:
        for frame in reversed(self._frames):
            if frame.kind == "loop":
                return frame
        return None

    # ---- queries --------------------------------------------------------

    def reachable(self) -> List[Block]:
        seen = {self.entry.id}
        order = [self.entry]
        stack = [self.entry]
        while stack:
            b = stack.pop()
            for dst, _ in b.succs:
                if dst.id not in seen:
                    seen.add(dst.id)
                    order.append(dst)
                    stack.append(dst)
        return order

    def back_edges(self) -> List[Tuple[Block, Block]]:
        return [(b, dst) for b in self.blocks
                for dst, kind in b.succs if kind == BACK]


class _WithHead(ast.stmt):
    pass


def stmt_expr_of(with_stmt) -> ast.stmt:
    """A synthetic statement holding a with-statement's context
    expressions, so entering the with can carry EXC edges without
    re-walking its whole body."""
    expr = ast.Expr(value=ast.Tuple(
        elts=[item.context_expr for item in with_stmt.items],
        ctx=ast.Load()))
    ast.copy_location(expr, with_stmt)
    ast.fix_missing_locations(expr)
    return expr


def function_defs(tree: ast.AST):
    """Every (async) function definition in ``tree``, including
    nested ones."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
