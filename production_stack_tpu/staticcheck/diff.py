"""``--diff <ref>`` support: restrict findings to touched lines.

Pre-commit latency must not grow with the rule count, so the CLI can
filter findings to those on lines changed vs a base ref. The full
analysis still runs (rules are cross-file contracts — a route added
in server.py fires a finding anchored in fake_engine.py), only the
*reporting* is filtered:

- a finding with a line number survives if its file is in the diff
  and its line is inside a changed hunk;
- a line-0 (file/project-contract) finding survives if its file is in
  the diff at all — contract findings have no better anchor, and
  hiding them on a touched file would let a PR break a contract
  invisibly.

Parsing is ``git diff -U0 <ref>`` hunk headers only (``+++ b/path``,
``@@ -a,b +c,d @@``): zero context means changed-line ranges are
exact.
"""

from __future__ import annotations

import re
import subprocess
from typing import Dict, Iterable, List, Set

from production_stack_tpu.staticcheck.core import Finding

_FILE_RE = re.compile(r"^\+\+\+ b/(.+)$")
_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def parse_unified_diff(text: str) -> Dict[str, Set[int]]:
    """{path: changed line numbers (new side)} from ``-U0`` output.
    A file that only lost lines maps to an empty set — it is still
    'touched'."""
    changed: Dict[str, Set[int]] = {}
    current: str = ""
    for line in text.splitlines():
        m = _FILE_RE.match(line)
        if m:
            current = m.group(1)
            changed.setdefault(current, set())
            continue
        m = _HUNK_RE.match(line)
        if m and current:
            start = int(m.group(1))
            # "+N" means one line; "+N,0" is a pure deletion — the
            # file is touched but no new-side lines exist.
            count = int(m.group(2)) if m.group(2) is not None else 1
            changed[current].update(range(start, start + count))
    return changed


def changed_lines(root, ref: str) -> Dict[str, Set[int]]:
    """Run ``git diff -U0 <ref>`` in ``root`` and parse it. Raises
    RuntimeError (for the CLI's usage-error exit) when git fails —
    e.g. an unknown ref."""
    proc = subprocess.run(
        ["git", "diff", "-U0", ref, "--"],
        cwd=str(root), capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"git diff -U0 {ref} failed: "
            f"{proc.stderr.strip() or proc.stdout.strip()}")
    return parse_unified_diff(proc.stdout)


def filter_findings(findings: Iterable[Finding],
                    changed: Dict[str, Set[int]]) -> List[Finding]:
    out: List[Finding] = []
    for f in findings:
        lines = changed.get(f.path)
        if lines is None:
            continue
        if f.line == 0 or f.line in lines:
            out.append(f)
    return out
