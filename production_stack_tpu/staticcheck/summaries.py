"""Bottom-up function summaries over the project call graph.

``callgraph.py`` gives the edges; this module computes, per function,
the facts the interprocedural rules consume — processed SCC by SCC in
reverse topological order (callees first), iterating each SCC to a
fixpoint so mutual recursion converges instead of recursing:

- **may_block**: the function can execute a blocking primitive
  (``time.sleep``, sync ``requests``, ``subprocess.run``, sync
  ``open`` — the ``async-blocking`` vocabulary) on its own frame or
  through any *resolved* callee. Carried as a chain of
  ``(path, line, label)`` frames down to the primitive so the finding
  at an ``async def`` call site can print the whole path.
- **may_host_sync**: same shape, for device->host syncs
  (``.item()``, ``jax.device_get``, ``.block_until_ready()``).
- **may_raise**: exception type names the function can raise,
  transitively through resolved callees. The page-lifecycle rule
  turns "calls a function that may raise" into CFG exception edges —
  proving cleanup instead of assuming helpers are total.
- **consumed_params / returns_alloc**: page-ownership in/out. A
  parameter is *consumed* when the callee may take custody of it
  (stores it, returns it, passes it onward to a consuming or
  unresolved callee); it is provably **non-custodial** only when
  every use is a read (comparisons, ``len()``-class builtins,
  resolved non-consuming callees). ``returns_alloc`` marks functions
  whose return value is a fresh ``allocate_pages`` result, so an
  allocation two frames deep still creates a leak fact at the caller.

Soundness stance (see docs/static_analysis.md): facts that *create*
findings (may_block, may_host_sync) propagate only through resolved
edges — an unresolved edge can never manufacture a finding. Facts
that *suppress* findings (consumed_params) treat unresolved callees
as consuming — an unresolved edge can never manufacture a finding
there either. All lattices are finite and grow monotonically, so the
per-SCC fixpoint terminates.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

from production_stack_tpu.staticcheck import callgraph

Frame = Tuple[str, int, str]  # (path, 1-based line, label)

# Builtins that only *read* their arguments — passing a tracked value
# to one of these is not a transfer of custody.
READONLY_BUILTINS = frozenset({
    "len", "print", "repr", "str", "format", "isinstance", "bool",
    "sum", "min", "max", "any", "all", "sorted", "enumerate", "id",
    "hash", "abs", "round", "int", "float",
})

# Host-sync primitives (the host-read / tracer-hygiene vocabulary).
_HOST_SYNC_TAILS = {"device_get", "block_until_ready", "item"}


@dataclasses.dataclass(frozen=True)
class FunctionSummary:
    qual: str
    may_block: Optional[Tuple[Frame, ...]] = None
    may_host_sync: Optional[Tuple[Frame, ...]] = None
    may_raise: FrozenSet[str] = frozenset()
    consumed_params: FrozenSet[str] = frozenset()
    returns_alloc: bool = False


_EMPTY = FunctionSummary(qual="")


def own_body_nodes(fn_node):
    """Every AST node on the function's own frame — nested def/class
    bodies excluded (their effects belong to their own summaries)."""
    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            yield child
            yield from visit(child)
    yield from visit(fn_node)


def _tail(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def host_sync_reason(call: ast.Call) -> str:
    """Why this call syncs device->host ('' if it doesn't)."""
    func = call.func
    name = _tail(func)
    if name == "device_get":
        return "jax.device_get blocks on device results"
    if isinstance(func, ast.Attribute):
        if name == "block_until_ready":
            return ".block_until_ready() is a host sync"
        if name == "item":
            return ".item() is a device->host sync"
    return ""


def _short(text: str, limit: int = 48) -> str:
    return text if len(text) <= limit else text[:limit - 1] + "…"


class Summaries:
    """Summary table for one project; build via :func:`for_project`."""

    def __init__(self, graph: callgraph.CallGraph):
        self.graph = graph
        self.by_qual: Dict[str, FunctionSummary] = {}
        self._compute()

    # ---- queries --------------------------------------------------------

    def get(self, qual: Optional[str]) -> FunctionSummary:
        if qual is None:
            return _EMPTY
        return self.by_qual.get(qual, _EMPTY)

    def for_edge(self, edge: callgraph.CallEdge) -> FunctionSummary:
        return self.get(edge.callee)

    # ---- computation ----------------------------------------------------

    def _compute(self) -> None:
        graph = self.graph
        for qual in graph.functions:
            self.by_qual[qual] = FunctionSummary(qual=qual)
        for scc in graph.sccs():
            # Monotone lattices: the raise/custody sets only grow and
            # chains always take the shortest candidate, so the
            # fixpoint converges; the iteration cap is a pure backstop.
            for _ in range(64):
                changed = False
                for qual in scc:
                    new = self._summarize(qual)
                    if new != self.by_qual[qual]:
                        self.by_qual[qual] = new
                        changed = True
                if not changed:
                    break
                if len(scc) == 1 and not self._self_recursive(scc[0]):
                    break

    def _self_recursive(self, qual: str) -> bool:
        return any(e.callee == qual
                   for e in self.graph.edges_from(qual))

    def _summarize(self, qual: str) -> FunctionSummary:
        info = self.graph.functions[qual]
        fn = info.node
        edges_by_call = {id(e.call): e
                         for e in self.graph.edges_from(qual)}

        block_candidates: List[Tuple[Frame, ...]] = []
        sync_candidates: List[Tuple[Frame, ...]] = []
        may_raise: set = set()

        # Lazy import: async_blocking imports this module at top
        # level; by the time summaries are *computed* both are loaded.
        from production_stack_tpu.staticcheck.analyzers import (
            async_blocking,
        )

        for node in own_body_nodes(fn):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                name = _tail(exc)
                if name:
                    may_raise.add(name)
            if not isinstance(node, ast.Call):
                continue
            if async_blocking.blocking_reason(node):
                block_candidates.append(
                    ((info.path, node.lineno,
                      _short(callgraph._dotted(node.func) + "()")),))
            if host_sync_reason(node):
                sync_candidates.append(
                    ((info.path, node.lineno,
                      _short(callgraph._dotted(node.func) + "()")),))
            edge = edges_by_call.get(id(node))
            if edge is None or edge.callee is None:
                continue
            callee = self.by_qual.get(edge.callee, _EMPTY)
            callee_info = self.graph.functions.get(edge.callee)
            label = (callee_info.label() if callee_info
                     else edge.target_text)
            site: Frame = (info.path, node.lineno, label)
            if callee.may_block is not None:
                block_candidates.append((site,) + callee.may_block)
            if callee.may_host_sync is not None:
                sync_candidates.append((site,) + callee.may_host_sync)
            may_raise |= callee.may_raise

        # Shortest chain wins — keeps recursive SCCs convergent and
        # the rendered path maximally direct.
        may_block = min(block_candidates, key=lambda c: (len(c), c),
                        default=None)
        may_host_sync = min(sync_candidates,
                            key=lambda c: (len(c), c), default=None)
        consumed = self._consumed_params(info, edges_by_call)
        returns_alloc = self._returns_alloc(fn, edges_by_call)
        return FunctionSummary(
            qual=qual,
            may_block=may_block,
            may_host_sync=may_host_sync,
            may_raise=frozenset(may_raise),
            consumed_params=consumed,
            returns_alloc=returns_alloc,
        )

    # ---- page ownership -------------------------------------------------

    def _param_names(self, fn) -> List[str]:
        args = fn.args
        return [a.arg for a in (args.posonlyargs + args.args
                                + args.kwonlyargs)]

    def callee_param_for_arg(self, edge: callgraph.CallEdge,
                              pos: int,
                              kw: Optional[str]) -> Optional[str]:
        """Map an actual argument (position or keyword) to the callee
        parameter name, accounting for the bound ``self``/``cls`` of
        method-style calls. None when unmappable."""
        callee_info = self.graph.functions.get(edge.callee or "")
        if callee_info is None:
            return None
        params = self._param_names(callee_info.node)
        if kw is not None:
            return kw if kw in params else None
        offset = 0
        if callee_info.class_name and params \
                and params[0] in ("self", "cls") \
                and isinstance(edge.call.func, ast.Attribute):
            offset = 1
        idx = pos + offset
        return params[idx] if idx < len(params) else None

    def _consumed_params(self, info: callgraph.FunctionInfo,
                         edges_by_call: Dict[int, callgraph.CallEdge]
                         ) -> FrozenSet[str]:
        """Parameters that may leave the callee's frame (custody)."""
        fn = info.node
        params = set(self._param_names(fn))
        params.discard("self")
        params.discard("cls")
        if not params:
            return frozenset()
        consumed: set = set()

        def refs(node) -> set:
            return {n.id for n in ast.walk(node)
                    if isinstance(n, ast.Name) and n.id in params}

        # Captured by a nested def -> custody unknowable, be safe.
        for child in ast.walk(fn):
            if child is fn:
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                consumed |= {n.id for n in ast.walk(child)
                             if isinstance(n, ast.Name)
                             and n.id in params}

        for node in own_body_nodes(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.Return,
                                 ast.Yield, ast.YieldFrom, ast.Raise,
                                 ast.withitem, ast.Delete)):
                consumed |= refs(node)
            elif isinstance(node, ast.Call):
                edge = edges_by_call.get(id(node))
                builtin_ok = (edge is not None
                              and edge.kind == "builtin"
                              and edge.target_text in
                              READONLY_BUILTINS)
                resolved = (edge is not None
                            and edge.callee is not None)
                # Receiver custody: p.method(...) may retain p.
                recv = node.func
                if isinstance(recv, ast.Attribute):
                    consumed |= refs(recv.value)
                for pos, arg in enumerate(node.args):
                    for name in refs(arg):
                        if builtin_ok:
                            continue
                        if resolved and isinstance(arg, ast.Name):
                            callee_param = self.callee_param_for_arg(
                                edge, pos, None)
                            callee_sum = self.get(edge.callee)
                            if callee_param is not None and \
                                    callee_param not in \
                                    callee_sum.consumed_params:
                                continue
                        consumed.add(name)
                for kwnode in node.keywords:
                    for name in refs(kwnode.value):
                        if builtin_ok:
                            continue
                        if resolved and kwnode.arg is not None and \
                                isinstance(kwnode.value, ast.Name):
                            callee_param = self.callee_param_for_arg(
                                edge, 0, kwnode.arg)
                            callee_sum = self.get(edge.callee)
                            if callee_param is not None and \
                                    callee_param not in \
                                    callee_sum.consumed_params:
                                continue
                        consumed.add(name)
        return frozenset(consumed & params)

    def _returns_alloc(self, fn,
                       edges_by_call: Dict[int, callgraph.CallEdge]
                       ) -> bool:
        """Does this function return a fresh allocate_pages result
        (directly, via list()/tuple(), or via a callee that does)?"""
        for node in own_body_nodes(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            value = node.value
            if isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Name) and \
                    value.func.id in ("list", "tuple") and value.args:
                value = value.args[0]
            if not isinstance(value, ast.Call):
                continue
            if _tail(value.func) == "allocate_pages":
                return True
            edge = edges_by_call.get(id(value)) or \
                edges_by_call.get(id(node.value))
            if edge is not None and edge.callee is not None and \
                    self.get(edge.callee).returns_alloc:
                return True
        return False


def for_project(project) -> Summaries:
    """Build (once) and memoize summaries on the project."""
    sums = getattr(project, "_summaries", None)
    if sums is None:
        lock = getattr(project, "_ipc_lock", None)
        if lock is not None:
            with lock:
                sums = getattr(project, "_summaries", None)
                if sums is None:
                    sums = Summaries(callgraph.for_project(project))
                    project._summaries = sums
        else:
            sums = Summaries(callgraph.for_project(project))
            project._summaries = sums
    return sums
