"""Cluster-wide KV economy (docs/kv_economy.md).

The KV cache stops being per-engine scratch and becomes a cluster
resource with an explicit economy:

- ``summary``: the text-domain chain-hash scheme shared by the router
  and the engines, plus the engine-side ``PrefixSummaryTracker`` that
  maintains the hot-chain summary exported at ``GET /kv/summary``.
- ``cluster_cache``: the managed shared-cache policy object
  (``ManagedKVStore``) behind the cache server — hit-count admission,
  TTL + LRU eviction under capacity watermarks, per-chain metadata.

The router's ``KVStateAwarePolicy`` (router/routing/logic.py) scores
candidates against the summaries; the engines' offload clients
(engine/offload.py) speak the admission protocol to the shared tier.
"""

from production_stack_tpu.kvecon.cluster_cache import (  # noqa: F401
    CHAIN_HEADER,
    REQUESTER_HEADER,
    ChainMeta,
    ManagedKVStore,
)
from production_stack_tpu.kvecon.summary import (  # noqa: F401
    BLOCK_CHARS,
    TOKENS_PER_BLOCK,
    PrefixSummaryTracker,
    chain_text,
    expected_hit_blocks,
    routable_text,
)
