"""Managed shared-cache policy for the cluster cache server.

``ManagedKVStore`` replaces the cache server's plain write-through
LRU (engine/cache_server.py) with an explicit economy:

- **Admission by demand promotion.** A chain's pages are accepted
  only after the chain has been *wanted* by ``admit_hits`` distinct
  requesters (engines / requests) — recorded on PUTs and on probe or
  fetch misses. A chain computed once and never asked for again never
  displaces genuinely shared prefixes. Rejected PUTs return
  ``{"admitted": false}`` with HTTP 200; the engine-side client
  treats that as success (satellite: no retry storm).
- **TTL + watermark eviction, coldest chains whole.** When stored
  bytes exceed ``watermark_high * max_bytes``, chains are evicted in
  coldest-first order (least-recent access) down to
  ``watermark_low * max_bytes``. Pages of a chain live and die
  together: a chain with its middle evicted is useless to the
  restore path (``lookup_chain`` walks parent→child), so partial
  eviction would waste both the bytes kept and the fetches spent.
- **Per-chain metadata** (hits, distinct requesters, last access,
  byte size, kv_dtype) for /stats and the kvcache:* metrics.

Chain grouping: the engine tags uploads with ``X-KV-Chain`` (the
stable key of the chain's ROOT page hash). Untagged pages form a
singleton chain keyed by their own key, which degrades exactly to
per-page LRU — legacy clients keep working.

The store is policy only — no HTTP here. ``clock`` is injectable so
tests can drive TTL/eviction state machines deterministically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

CHAIN_HEADER = "X-KV-Chain"
REQUESTER_HEADER = "X-KV-Requester"


@dataclass
class ChainMeta:
    """Bookkeeping for one admitted (or still-courting) chain."""

    chain_id: str
    bytes: int = 0
    hits: int = 0
    last_access: float = 0.0
    kv_dtype: str = ""
    keys: List[str] = field(default_factory=list)
    requesters: Set[str] = field(default_factory=set)

    @property
    def demand(self) -> int:
        return len(self.requesters)


class ManagedKVStore:
    """Thread-safe shared prefix cache with admission and eviction."""

    def __init__(self, max_bytes: int, admit_hits: int = 2,
                 ttl_s: float = 900.0, watermark_high: float = 0.95,
                 watermark_low: float = 0.80, clock=time.monotonic):
        if not 0.0 < watermark_low <= watermark_high <= 1.0:
            raise ValueError(
                "require 0 < watermark_low <= watermark_high <= 1, got "
                f"low={watermark_low} high={watermark_high}")
        self.max_bytes = int(max_bytes)
        self.admit_hits = max(1, int(admit_hits))
        self.ttl_s = float(ttl_s)
        self.watermark_high = float(watermark_high)
        self.watermark_low = float(watermark_low)
        self._clock = clock
        self._lock = threading.Lock()
        self._blobs: Dict[str, bytes] = {}
        self._key_chain: Dict[str, str] = {}
        self._chains: Dict[str, ChainMeta] = {}
        # chain_id -> requesters wanting a chain we don't hold yet
        # (demand survives rejected PUTs so promotion can happen).
        self._courting: Dict[str, Tuple[Set[str], float]] = {}
        self.hits = 0
        self.misses = 0
        self.admissions = 0
        self.evictions = 0
        self.rejected_puts = 0

    # -- internals (call with lock held) --------------------------------

    def _bytes_stored(self) -> int:
        return sum(m.bytes for m in self._chains.values())

    def _chain_for(self, key: str, chain_id: Optional[str]) -> str:
        return chain_id or self._key_chain.get(key) or key

    def _record_demand(self, chain_id: str, requester: str,
                       now: float) -> int:
        meta = self._chains.get(chain_id)
        if meta is not None:
            meta.requesters.add(requester)
            return meta.demand
        reqs, _ = self._courting.get(chain_id, (set(), now))
        reqs.add(requester)
        self._courting[chain_id] = (reqs, now)
        if len(self._courting) > 65536:  # bound courting-table memory
            oldest = sorted(self._courting.items(),
                            key=lambda kv: kv[1][1])
            for cid, _ in oldest[:len(oldest) // 2]:
                del self._courting[cid]
        return len(reqs)

    def _drop_chain(self, chain_id: str) -> None:
        meta = self._chains.pop(chain_id, None)
        if meta is None:
            return
        for k in meta.keys:
            self._blobs.pop(k, None)
            self._key_chain.pop(k, None)

    def _sweep(self, now: float) -> None:
        if self.ttl_s > 0:
            for cid in [c for c, m in self._chains.items()
                        if now - m.last_access > self.ttl_s]:
                self._drop_chain(cid)
                self.evictions += 1
            for cid in [c for c, (_, t) in self._courting.items()
                        if now - t > self.ttl_s]:
                del self._courting[cid]
        high = self.watermark_high * self.max_bytes
        if self._bytes_stored() <= high:
            return
        low = self.watermark_low * self.max_bytes
        for cid in sorted(self._chains,
                          key=lambda c: self._chains[c].last_access):
            if self._bytes_stored() <= low:
                break
            self._drop_chain(cid)
            self.evictions += 1

    # -- public API ------------------------------------------------------

    def put(self, key: str, blob: bytes, chain_id: Optional[str] = None,
            requester: str = "", kv_dtype: str = "") -> bool:
        """Store a page; returns the admission verdict."""
        now = self._clock()
        requester = requester or "anon"
        with self._lock:
            cid = self._chain_for(key, chain_id)
            demand = self._record_demand(cid, requester, now)
            meta = self._chains.get(cid)
            if meta is None and demand < self.admit_hits:
                self.rejected_puts += 1
                return False
            if meta is None:
                reqs, _ = self._courting.pop(cid, (set(), now))
                meta = ChainMeta(chain_id=cid, kv_dtype=kv_dtype,
                                 requesters=reqs or {requester})
                self._chains[cid] = meta
                self.admissions += 1
            old = self._blobs.get(key)
            if old is not None:
                meta.bytes -= len(old)
            else:
                meta.keys.append(key)
            self._blobs[key] = blob
            self._key_chain[key] = cid
            meta.bytes += len(blob)
            meta.last_access = now
            self._sweep(now)
            # The new chain itself may have been swept if it alone
            # overshoots capacity; report what actually happened.
            return key in self._blobs

    def get(self, key: str, requester: str = "") -> Optional[bytes]:
        now = self._clock()
        with self._lock:
            self._sweep(now)
            blob = self._blobs.get(key)
            cid = self._chain_for(key, None)
            if blob is None:
                self.misses += 1
                self._record_demand(cid, requester or "anon", now)
                return None
            self.hits += 1
            meta = self._chains.get(cid)
            if meta is not None:
                meta.hits += 1
                meta.last_access = now
                if requester:
                    meta.requesters.add(requester)
            return blob

    def contains(self, key: str, requester: str = "") -> bool:
        """Probe (HEAD) — a miss records demand toward admission."""
        now = self._clock()
        with self._lock:
            self._sweep(now)
            if key in self._blobs:
                cid = self._chain_for(key, None)
                meta = self._chains.get(cid)
                if meta is not None:
                    meta.last_access = now
                return True
            self._record_demand(key, requester or "anon", now)
            return False

    def associate(self, key: str, chain_id: str) -> None:
        """Merge demand recorded under a bare page key into its chain
        (a probe miss only knows the key; the PUT knows the chain)."""
        with self._lock:
            if key == chain_id or key not in self._courting:
                return
            reqs, t = self._courting.pop(key)
            held, t2 = self._courting.get(chain_id, (set(), t))
            self._courting[chain_id] = (held | reqs, max(t, t2))

    def sweep(self) -> None:
        with self._lock:
            self._sweep(self._clock())

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._blobs),
                "bytes": self._bytes_stored(),
                "max_bytes": self.max_bytes,
                "chains": len(self._chains),
                "courting_chains": len(self._courting),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / total if total else 0.0,
                "admissions": self.admissions,
                "evictions": self.evictions,
                "rejected_puts": self.rejected_puts,
                "admit_hits": self.admit_hits,
                "ttl_s": self.ttl_s,
                "watermark_high": self.watermark_high,
                "watermark_low": self.watermark_low,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._blobs)
