"""Text-domain prefix chains and the engine's hot-chain summary.

Two hash domains coexist in the KV economy, one per purpose:

- Tier KEYS (engine/offload.py ``_stable_key``) are token-domain
  sha256 over the page's content chain — they address byte payloads
  and must be exact.
- Routing SUMMARIES live in the TEXT domain: the router cannot
  tokenize, so both sides chain-hash the request's prompt text in
  fixed-size character blocks with blake2b (the scheme
  ``PrefixAwarePolicy`` introduced). The engine observes the same
  canonical text the router routes on (``routable_text``), so a chain
  hash computed by the router for an incoming prompt is directly
  comparable against the hot chains an engine advertises at
  ``GET /kv/summary``.

Everything here is dependency-free and cheap: one blake2b pass per
request, no per-step cost.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

# ~64 tokens per block at 4 chars/token; must match
# PrefixAwarePolicy.BLOCK_CHARS (router/routing/logic.py delegates
# here so the two can never drift).
BLOCK_CHARS = 256
TOKENS_PER_BLOCK = BLOCK_CHARS // 4


def chain_text(text: str, block_chars: int = BLOCK_CHARS) -> List[int]:
    """Chained blake2b over fixed-size character blocks.

    blake2b, not builtin ``hash()``: str hashing is salted per process
    (PYTHONHASHSEED), so replicated routers and engines would score
    the same prefix with different chains. The chain must be a pure
    function of the text — verified across interpreters by
    tests/test_routing_logic.py.
    """
    out: List[int] = []
    h = b""
    for i in range(0, len(text), block_chars):
        block = text[i:i + block_chars]
        h = hashlib.blake2b(
            h + block.encode("utf-8", "surrogatepass"),
            digest_size=8,
        ).digest()
        out.append(int.from_bytes(h, "big"))
    return out


def routable_text(payload: dict) -> Optional[str]:
    """Stable text rendering of a request's prompt (chat history or
    completion prompt; None when the body carries neither).

    This is the canonical form BOTH sides hash: the router renders it
    from the request body before routing
    (router/services/request_service.py), and the engine server
    renders it from the same body shape when updating its summary —
    the \\x1f/\\x1e separators make the rendering injective so
    "role+content" boundaries can't alias across messages.
    """
    messages = payload.get("messages")
    if isinstance(messages, list):
        parts = []
        for m in messages:
            if isinstance(m, dict) and isinstance(m.get("content"), str):
                parts.append(f"{m.get('role', '')}\x1f{m['content']}")
        return "\x1e".join(parts) if parts else None
    prompt = payload.get("prompt")
    if isinstance(prompt, str):
        return prompt
    if isinstance(prompt, list) and prompt and \
            all(isinstance(p, str) for p in prompt):
        return "\x1e".join(prompt)
    return None


def expected_hit_blocks(chains: List[int],
                        hot: Iterable[int]) -> int:
    """Expected prefix-hit depth of a prompt against a hot-chain set.

    Chain hash i commits to the ENTIRE prefix up to block i, so the
    deepest advertised hash alone determines the match depth — the
    summary's top-k may have decayed intermediate blocks out, which
    must not truncate the estimate.
    """
    hot_set = set(hot)
    best = 0
    for i, h in enumerate(chains):
        if h in hot_set:
            best = i + 1
    return best


class PrefixSummaryTracker:
    """Hit-count-decayed top-k hot chains served by this engine.

    The engine server feeds every request's routable text through
    ``observe_text``; ``snapshot`` returns the admitted hot chains
    (``[[chain_hash, decayed_hits], ...]``) for ``GET /kv/summary``.

    Economy knobs (EngineConfig.kvecon, docs/kv_economy.md):
    - ``admit_hits``: a chain is advertised only once its decayed hit
      count reaches this floor — a prefix seen once is not "hot", and
      advertising it would pull follow-up traffic toward KV that was
      probably never worth keeping.
    - ``ttl_s``: chains idle longer than this are dropped outright
      (0 disables).
    - Hits decay exponentially with ``HALF_LIFE_S`` so the summary
      tracks what is hot NOW, not what was hot an hour ago.

    ``clock`` is injectable for deterministic tests.
    """

    HALF_LIFE_S = 300.0
    # Bounded memory: at most this many tracked chains per top_k slot.
    CAPACITY_FACTOR = 8

    def __init__(self, top_k: int = 64, admit_hits: int = 2,
                 ttl_s: float = 900.0, clock=time.monotonic):
        self.top_k = max(1, int(top_k))
        self.admit_hits = max(1, int(admit_hits))
        self.ttl_s = float(ttl_s)
        self._clock = clock
        # chain_hash -> [decayed_hits_at_last_seen, last_seen]
        self._chains: Dict[int, List[float]] = {}
        self._lock = threading.Lock()

    def _decayed(self, entry: List[float], now: float) -> float:
        hits, last = entry
        if now <= last:
            return hits
        return hits * 0.5 ** ((now - last) / self.HALF_LIFE_S)

    def observe_text(self, text: Optional[str]) -> None:
        if text:
            self.observe(chain_text(text))

    def observe(self, chains: List[int]) -> None:
        if not chains:
            return
        now = self._clock()
        with self._lock:
            for h in chains:
                entry = self._chains.get(h)
                if entry is None:
                    self._chains[h] = [1.0, now]
                else:
                    entry[0] = self._decayed(entry, now) + 1.0
                    entry[1] = now
            self._prune(now)

    def _prune(self, now: float) -> None:
        if self.ttl_s > 0:
            dead = [h for h, e in self._chains.items()
                    if now - e[1] > self.ttl_s]
            for h in dead:
                del self._chains[h]
        cap = self.top_k * self.CAPACITY_FACTOR
        if len(self._chains) > cap:
            ranked = sorted(self._chains.items(),
                            key=lambda kv: self._decayed(kv[1], now),
                            reverse=True)
            self._chains = dict(ranked[:cap])

    def snapshot(self) -> List[Tuple[int, float]]:
        """Admitted hot chains, hottest first: [(chain_hash, hits)]."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            hot = [(h, self._decayed(e, now))
                   for h, e in self._chains.items()]
        hot = [(h, round(v, 3)) for h, v in hot
               if v >= self.admit_hits]
        hot.sort(key=lambda kv: (-kv[1], kv[0]))
        return hot[:self.top_k]

    def hot_count(self) -> int:
        return len(self.snapshot())

    def __len__(self) -> int:
        with self._lock:
            return len(self._chains)
