"""Colored logging (parity with reference src/vllm_router/log.py)."""

import logging
import os
import sys

_RESET = "\x1b[0m"
_COLORS = {
    logging.DEBUG: "\x1b[38;20m",  # grey
    logging.INFO: "\x1b[32;20m",  # green
    logging.WARNING: "\x1b[33;20m",  # yellow
    logging.ERROR: "\x1b[31;20m",  # red
    logging.CRITICAL: "\x1b[31;1m",  # bold red
}
_FMT = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"


class ColorFormatter(logging.Formatter):
    def __init__(self, use_color: bool = True):
        super().__init__(_FMT)
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if self.use_color:
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def init_logger(name: str, level: str | int | None = None) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(ColorFormatter(use_color=sys.stderr.isatty()))
        logger.addHandler(handler)
        logger.propagate = False
    env_level = os.environ.get("PSTPU_LOG_LEVEL")
    logger.setLevel(level or env_level or logging.INFO)
    return logger
