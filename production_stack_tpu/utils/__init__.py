"""Shared utilities for the router and engine.

Capability parity with reference src/vllm_router/utils.py (SingletonMeta L10,
validate_url L42, set_ulimit L64, static list parsers L83-96), re-implemented.
"""

import abc
import re
import resource
import threading
from typing import Any, Dict, List, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_URL_RE = re.compile(
    r"^(https?)://"  # scheme
    r"([a-zA-Z0-9.\-_]+|\[[0-9a-fA-F:]+\])"  # host or ipv6
    r"(:\d{1,5})?"  # optional port
    r"(/.*)?$"  # optional path
)


class SingletonMeta(type):
    """Metaclass giving each class a single process-wide instance.

    Thread-safe; tests may clear ``SingletonMeta._instances`` to reset state.
    """

    _instances: Dict[type, Any] = {}
    _lock = threading.Lock()

    def __call__(cls, *args, **kwargs):
        with SingletonMeta._lock:
            if cls not in SingletonMeta._instances:
                SingletonMeta._instances[cls] = super().__call__(*args, **kwargs)
        return SingletonMeta._instances[cls]


class SingletonABCMeta(abc.ABCMeta, SingletonMeta):
    """Singleton metaclass for abstract base classes."""


def validate_url(url: str) -> bool:
    """Return True iff *url* is a well-formed http(s) URL."""
    return bool(_URL_RE.match(url))


def parse_comma_separated_urls(arg: Optional[str]) -> List[str]:
    """Parse ``--static-backends http://a:1,http://b:2`` style flags."""
    if not arg:
        return []
    urls = [u.strip().rstrip("/") for u in arg.split(",") if u.strip()]
    for url in urls:
        if not validate_url(url):
            raise ValueError(f"Invalid backend URL: {url!r}")
    return urls


def parse_comma_separated_values(arg: Optional[str]) -> List[str]:
    """Parse comma-separated plain values (model names, labels, ...)."""
    if not arg:
        return []
    return [v.strip() for v in arg.split(",") if v.strip()]


def set_ulimit(target_soft: int = 65535) -> None:
    """Raise RLIMIT_NOFILE soft limit so high-QPS proxying doesn't EMFILE."""
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < target_soft:
            resource.setrlimit(
                resource.RLIMIT_NOFILE, (min(target_soft, hard), hard)
            )
    except (ValueError, OSError) as e:  # pragma: no cover - platform dependent
        logger.warning("Could not raise ulimit -n: %s", e)
