"""Version-compatibility shims for the pinned accelerator stack."""

import jax


def shard_map(body, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` graduated out of ``jax.experimental`` (and
    renamed ``check_rep`` -> ``check_vma``) around jax 0.5; serve both
    spellings so the parallel layer runs on either runtime."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
