"""Architecture registry mapping config.architecture -> (init, forward)."""

from typing import Callable, Tuple

from production_stack_tpu.engine.config import ModelConfig


def get_model(config: ModelConfig) -> Tuple[Callable, Callable]:
    """Returns (init_params, forward) for the configured architecture."""
    arch = config.architecture
    if arch in ("llama", "mistral", "qwen2"):
        from production_stack_tpu.models import llama
        return llama.init_params, llama.forward
    if arch == "opt":
        from production_stack_tpu.models import opt
        return opt.init_params, opt.forward
    if arch == "gpt2":
        from production_stack_tpu.models import gpt2
        return gpt2.init_params, gpt2.forward
    if arch == "mixtral":
        from production_stack_tpu.models import mixtral
        return mixtral.init_params, mixtral.forward
    raise ValueError(f"Unknown architecture: {arch}")


def list_architectures():
    return ["llama", "mistral", "qwen2", "opt", "gpt2", "mixtral"]
