"""Llama-family model (Llama 2/3, Mistral, Qwen2-style GQA decoders).

Re-designed TPU-first rather than ported: parameters are stacked along a
leading layer axis and the decoder loop is STATICALLY UNROLLED so every
KV-cache update is an in-place scatter at a static layer index (scanning
layers with the cache as xs/ys makes XLA copy whole layer caches per
step); attention reads and writes the paged KV cache (ops/attention.py)
so prefill chunks and decode steps share one numerics path.

Capability parity: serves the model families the reference deploys via
vLLM (helm/values.yaml modelSpec examples: Llama-3, Mistral, TinyLlama).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.ops.attention import (
    paged_attention,
    write_to_pages,
    write_to_tail,
)
from production_stack_tpu.ops.rope import apply_rope

Params = Dict[str, jnp.ndarray]


def dispatch_attention(config: ModelConfig, q, k_cache, v_cache,
                       page_table, positions, kv_lens, layer=None):
    """Pick the attention implementation for this step shape.

    Under the pallas impl both shapes use page-walking kernels: decode
    (T==1) the online-softmax decode kernel, prefill chunks the
    chunked-prefill kernel (no materialized page gather). The XLA
    gather-based implementation is the CPU path and the ground truth.

    ``k_cache``/``v_cache`` are per-layer [kv, pages, d, p] slices
    when ``layer`` is None, or the full stacked [L, ...] caches with
    ``layer`` a static int — the stacked form is what the (unrolled)
    model loops use: the XLA path fuses the static slice into its
    gather and the Pallas kernels take the layer index through SMEM,
    so neither materializes a per-layer copy.

    Returns ``(attn, k_cache, v_cache)``. The returned caches are the
    inputs passed THROUGH the Pallas custom calls (input/output
    aliased, layer form only) — callers must use the returned caches
    for subsequent layers so the buffer chain stays linear and XLA's
    copy-insertion never duplicates the cache around the custom call.
    """
    if q.shape[1] == 1:
        impl = config.attention_impl_decode or config.attention_impl
        if impl.startswith("pallas"):
            from production_stack_tpu.ops.paged_attention_pallas import (
                paged_decode_attention,
            )
            res = paged_decode_attention(
                q[:, 0], k_cache, v_cache, page_table, kv_lens,
                layer=layer,
                interpret=impl == "pallas-interpret",
            )
            if layer is not None:
                out, k_cache, v_cache = res
            else:
                out = res
            return out[:, None], k_cache, v_cache
    else:
        impl = config.attention_impl_prefill or config.attention_impl
        if impl.startswith("pallas_ragged"):
            # Fused unified-step kernel: rebuild the row descriptors
            # from the planner's layout invariant (docs/unified_step.md
            # — every row kind satisfies positions[:, 0] == kv_lens - 1
            # - last_index, so last_index is recoverable losslessly and
            # nothing new threads through the family forwards).
            from production_stack_tpu.ops.ragged_attention_pallas import (
                paged_ragged_attention,
            )
            last_index = kv_lens - 1 - positions[:, 0]
            res = paged_ragged_attention(
                q, k_cache, v_cache, page_table, kv_lens, last_index,
                layer=layer,
                interpret=impl.endswith("-interpret"),
            )
            if layer is not None:
                out, k_cache, v_cache = res
            else:
                out = res
            return out, k_cache, v_cache
        if impl.startswith("pallas"):
            from production_stack_tpu.ops.prefill_attention_pallas import (
                paged_prefill_attention,
            )
            res = paged_prefill_attention(
                q, k_cache, v_cache, page_table, positions, kv_lens,
                layer=layer,
                interpret=impl == "pallas-interpret",
            )
            if layer is not None:
                out, k_cache, v_cache = res
            else:
                out = res
            return out, k_cache, v_cache
    return paged_attention(
        q, k_cache, v_cache, page_table, positions, kv_lens,
        layer=layer,
    ), k_cache, v_cache


def cached_attention(config: ModelConfig, q, k, v, k_cache, v_cache,
                     page_table, positions, kv_lens, valid, layer: int):
    """Write one layer's K/V into the paged cache and attend.

    The single place both cache layouts are handled
    (engine/config.py CacheConfig.cache_layout), shared by every model
    family's unrolled layer loop:

      stacked:   ``k_cache``/``v_cache`` are the full [L, kv, pages,
                 d, page_size] arrays; writes are in-place scatters at
                 the static ``layer`` index and the kernels take the
                 stacked cache with the layer index through SMEM.
      per_layer: tuples of L [kv, pages, d, page_size] buffers; this
                 layer's buffer is updated and the tuple rebuilt, so
                 each scatter/kernel operand is ONE layer's buffer and
                 jit donation aliases the L buffers 1:1 (the round-3
                 decode-roofline experiment, round3_onchip_notes §0.6).

    Returns ``(attn, k_cache, v_cache)``; callers must thread the
    returned caches so the buffer chain stays linear (see
    dispatch_attention).
    """
    if isinstance(k_cache, (list, tuple)):
        kc, vc = k_cache[layer], v_cache[layer]
        kc = write_to_pages(kc, k, page_table, positions, valid)
        vc = write_to_pages(vc, v, page_table, positions, valid)
        attn, kc, vc = dispatch_attention(
            config, q, kc, vc, page_table, positions, kv_lens,
            layer=None)
        k_cache = (tuple(k_cache[:layer]) + (kc,)
                   + tuple(k_cache[layer + 1:]))
        v_cache = (tuple(v_cache[:layer]) + (vc,)
                   + tuple(v_cache[layer + 1:]))
        return attn, k_cache, v_cache
    k_cache = write_to_pages(k_cache, k, page_table, positions, valid,
                             layer=layer)
    v_cache = write_to_pages(v_cache, v, page_table, positions, valid,
                             layer=layer)
    return dispatch_attention(config, q, k_cache, v_cache, page_table,
                              positions, kv_lens, layer=layer)


def slice_layer_params(params: Params, names, layer: int) -> Params:
    """One layer's weights out of the layer-stacked param dict.

    tree.map, not plain indexing: a projection may be a quantized
    (int8, scale) pytree pair rather than a bare array
    (engine/quantization.py), and every model family's unrolled layer
    loop must slice both forms identically.
    """
    return {k: jax.tree.map(lambda s: s[layer], params[k])
            for k in names}


def slice_layer_lora(lora_stacked, layer: int):
    """One layer's adapter stacks (or None when LoRA is off)."""
    if lora_stacked is None:
        return None
    return jax.tree.map(lambda s: s[layer], lora_stacked)


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray,
             eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Random-init parameters (for tests/benchmarks and cold starts)."""
    h = config.hidden_size
    ffn = config.intermediate_size
    nh, nkv, d = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim)
    layers = config.num_hidden_layers
    dtype = config.jax_dtype

    def dense(key, shape, scale=0.02):
        return (scale * jax.random.normal(key, shape, jnp.float32)
                ).astype(dtype)

    keys = iter(jax.random.split(key, 16))
    params: Params = {
        "embed": dense(next(keys), (config.vocab_size, h)),
        "final_norm": jnp.ones((h,), dtype),
        "attn_norm": jnp.ones((layers, h), dtype),
        "wq": dense(next(keys), (layers, h, nh * d)),
        "wk": dense(next(keys), (layers, h, nkv * d)),
        "wv": dense(next(keys), (layers, h, nkv * d)),
        "wo": dense(next(keys), (layers, nh * d, h)),
        "mlp_norm": jnp.ones((layers, h), dtype),
        "w_gate": dense(next(keys), (layers, h, ffn)),
        "w_up": dense(next(keys), (layers, h, ffn)),
        "w_down": dense(next(keys), (layers, ffn, h)),
    }
    if config.attention_bias:  # Qwen2-style q/k/v biases
        params["bq"] = jnp.zeros((layers, nh * d), dtype)
        params["bk"] = jnp.zeros((layers, nkv * d), dtype)
        params["bv"] = jnp.zeros((layers, nkv * d), dtype)
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(next(keys), (h, config.vocab_size))
    return params


def _layer_param_names(config: ModelConfig):
    names = ["attn_norm", "wq", "wk", "wv", "wo",
             "mlp_norm", "w_gate", "w_up", "w_down"]
    if config.attention_bias:
        names += ["bq", "bk", "bv"]
    return names


def forward(params: Params, config: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, page_table: jnp.ndarray,
            kv_lens: jnp.ndarray, valid: jnp.ndarray,
            k_cache: jnp.ndarray, v_cache: jnp.ndarray,
            lora=None, lora_ids=None, kv_tail=None,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One model invocation over a (possibly padded) token block.

    Args:
      tokens:     [B, T] token ids
      positions:  [B, T] absolute positions (0 for padded slots)
      page_table: [B, max_pages] physical page ids (page 0 = trash)
      kv_lens:    [B] valid cached tokens AFTER this block is written
                  (deferred mode: the FROZEN pre-burst count — tail
                  slots sit above it)
      valid:      [B, T] mask of real (non-padding) tokens
      k_cache/v_cache: [L, kv_heads, num_pages, head_dim, page_size]
      lora:       optional adapter stacks (engine/lora.py), layer-leading
      lora_ids:   [B] adapter slot per batch row (0 = base model)
      kv_tail:    optional deferred-write burst tails
                  ((k_tails, v_tails): L-tuples of [B, S, kv, d]).
                  When given (decode bursts, T == 1), this step's K/V
                  are appended to the tails instead of scattered into
                  the pages (ops/attention.write_to_tail) and
                  attention covers pages + tail; the caches return
                  UNCHANGED and the updated tails are returned in the
                  cache slots of the result tuple. The model runner
                  flushes tails to pages once per burst.

    Returns (logits [B, T, vocab], new_k_cache, new_v_cache) — or
    (logits, new_k_tails, new_v_tails) in deferred mode.
    """
    from production_stack_tpu.engine.lora import lora_matmul

    nh, nkv, d = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim)
    b, t = tokens.shape

    x = params["embed"][tokens]  # [B, T, H]

    lora_scale = (None if lora is None
                  else lora["scaling"][lora_ids])  # [B]
    lora_stacked = (None if lora is None
                    else {"a": lora["a"], "b": lora["b"]})

    # STATIC layer loop, caches updated in place at a static layer
    # index. Threading per-layer cache slices through lax.scan xs/ys
    # (the round-1/2 structure) made XLA dynamic-slice each 10s-of-MB
    # layer in and dynamic-update-slice a copy back out every layer of
    # every step — measured ~20 ms/decode-step on v5e for the 1B bench
    # config vs ~1.3 ms for this chained-scatter form. Weights are
    # read whole either way, so unrolling costs only HLO size.
    for layer in range(config.num_hidden_layers):
        lp = slice_layer_params(params, _layer_param_names(config),
                                layer)
        ll = slice_layer_lora(lora_stacked, layer)
        # Attention block
        a_in = rms_norm(x, lp["attn_norm"], config.rms_norm_eps)
        q = lora_matmul(a_in, lp["wq"], ll, "wq", lora_ids, lora_scale)
        k = lora_matmul(a_in, lp["wk"], ll, "wk", lora_ids, lora_scale)
        v = lora_matmul(a_in, lp["wv"], ll, "wv", lora_ids, lora_scale)
        if config.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(b, t, nh, d)
        k = k.reshape(b, t, nkv, d)
        v = v.reshape(b, t, nkv, d)
        q = apply_rope(q, positions, config.rope_theta)
        k = apply_rope(k, positions, config.rope_theta)
        if kv_tail is not None:
            k_tails, v_tails = kv_tail
            slot = positions[:, 0] - kv_lens
            act = valid[:, 0]
            kt = write_to_tail(k_tails[layer], k, slot, act)
            vt = write_to_tail(v_tails[layer], v, slot, act)
            kc, vc = ((k_cache[layer], v_cache[layer])
                      if isinstance(k_cache, (list, tuple))
                      else (k_cache, v_cache))
            attn = paged_attention(
                q, kc, vc, page_table, positions, kv_lens,
                layer=None if isinstance(k_cache, (list, tuple))
                else layer,
                k_tail=kt, v_tail=vt)
            k_tails = (tuple(k_tails[:layer]) + (kt,)
                       + tuple(k_tails[layer + 1:]))
            v_tails = (tuple(v_tails[:layer]) + (vt,)
                       + tuple(v_tails[layer + 1:]))
            kv_tail = (k_tails, v_tails)
        else:
            attn, k_cache, v_cache = cached_attention(
                config, q, k, v, k_cache, v_cache, page_table,
                positions, kv_lens, valid, layer,
            )
        x = x + lora_matmul(attn.reshape(b, t, nh * d), lp["wo"], ll,
                            "wo", lora_ids, lora_scale)
        # MLP block (SwiGLU)
        m_in = rms_norm(x, lp["mlp_norm"], config.rms_norm_eps)
        gate = jax.nn.silu(lora_matmul(m_in, lp["w_gate"], ll, "w_gate",
                                       lora_ids, lora_scale))
        up = lora_matmul(m_in, lp["w_up"], ll, "w_up", lora_ids,
                         lora_scale)
        x = x + lora_matmul(gate * up, lp["w_down"], ll, "w_down",
                            lora_ids, lora_scale)
    if kv_tail is not None:
        new_k, new_v = kv_tail
    else:
        new_k, new_v = k_cache, v_cache

    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = (x @ head).astype(jnp.float32)
    return logits, new_k, new_v


def forward_train(params: Params, config: ModelConfig,
                  tokens: jnp.ndarray) -> jnp.ndarray:
    """Cache-free dense causal forward for training/fine-tuning flows.

    Same weights/numerics as the serving path but attends within the
    batch (no paged cache), so it is cleanly differentiable.
    Returns logits [B, T, vocab].
    """
    x = encode(params, config, tokens)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return (x @ head).astype(jnp.float32)


def encode(params: Params, config: ModelConfig,
           tokens: jnp.ndarray) -> jnp.ndarray:
    """Dense causal forward returning final-norm hidden states.

    The /v1/embeddings path (engine/embeddings.py) pools these; the
    reference delegates embeddings to vLLM pooling models
    (src/vllm_router/routers/main_router.py:54-60 routes
    /v1/embeddings to engine pods).

    Returns [B, T, hidden].
    """
    nh, nkv, d = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim)
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    x = params["embed"][tokens]

    layer_params = {
        k: params[k] for k in _layer_param_names(config)
    }
    causal = jnp.tril(jnp.ones((t, t), bool))

    def layer_step(x, lp):
        a_in = rms_norm(x, lp["attn_norm"], config.rms_norm_eps)
        q, k, v = a_in @ lp["wq"], a_in @ lp["wk"], a_in @ lp["wv"]
        if config.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = apply_rope(q.reshape(b, t, nh, d),
                       positions, config.rope_theta)
        k = apply_rope(k.reshape(b, t, nkv, d),
                       positions, config.rope_theta)
        v = v.reshape(b, t, nkv, d)
        group = nh // nkv
        qg = q.reshape(b, t, nkv, group, d)
        scores = jnp.einsum(
            "btkgd,bskd->bkgts", qg.astype(jnp.float32),
            k.astype(jnp.float32),
        ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
        scores = jnp.where(causal[None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "bkgts,bskd->btkgd", probs, v.astype(jnp.float32)
        ).reshape(b, t, nh * d).astype(x.dtype)
        x = x + attn @ lp["wo"]
        m_in = rms_norm(x, lp["mlp_norm"], config.rms_norm_eps)
        x = x + (jax.nn.silu(m_in @ lp["w_gate"])
                 * (m_in @ lp["w_up"])) @ lp["w_down"]
        return x, None

    x, _ = jax.lax.scan(layer_step, x, layer_params)
    return rms_norm(x, params["final_norm"], config.rms_norm_eps)
