"""JAX model definitions.

Pure-functional models: parameters are pytrees of jnp arrays with the
layer dimension stacked so the transformer body is a single
``lax.scan`` — one layer gets traced/compiled regardless of depth, and
tensor-parallel sharding annotations apply uniformly across layers.
"""

from production_stack_tpu.models.registry import (
    get_model,
    list_architectures,
)

__all__ = ["get_model", "list_architectures"]
