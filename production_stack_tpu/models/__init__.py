"""JAX model definitions.

Pure-functional models: parameters are pytrees of jnp arrays with the
layer dimension stacked (tensor-parallel sharding annotations apply
uniformly across layers) and the decoder loop STATICALLY UNROLLED so
every paged-KV update is an in-place scatter at a static layer index.
Scanning layers with the cache as scan xs/ys made XLA copy whole layer
caches in and out per step — ~16x the cost of the chained in-place
scatters on a v5e (benchmarks/results/round3_onchip_notes.md §0); the
cache-free training forwards (forward_train) still scan.
"""

from production_stack_tpu.models.registry import (
    get_model,
    list_architectures,
)

__all__ = ["get_model", "list_architectures"]
