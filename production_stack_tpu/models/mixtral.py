"""Mixtral-family sparse-MoE decoders (mixtral-8x7b style).

Adds the MoE model class the reference serves through vLLM's zoo, and
the stack's expert-parallel (ep) axis. TPU-first formulation: instead
of translating token-routing/dispatch kernels, the MoE block is a
*dense* pair of expert einsums with a top-k combine mask —

    gate/up:  [B,T,H] x [E,H,F] -> [B,E,T,F]
    down:     [B,E,T,F] x [E,F,H] -> [B,E,T,H]
    combine:  [B,T,E] softmax(top-k) weights zero the unselected
              experts, then sum over E.

With the expert axis E carrying a NamedSharding (parallel/mesh.py),
GSPMD partitions those einsums so each device computes only its local
experts and inserts one psum for the combine — expert parallelism
without any hand-written all-to-all. FLOPs are E/k-fold dense, the
standard capacity-free trade at serving batch sizes, and every matmul
stays a large static MXU contraction.

Attention is the llama GQA path over the shared paged cache.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import (
    cached_attention,
    rms_norm,
    slice_layer_lora,
    slice_layer_params,
)
from production_stack_tpu.ops.rope import apply_rope

Params = Dict[str, jnp.ndarray]


def moe_block(x: jnp.ndarray, gate_w: jnp.ndarray,
              w_gate: jnp.ndarray, w_up: jnp.ndarray,
              w_down: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Top-k routed SwiGLU experts, dense formulation.

    Args:
      x:      [B, T, H]
      gate_w: [H, E] router
      w_gate/w_up: [E, H, F]; w_down: [E, F, H]
      top_k:  experts per token

    Returns [B, T, H].
    """
    router_logits = (x @ gate_w).astype(jnp.float32)  # [B, T, E]
    top_vals, top_idx = jax.lax.top_k(router_logits, top_k)
    top_weights = jax.nn.softmax(top_vals, axis=-1)  # [B, T, k]
    e = gate_w.shape[-1]
    # Combine mask [B, T, E]: weight where selected, 0 elsewhere.
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, e, dtype=top_weights.dtype)
        * top_weights[..., None],
        axis=-2,
    )

    hidden = jax.nn.silu(jnp.einsum("bth,ehf->betf", x, w_gate))
    hidden = hidden * jnp.einsum("bth,ehf->betf", x, w_up)
    expert_out = jnp.einsum("betf,efh->beth", hidden, w_down)
    out = jnp.einsum(
        "beth,bte->bth", expert_out, combine.astype(expert_out.dtype)
    )
    return out.astype(x.dtype)


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    h = config.hidden_size
    ffn = config.intermediate_size
    nh, nkv, d = (config.num_attention_heads,
                  config.num_key_value_heads, config.head_dim)
    layers = config.num_hidden_layers
    e = config.num_local_experts
    dtype = config.jax_dtype

    def dense(key, shape, scale=0.02):
        return (scale * jax.random.normal(key, shape, jnp.float32)
                ).astype(dtype)

    keys = iter(jax.random.split(key, 16))
    params: Params = {
        "embed": dense(next(keys), (config.vocab_size, h)),
        "final_norm": jnp.ones((h,), dtype),
        "attn_norm": jnp.ones((layers, h), dtype),
        "wq": dense(next(keys), (layers, h, nh * d)),
        "wk": dense(next(keys), (layers, h, nkv * d)),
        "wv": dense(next(keys), (layers, h, nkv * d)),
        "wo": dense(next(keys), (layers, nh * d, h)),
        "mlp_norm": jnp.ones((layers, h), dtype),
        "moe_gate": dense(next(keys), (layers, h, e)),
        "w_gate": dense(next(keys), (layers, e, h, ffn)),
        "w_up": dense(next(keys), (layers, e, h, ffn)),
        "w_down": dense(next(keys), (layers, e, ffn, h)),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(next(keys), (h, config.vocab_size))
    return params


def forward(params: Params, config: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, page_table: jnp.ndarray,
            kv_lens: jnp.ndarray, valid: jnp.ndarray,
            k_cache: jnp.ndarray, v_cache: jnp.ndarray,
            lora=None, lora_ids=None,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Same contract as models.llama.forward. LoRA applies to the
    attention projections (expert weights are not LoRA targets)."""
    from production_stack_tpu.engine.lora import lora_matmul

    nh, nkv, d = (config.num_attention_heads,
                  config.num_key_value_heads, config.head_dim)
    b, t = tokens.shape

    x = params["embed"][tokens]

    names = ("attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
             "moe_gate", "w_gate", "w_up", "w_down")
    lora_scale = (None if lora is None
                  else lora["scaling"][lora_ids])
    lora_stacked = (None if lora is None
                    else {"a": lora["a"], "b": lora["b"]})

    # Static layer loop with in-place cache scatters at a static layer
    # index (see models.llama.forward for why scan xs/ys is slow).
    for layer in range(config.num_hidden_layers):
        lp = slice_layer_params(params, names, layer)
        ll = slice_layer_lora(lora_stacked, layer)
        a_in = rms_norm(x, lp["attn_norm"], config.rms_norm_eps)
        q = lora_matmul(a_in, lp["wq"], ll, "wq", lora_ids,
                        lora_scale).reshape(b, t, nh, d)
        k = lora_matmul(a_in, lp["wk"], ll, "wk", lora_ids,
                        lora_scale).reshape(b, t, nkv, d)
        v = lora_matmul(a_in, lp["wv"], ll, "wv", lora_ids,
                        lora_scale).reshape(b, t, nkv, d)
        q = apply_rope(q, positions, config.rope_theta)
        k = apply_rope(k, positions, config.rope_theta)
        attn, k_cache, v_cache = cached_attention(
            config, q, k, v, k_cache, v_cache, page_table, positions,
            kv_lens, valid, layer,
        )
        x = x + lora_matmul(attn.reshape(b, t, nh * d), lp["wo"], ll,
                            "wo", lora_ids, lora_scale)
        m_in = rms_norm(x, lp["mlp_norm"], config.rms_norm_eps)
        x = x + moe_block(
            m_in, lp["moe_gate"], lp["w_gate"], lp["w_up"],
            lp["w_down"], config.num_experts_per_tok,
        )
    new_k, new_v = k_cache, v_cache

    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = (x @ head).astype(jnp.float32)
    return logits, new_k, new_v
