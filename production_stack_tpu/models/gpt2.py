"""GPT-2 family (gpt2, gpt2-medium/large/xl, distilgpt2).

Completes the decoder-family coverage the reference gets from vLLM's
model zoo (engines are external images there —
helm/templates/deployment-vllm-multi.yaml:55-64). Differences from OPT
handled here: positional embeddings with no offset, gelu(tanh) MLP,
always-tied LM head. Same unrolled-layer + paged-cache structure as
models/llama.py; the HF checkpoint's fused ``c_attn`` is split into
q/k/v at load time (engine/weights.py) so the attention path is shared.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import (
    cached_attention,
    slice_layer_lora,
    slice_layer_params,
)
from production_stack_tpu.models.opt import layer_norm

Params = Dict[str, jnp.ndarray]

# Canonical per-layer parameter names (leading L axis) — the single
# source for the layer/shared split used by the unrolled forward here
# and the pp/sp shard_map bodies (parallel/pipeline_serving.py,
# parallel/context_serving.py).
GPT2_LAYER_NAMES = (
    "attn_norm_w", "attn_norm_b", "wq", "bq", "wk", "bk", "wv", "bv",
    "wo", "bo", "mlp_norm_w", "mlp_norm_b", "fc1", "fc1_b", "fc2",
    "fc2_b")


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    h = config.hidden_size
    ffn = config.intermediate_size
    nh, d = config.num_attention_heads, config.head_dim
    layers = config.num_hidden_layers
    dtype = config.jax_dtype

    def dense(key, shape, scale=0.02):
        return (scale * jax.random.normal(key, shape, jnp.float32)
                ).astype(dtype)

    keys = iter(jax.random.split(key, 16))
    return {
        "embed": dense(next(keys), (config.vocab_size, h)),
        "pos_embed": dense(
            next(keys), (config.max_position_embeddings, h)),
        "final_norm_w": jnp.ones((h,), dtype),
        "final_norm_b": jnp.zeros((h,), dtype),
        "attn_norm_w": jnp.ones((layers, h), dtype),
        "attn_norm_b": jnp.zeros((layers, h), dtype),
        "wq": dense(next(keys), (layers, h, nh * d)),
        "bq": jnp.zeros((layers, nh * d), dtype),
        "wk": dense(next(keys), (layers, h, nh * d)),
        "bk": jnp.zeros((layers, nh * d), dtype),
        "wv": dense(next(keys), (layers, h, nh * d)),
        "bv": jnp.zeros((layers, nh * d), dtype),
        "wo": dense(next(keys), (layers, nh * d, h)),
        "bo": jnp.zeros((layers, h), dtype),
        "mlp_norm_w": jnp.ones((layers, h), dtype),
        "mlp_norm_b": jnp.zeros((layers, h), dtype),
        "fc1": dense(next(keys), (layers, h, ffn)),
        "fc1_b": jnp.zeros((layers, ffn), dtype),
        "fc2": dense(next(keys), (layers, ffn, h)),
        "fc2_b": jnp.zeros((layers, h), dtype),
    }


def forward(params: Params, config: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, page_table: jnp.ndarray,
            kv_lens: jnp.ndarray, valid: jnp.ndarray,
            k_cache: jnp.ndarray, v_cache: jnp.ndarray,
            lora=None, lora_ids=None,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Same contract as models.llama.forward."""
    from production_stack_tpu.engine.lora import lora_matmul

    nh, d = config.num_attention_heads, config.head_dim
    b, t = tokens.shape

    x = params["embed"][tokens] + params["pos_embed"][positions]

    names = GPT2_LAYER_NAMES
    lora_scale = (None if lora is None
                  else lora["scaling"][lora_ids])
    lora_stacked = (None if lora is None
                    else {"a": lora["a"], "b": lora["b"]})

    # Static layer loop with in-place cache scatters at a static layer
    # index (see models.llama.forward for why scan xs/ys is slow).
    for layer in range(config.num_hidden_layers):
        lp = slice_layer_params(params, names, layer)
        ll = slice_layer_lora(lora_stacked, layer)
        a_in = layer_norm(x, lp["attn_norm_w"], lp["attn_norm_b"])
        q = (lora_matmul(a_in, lp["wq"], ll, "wq", lora_ids, lora_scale)
             + lp["bq"]).reshape(b, t, nh, d)
        k = (lora_matmul(a_in, lp["wk"], ll, "wk", lora_ids, lora_scale)
             + lp["bk"]).reshape(b, t, nh, d)
        v = (lora_matmul(a_in, lp["wv"], ll, "wv", lora_ids, lora_scale)
             + lp["bv"]).reshape(b, t, nh, d)
        attn, k_cache, v_cache = cached_attention(
            config, q, k, v, k_cache, v_cache, page_table, positions,
            kv_lens, valid, layer,
        )
        x = x + (lora_matmul(attn.reshape(b, t, nh * d), lp["wo"], ll,
                             "wo", lora_ids, lora_scale) + lp["bo"])
        m_in = layer_norm(x, lp["mlp_norm_w"], lp["mlp_norm_b"])
        # HF GPT-2 uses gelu_new == tanh-approximated gelu.
        hidden = jax.nn.gelu(
            lora_matmul(m_in, lp["fc1"], ll, "fc1", lora_ids, lora_scale)
            + lp["fc1_b"], approximate=True)
        x = x + (lora_matmul(hidden, lp["fc2"], ll, "fc2", lora_ids,
                             lora_scale) + lp["fc2_b"])
    new_k, new_v = k_cache, v_cache

    x = layer_norm(x, params["final_norm_w"], params["final_norm_b"])
    logits = (x @ params["embed"].T).astype(jnp.float32)
    return logits, new_k, new_v
