"""Python harness for the native control-plane agent.

The agent itself is a dependency-free C++ binary (``controlplane/``) that
fills the role of the reference's Go operator
(src/router-controller/cmd/main.go, staticroute_controller.go:71-132):
StaticRoute specs -> rendered ``dynamic_config.json`` -> router
DynamicConfigWatcher, plus router ``/health`` probing with configurable
thresholds. This module builds and launches it for tests, local runs, and
the bare-metal runbook.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from pathlib import Path
from typing import List, Optional

REPO_ROOT = Path(__file__).resolve().parent.parent
CONTROLPLANE_DIR = REPO_ROOT / "controlplane"
BINARY = CONTROLPLANE_DIR / "bin" / "tpu-stack-controlplane"


class BuildError(RuntimeError):
    pass


def ensure_built(force: bool = False) -> Path:
    """Builds the agent with make if the binary is missing/stale."""
    if not force and BINARY.exists():
        sources = list((CONTROLPLANE_DIR / "src").glob("*"))
        newest_src = max(p.stat().st_mtime for p in sources)
        if BINARY.stat().st_mtime >= newest_src:
            return BINARY
    if shutil.which("make") is None or shutil.which("g++") is None:
        raise BuildError("make/g++ not available to build the agent")
    proc = subprocess.run(
        ["make", "-C", str(CONTROLPLANE_DIR)],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise BuildError(
            f"controlplane build failed:\n{proc.stdout}\n{proc.stderr}"
        )
    return BINARY


def agent_args(
    spec_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    kube_api: Optional[str] = None,
    namespace: Optional[str] = None,
    period_s: int = 10,
    once: bool = False,
) -> List[str]:
    args = [str(BINARY)]
    if spec_dir:
        args += ["--spec-dir", spec_dir, "--out-dir", out_dir or ""]
    if kube_api:
        args += ["--kube-api", kube_api]
        if namespace:
            args += ["--namespace", namespace]
    args += ["--period", str(period_s)]
    if once:
        args.append("--once")
    return args


def run_once(
    spec_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    kube_api: Optional[str] = None,
    namespace: Optional[str] = None,
    timeout_s: float = 60.0,
) -> subprocess.CompletedProcess:
    """Runs a single reconcile pass and returns the completed process."""
    ensure_built()
    return subprocess.run(
        agent_args(spec_dir, out_dir, kube_api, namespace, once=True),
        capture_output=True,
        text=True,
        timeout=timeout_s,
    )


def launch(
    spec_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    kube_api: Optional[str] = None,
    namespace: Optional[str] = None,
    period_s: int = 10,
    log_path: Optional[str] = None,
) -> subprocess.Popen:
    """Starts the agent as a background daemon process.

    Output goes to *log_path* (or /dev/null) — never an undrained PIPE,
    which would eventually block the agent's reconcile loop once the
    pipe buffer fills.
    """
    ensure_built()
    log = open(log_path, "ab") if log_path else subprocess.DEVNULL
    try:
        return subprocess.Popen(
            agent_args(spec_dir, out_dir, kube_api, namespace, period_s),
            stdout=log,
            stderr=log,
            env=os.environ.copy(),
        )
    finally:
        if log is not subprocess.DEVNULL:
            log.close()
