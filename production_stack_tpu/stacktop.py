"""stacktop: a live terminal console over ``GET /cluster/status``.

``top`` for the serving stack: one screen answering "how is the
fleet, are we meeting SLO, and is anything drifting" — per-server
health/load/KV/QoS/compile columns, the SLO attainment and burn-rate
block, the perf-drift sentinel verdicts, and the slow-archive depth.

Run::

    python -m production_stack_tpu.stacktop --url http://router:8080

Polls the router and redraws on an interval, marking rows whose load
changed since the previous poll. ``--once`` renders a single
snapshot and exits; ``--plain`` suppresses ANSI control sequences
(the mode tests golden-match against). Rendering is a pure function
of the snapshot, so the same code path serves both the live console
and the tests.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

import requests


def _fmt(value, width: int) -> str:
    return f"{value:>{width}}" if value is not None else " " * width


def render_snapshot(snap: dict, changed: Optional[set] = None) -> str:
    """Plain-text render of one /cluster/status payload. ``changed``
    marks server URLs whose load moved since the previous poll."""
    changed = changed or set()
    lines: List[str] = []
    ts = snap.get("ts")
    stamp = (time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(ts))
             if isinstance(ts, (int, float)) else "-")
    lines.append(f"tpu-stack cluster status @ {stamp}")

    slo = snap.get("slo")
    if slo:
        burn = slo.get("burn_rate", {})
        lines.append(
            f"SLO objective={slo.get('objective')} "
            f"burn 5m={burn.get('5m', 0.0):.2f} "
            f"1h={burn.get('1h', 0.0):.2f} "
            f"good={slo.get('good_requests', 0)} "
            f"bad={slo.get('bad_requests', 0)}")
        for key, frac in sorted(
                (slo.get("attainment") or {}).items()):
            lines.append(f"  attainment {key} = {frac:.4f}")

    drift = snap.get("perf_drift")
    if drift:
        parts = []
        for phase, info in sorted(drift.items()):
            verdict = "TRIPPED" if info.get("tripped") else "ok"
            observed = info.get("observed_s")
            obs_txt = (f"{observed:.4f}s"
                       if isinstance(observed, (int, float)) else "-")
            parts.append(f"{phase}: {verdict} "
                         f"({obs_txt} vs {info.get('baseline_s')}s)")
        lines.append("drift " + "  ".join(parts))

    arch = snap.get("slow_archive")
    if arch:
        lines.append(
            f"slow archive: {arch.get('depth', 0)}"
            f"/{arch.get('capacity', 0)} "
            f"({arch.get('archived_total', 0)} archived)")

    rollout = snap.get("rollout") or {}
    for pool, info in sorted(rollout.items()):
        line = (f"rollout {pool}: {info.get('phase', '-')} "
                f"{info.get('current_build') or '-'} -> "
                f"{info.get('target_build') or '-'} "
                f"rollbacks={info.get('rollbacks', 0)}")
        if info.get("verdict"):
            line += f" verdict={info['verdict']}"
        if info.get("alarm"):
            line += "  ALARM"
        lines.append(line)

    servers = snap.get("servers") or {}
    if servers:
        lines.append("")
        lines.append(
            f"{'SERVER':<42} {'HEALTH':<7} {'ROLE':<7} "
            f"{'MESH':<9} "
            f"{'RUN':>4} {'WAIT':>4} {'CACHE':>6} {'HIT':>6} "
            f"{'MFU':>6} {'SHED':>5} {'COMPILES':>8} {'AUTOTUNE':>8}")
        for url in sorted(servers):
            s = servers[url]
            health = "drain" if s.get("draining") else (
                "ok" if s.get("healthy", True) else "DOWN")
            shed = sum((s.get("qos_shed") or {}).values())
            compiles = sum((s.get("compile_events") or {}).values())
            # Mesh axis sizes as dpxppxspxtp; a trailing "!" flags a
            # dead slice (docs/parallelism.md) — the one-glance cue
            # that a multi-host replica lost a host.
            mesh_info = s.get("mesh") or {}
            shape = mesh_info.get("shape") or {}
            if shape:
                mesh = "x".join(str(int(shape.get(a, 1)))
                                for a in ("dp", "pp", "sp", "tp"))
                slices_live = mesh_info.get("slices_live") or {}
                if slices_live and not all(slices_live.values()):
                    mesh += "!"
            else:
                mesh = "-"
            # Self-tuning (docs/autotuning.md): controllers allowed to
            # act right now; "!" flags a guardrail-frozen controller
            # waiting on an operator POST /autotune/reset.
            auto_info = s.get("autotune") or {}
            auto = str(int(auto_info.get("active", 0)))
            if any((auto_info.get("frozen") or {}).values()):
                auto += "!"
            mark = "*" if url in changed else " "
            row = (
                f"{url:<41}{mark} {health:<7} "
                f"{str(s.get('role') or '-'):<7} "
                f"{mesh:<9} "
                f"{_fmt(s.get('running'), 4)} "
                f"{_fmt(s.get('waiting'), 4)} "
                f"{s.get('cache_usage', 0.0):>6.2f} "
                f"{s.get('prefix_hit_rate', 0.0):>6.2f} "
                f"{s.get('mfu', 0.0):>6.2f} "
                f"{shed:>5} {compiles:>8} {auto:>8}")
            # Revision suffix only during rollouts, so the plain table
            # stays byte-stable for the golden tests.
            if s.get("revision"):
                row += f" rev={s['revision']}"
            lines.append(row)
    return "\n".join(lines)


def _load_changes(prev: Optional[dict], snap: dict) -> set:
    """Server URLs whose load gauges moved between two snapshots."""
    if not prev:
        return set()
    watched = ("running", "waiting", "cache_usage")
    out = set()
    prev_servers = prev.get("servers") or {}
    for url, s in (snap.get("servers") or {}).items():
        before = prev_servers.get(url)
        if before is None or any(
                s.get(k) != before.get(k) for k in watched):
            out.add(url)
    return out


def fetch_snapshot(url: str, timeout: float = 5.0) -> dict:
    resp = requests.get(f"{url.rstrip('/')}/cluster/status",
                        timeout=timeout)
    resp.raise_for_status()
    return resp.json()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.stacktop",
        description="Live fleet console over the router's "
                    "/cluster/status rollup.")
    parser.add_argument("--url", default="http://localhost:8080",
                        help="router base URL")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="seconds between polls")
    parser.add_argument("--once", action="store_true",
                        help="render one snapshot and exit")
    parser.add_argument("--plain", action="store_true",
                        help="no ANSI control sequences (tests, "
                             "pipes)")
    parser.add_argument("--json", action="store_true",
                        help="print the raw snapshot JSON instead of "
                             "the rendered console")
    args = parser.parse_args(argv)

    prev: Optional[dict] = None
    while True:
        try:
            snap = fetch_snapshot(args.url)
        except Exception as e:
            print(f"stacktop: {args.url}: {e}", file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.json:
            out = json.dumps(snap, indent=2, sort_keys=True)
        else:
            out = render_snapshot(snap, _load_changes(prev, snap))
        if not (args.plain or args.once):
            sys.stdout.write("\x1b[2J\x1b[H")
        print(out)
        if args.once:
            return 0
        prev = snap
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
