"""Service discovery: which engine endpoints exist and what they serve.

Capability parity with reference src/vllm_router/service_discovery.py:
``StaticServiceDiscovery`` (fixed URL/model lists, L64) and
``K8sServiceDiscovery`` (label-selector pod watch + readiness + model probe,
L85-239), with global init/get/reconfigure (L293-337). The K8s backend is
import-gated: the ``kubernetes`` client is only required when selected.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import requests

from production_stack_tpu.utils import SingletonMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


@dataclass
class EndpointInfo:
    url: str
    model_names: List[str] = field(default_factory=list)
    added_timestamp: float = field(default_factory=time.time)
    pod_name: Optional[str] = None
    # An empty model list historically meant "serves everything". That
    # stays the default (static discovery without --static-models), but
    # probed endpoints set wildcard=False so a model list that is
    # *authoritatively* empty serves nothing instead of everything.
    wildcard: bool = True
    # Disaggregated-serving deployment role ("prefill" | "decode" |
    # "both", docs/disaggregation.md). Any role can serve any request —
    # the role only steers the router's two-hop disagg dispatch — so
    # engines that predate role reporting default to "both".
    role: str = "both"
    # Build revision serving on this endpoint (fleet rollouts,
    # docs/fleet.md); empty for unversioned deployments.
    revision: str = ""

    def serves_model(self, model: str) -> bool:
        if model in self.model_names:
            return True
        return not self.model_names and self.wildcard


class ServiceDiscoveryType(str, enum.Enum):
    STATIC = "static"
    K8S = "k8s"


class ServiceDiscovery:
    def _list_endpoints(self) -> List[EndpointInfo]:
        raise NotImplementedError

    def get_endpoint_info(
            self, include_unhealthy: bool = False) -> List[EndpointInfo]:
        """Discovered endpoints; by default filtered down to the ones the
        active health checker (when enabled) currently believes alive, so
        dead backends leave rotation for every discovery type — not just
        the K8s pod-watch path."""
        endpoints = self._list_endpoints()
        if include_unhealthy:
            return endpoints
        from production_stack_tpu.router.resilience import get_resilience
        mgr = get_resilience()
        if mgr is None or mgr.health is None:
            return endpoints
        return [ep for ep in endpoints if mgr.health.is_healthy(ep.url)]

    def get_health(self) -> bool:
        """Liveness of the discovery machinery itself. With active health
        checking enabled this reports the prober task's liveness instead
        of being hardwired True."""
        from production_stack_tpu.router.resilience import get_resilience
        mgr = get_resilience()
        if mgr is None or mgr.health is None:
            return True
        return mgr.health.is_running()

    def close(self) -> None:
        pass


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed backend list from --static-backends / --static-models flags."""

    def __init__(self, urls: List[str],
                 models: Optional[List[str]] = None,
                 roles: Optional[List[str]] = None,
                 revisions: Optional[List[str]] = None):
        if models and len(models) != len(urls):
            raise ValueError(
                "static models list must match static backends list"
            )
        if roles and len(roles) != len(urls):
            raise ValueError(
                "static roles list must match static backends list"
            )
        if revisions and len(revisions) != len(urls):
            raise ValueError(
                "static revisions list must match static backends list"
            )
        if roles:
            for role in roles:
                if role not in ("prefill", "decode", "both"):
                    raise ValueError(
                        f"static role must be 'prefill', 'decode' or "
                        f"'both' (got {role!r})"
                    )
        now = time.time()
        self._endpoints = [
            EndpointInfo(
                url=url,
                model_names=[models[i]] if models else [],
                added_timestamp=now,
                role=roles[i] if roles else "both",
                revision=revisions[i] if revisions else "",
            )
            for i, url in enumerate(urls)
        ]

    def _list_endpoints(self) -> List[EndpointInfo]:
        return list(self._endpoints)


class K8sServiceDiscovery(ServiceDiscovery):
    """Kubernetes pod watch: pods matching a label selector become engines.

    A daemon thread runs a watch on pods in *namespace*; on ADDED/MODIFIED
    ready pods, the pod IP is probed at ``GET /v1/models`` to learn what it
    serves; on DELETED/not-ready, the endpoint is removed so traffic stops.
    """

    _MODEL_PROBE_TIMEOUT_S = 5.0
    # Bounded re-probe schedule for pods whose /v1/models probe failed:
    # they stay OUT of rotation (a failed probe must not degrade into
    # wildcard "serves everything" routing) and are retried with
    # exponential spacing until this many attempts, after which the pod
    # waits for its next watch event to be considered again.
    _REPROBE_BASE_S = 2.0
    _REPROBE_MAX_ATTEMPTS = 5
    _REPROBE_TICK_S = 0.5

    def __init__(self, namespace: str, port: int, label_selector: str):
        try:
            from kubernetes import client, config, watch  # noqa: F401
        except ImportError as e:  # pragma: no cover - env without k8s
            raise RuntimeError(
                "K8s service discovery requires the 'kubernetes' package; "
                "use --service-discovery static in this environment"
            ) from e
        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        self._core = client.CoreV1Api()
        self._watch = watch.Watch()
        self.namespace = namespace
        self.port = port
        self.label_selector = label_selector
        self._endpoints: Dict[str, EndpointInfo] = {}  # pod name -> info
        # pod name -> (url, attempts, next_probe_at, generation) for
        # failed probes. The generation token is bumped every time the
        # watch (re)registers the pod, so a re-probe that raced with a
        # watch event can detect its snapshot is stale instead of
        # clobbering the fresh entry with stale attempt counts.
        self._pending_probe: Dict[str, tuple] = {}
        self._probe_generation = 0
        self._lock = threading.Lock()
        self._running = True
        self._thread = threading.Thread(
            target=self._watch_pods, daemon=True, name="k8s-pod-watcher"
        )
        self._thread.start()
        self._reprobe_thread = threading.Thread(
            target=self._reprobe_loop, daemon=True, name="k8s-model-reprobe"
        )
        self._reprobe_thread.start()

    @staticmethod
    def _pod_is_ready(pod) -> bool:
        conditions = (pod.status and pod.status.conditions) or []
        return any(
            c.type == "Ready" and c.status == "True" for c in conditions
        )

    @classmethod
    def _probe_models(cls, url: str) -> Optional[List[str]]:
        """Model list served at *url*, or None when the probe failed —
        never an empty list standing in for "unknown", which upstream
        would misread as a wildcard endpoint serving every model."""
        try:
            resp = requests.get(
                f"{url}/v1/models", timeout=cls._MODEL_PROBE_TIMEOUT_S
            )
            resp.raise_for_status()
            return [m["id"] for m in resp.json().get("data", [])]
        except Exception as e:
            logger.warning("Model probe failed for %s: %s", url, e)
            return None

    @classmethod
    def _probe_role(cls, url: str) -> str:
        """Engine role reported by ``GET /health`` ("prefill" |
        "decode" | "both"). Engines that predate disaggregation (or a
        failed probe) default to "both": any engine can serve any
        request, the role only enables two-hop disagg dispatch."""
        try:
            resp = requests.get(
                f"{url}/health", timeout=cls._MODEL_PROBE_TIMEOUT_S
            )
            resp.raise_for_status()
            role = resp.json().get("role")
        except Exception:
            return "both"
        return role if role in ("prefill", "decode", "both") else "both"

    def _reprobe_loop(self) -> None:
        while self._running:
            time.sleep(self._REPROBE_TICK_S)
            self._reprobe_pass(time.time())

    def _reprobe_pass(self, now: float) -> None:
        """Retry failed model probes on a bounded exponential schedule;
        the pod only enters rotation once a probe succeeds. The probe
        itself runs unlocked, so after re-acquiring the lock each entry
        is revalidated by its generation token: a watch event that
        churned or re-registered the pod meanwhile wins, and this pass's
        stale snapshot is discarded."""
        with self._lock:
            due = [
                (name, url, attempts, gen)
                for name, (url, attempts, next_at, gen)
                in self._pending_probe.items()
                if next_at <= now
            ]
        for name, url, attempts, gen in due:
            models = self._probe_models(url)
            role = self._probe_role(url) if models is not None else "both"
            with self._lock:
                current = self._pending_probe.get(name)
                if current is None or current[3] != gen:
                    continue  # pod churned / re-registered meanwhile
                if models is not None:
                    del self._pending_probe[name]
                    self._endpoints[name] = EndpointInfo(
                        url=url, model_names=models, pod_name=name,
                        wildcard=False, role=role,
                    )
                    logger.info("Engine pod up after re-probe: "
                                "%s -> %s (%s)", name, url, models)
                elif attempts + 1 >= self._REPROBE_MAX_ATTEMPTS:
                    del self._pending_probe[name]
                    logger.error(
                        "Model probe for %s (%s) failed %d times; "
                        "pod stays out of rotation until its next "
                        "watch event", name, url, attempts + 1)
                else:
                    self._pending_probe[name] = (
                        url, attempts + 1,
                        time.time()
                        + self._REPROBE_BASE_S * 2 ** (attempts + 1),
                        gen,
                    )

    def _watch_pods(self) -> None:
        from kubernetes import watch
        while self._running:
            try:
                self._watch = watch.Watch()
                stream = self._watch.stream(
                    self._core.list_namespaced_pod,
                    namespace=self.namespace,
                    label_selector=self.label_selector,
                )
                for event in stream:
                    if not self._running:
                        break
                    self._handle_event(event)
            except Exception as e:
                if self._running:
                    logger.error("Pod watch error, retrying: %s", e)
                    time.sleep(1)

    def _handle_event(self, event) -> None:
        pod = event["object"]
        name = pod.metadata.name
        etype = event["type"]
        ready = self._pod_is_ready(pod) and pod.status.pod_ip
        if etype in ("ADDED", "MODIFIED") and ready:
            url = f"http://{pod.status.pod_ip}:{self.port}"
            with self._lock:
                known = self._endpoints.get(name)
            if known is None or known.url != url:
                models = self._probe_models(url)
                role = (self._probe_role(url) if models is not None
                        else "both")
                with self._lock:
                    if models is None:
                        # Keep the pod out of rotation until a probe
                        # succeeds; the re-probe loop picks it up.
                        self._endpoints.pop(name, None)
                        self._probe_generation += 1
                        self._pending_probe[name] = (
                            url, 0, time.time() + self._REPROBE_BASE_S,
                            self._probe_generation)
                    else:
                        self._pending_probe.pop(name, None)
                        self._endpoints[name] = EndpointInfo(
                            url=url, model_names=models, pod_name=name,
                            wildcard=False, role=role,
                        )
                if models is not None:
                    logger.info("Engine pod up: %s -> %s (%s)",
                                name, url, models)
        elif etype == "DELETED" or not ready:
            with self._lock:
                self._pending_probe.pop(name, None)
                if self._endpoints.pop(name, None) is not None:
                    logger.info("Engine pod removed: %s", name)

    def _list_endpoints(self) -> List[EndpointInfo]:
        with self._lock:
            return list(self._endpoints.values())

    def get_health(self) -> bool:
        return self._thread.is_alive() and super().get_health()

    def close(self) -> None:
        self._running = False
        try:
            self._watch.stop()
        except Exception:
            pass


class _DiscoveryHolder(metaclass=SingletonMeta):
    def __init__(self):
        self.instance: Optional[ServiceDiscovery] = None


def initialize_service_discovery(discovery_type: str,
                                 **kwargs) -> ServiceDiscovery:
    holder = _DiscoveryHolder()
    dtype = ServiceDiscoveryType(discovery_type)
    if dtype == ServiceDiscoveryType.STATIC:
        holder.instance = StaticServiceDiscovery(
            urls=kwargs["urls"], models=kwargs.get("models"),
            roles=kwargs.get("roles"),
            revisions=kwargs.get("revisions"),
        )
    else:
        holder.instance = K8sServiceDiscovery(
            namespace=kwargs.get("namespace", "default"),
            port=int(kwargs.get("port", 8000)),
            label_selector=kwargs.get("label_selector", ""),
        )
    return holder.instance


def reconfigure_service_discovery(discovery_type: str,
                                  **kwargs) -> ServiceDiscovery:
    holder = _DiscoveryHolder()
    old = holder.instance
    new = initialize_service_discovery(discovery_type, **kwargs)
    if old is not None and old is not new:
        old.close()
    return new


def get_service_discovery() -> ServiceDiscovery:
    holder = _DiscoveryHolder()
    if holder.instance is None:
        raise ValueError("Service discovery has not been initialized")
    return holder.instance
