"""Service discovery: which engine endpoints exist and what they serve.

Capability parity with reference src/vllm_router/service_discovery.py:
``StaticServiceDiscovery`` (fixed URL/model lists, L64) and
``K8sServiceDiscovery`` (label-selector pod watch + readiness + model probe,
L85-239), with global init/get/reconfigure (L293-337). The K8s backend is
import-gated: the ``kubernetes`` client is only required when selected.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import requests

from production_stack_tpu.utils import SingletonMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


@dataclass
class EndpointInfo:
    url: str
    model_names: List[str] = field(default_factory=list)
    added_timestamp: float = field(default_factory=time.time)
    pod_name: Optional[str] = None

    def serves_model(self, model: str) -> bool:
        return not self.model_names or model in self.model_names


class ServiceDiscoveryType(str, enum.Enum):
    STATIC = "static"
    K8S = "k8s"


class ServiceDiscovery:
    def get_endpoint_info(self) -> List[EndpointInfo]:
        raise NotImplementedError

    def get_health(self) -> bool:
        return True

    def close(self) -> None:
        pass


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed backend list from --static-backends / --static-models flags."""

    def __init__(self, urls: List[str],
                 models: Optional[List[str]] = None):
        if models and len(models) != len(urls):
            raise ValueError(
                "static models list must match static backends list"
            )
        now = time.time()
        self._endpoints = [
            EndpointInfo(
                url=url,
                model_names=[models[i]] if models else [],
                added_timestamp=now,
            )
            for i, url in enumerate(urls)
        ]

    def get_endpoint_info(self) -> List[EndpointInfo]:
        return list(self._endpoints)


class K8sServiceDiscovery(ServiceDiscovery):
    """Kubernetes pod watch: pods matching a label selector become engines.

    A daemon thread runs a watch on pods in *namespace*; on ADDED/MODIFIED
    ready pods, the pod IP is probed at ``GET /v1/models`` to learn what it
    serves; on DELETED/not-ready, the endpoint is removed so traffic stops.
    """

    _MODEL_PROBE_TIMEOUT_S = 5.0

    def __init__(self, namespace: str, port: int, label_selector: str):
        try:
            from kubernetes import client, config, watch  # noqa: F401
        except ImportError as e:  # pragma: no cover - env without k8s
            raise RuntimeError(
                "K8s service discovery requires the 'kubernetes' package; "
                "use --service-discovery static in this environment"
            ) from e
        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        self._core = client.CoreV1Api()
        self._watch = watch.Watch()
        self.namespace = namespace
        self.port = port
        self.label_selector = label_selector
        self._endpoints: Dict[str, EndpointInfo] = {}  # pod name -> info
        self._lock = threading.Lock()
        self._running = True
        self._thread = threading.Thread(
            target=self._watch_pods, daemon=True, name="k8s-pod-watcher"
        )
        self._thread.start()

    @staticmethod
    def _pod_is_ready(pod) -> bool:
        conditions = (pod.status and pod.status.conditions) or []
        return any(
            c.type == "Ready" and c.status == "True" for c in conditions
        )

    def _probe_models(self, url: str) -> List[str]:
        try:
            resp = requests.get(
                f"{url}/v1/models", timeout=self._MODEL_PROBE_TIMEOUT_S
            )
            resp.raise_for_status()
            return [m["id"] for m in resp.json().get("data", [])]
        except Exception as e:
            logger.warning("Model probe failed for %s: %s", url, e)
            return []

    def _watch_pods(self) -> None:
        from kubernetes import watch
        while self._running:
            try:
                self._watch = watch.Watch()
                stream = self._watch.stream(
                    self._core.list_namespaced_pod,
                    namespace=self.namespace,
                    label_selector=self.label_selector,
                )
                for event in stream:
                    if not self._running:
                        break
                    self._handle_event(event)
            except Exception as e:
                if self._running:
                    logger.error("Pod watch error, retrying: %s", e)
                    time.sleep(1)

    def _handle_event(self, event) -> None:
        pod = event["object"]
        name = pod.metadata.name
        etype = event["type"]
        ready = self._pod_is_ready(pod) and pod.status.pod_ip
        if etype in ("ADDED", "MODIFIED") and ready:
            url = f"http://{pod.status.pod_ip}:{self.port}"
            with self._lock:
                known = self._endpoints.get(name)
            if known is None or known.url != url:
                models = self._probe_models(url)
                with self._lock:
                    self._endpoints[name] = EndpointInfo(
                        url=url, model_names=models, pod_name=name
                    )
                logger.info("Engine pod up: %s -> %s (%s)", name, url, models)
        elif etype == "DELETED" or not ready:
            with self._lock:
                if self._endpoints.pop(name, None) is not None:
                    logger.info("Engine pod removed: %s", name)

    def get_endpoint_info(self) -> List[EndpointInfo]:
        with self._lock:
            return list(self._endpoints.values())

    def get_health(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._running = False
        try:
            self._watch.stop()
        except Exception:
            pass


class _DiscoveryHolder(metaclass=SingletonMeta):
    def __init__(self):
        self.instance: Optional[ServiceDiscovery] = None


def initialize_service_discovery(discovery_type: str,
                                 **kwargs) -> ServiceDiscovery:
    holder = _DiscoveryHolder()
    dtype = ServiceDiscoveryType(discovery_type)
    if dtype == ServiceDiscoveryType.STATIC:
        holder.instance = StaticServiceDiscovery(
            urls=kwargs["urls"], models=kwargs.get("models")
        )
    else:
        holder.instance = K8sServiceDiscovery(
            namespace=kwargs.get("namespace", "default"),
            port=int(kwargs.get("port", 8000)),
            label_selector=kwargs.get("label_selector", ""),
        )
    return holder.instance


def reconfigure_service_discovery(discovery_type: str,
                                  **kwargs) -> ServiceDiscovery:
    holder = _DiscoveryHolder()
    old = holder.instance
    new = initialize_service_discovery(discovery_type, **kwargs)
    if old is not None and old is not new:
        old.close()
    return new


def get_service_discovery() -> ServiceDiscovery:
    holder = _DiscoveryHolder()
    if holder.instance is None:
        raise ValueError("Service discovery has not been initialized")
    return holder.instance
