"""Router application assembly (parity: src/vllm_router/app.py +
routers/main_router.py + files/batches routers).

One aiohttp application; daemon threads for the pod watcher, metrics
scraper, config watcher and stats logger; everything else async on the
event loop. API surface:

  POST /v1/chat/completions | /v1/completions | /v1/embeddings
       /v1/rerank | /rerank | /v1/score | /score      -> proxied to engines
  GET  /v1/models   aggregated from discovery
  GET  /health      composes thread liveness + dynamic config
  GET  /version, /metrics
  Files API  POST/GET/DELETE /v1/files...
  Batch API  POST/GET /v1/batches...   (--enable-batch-api)
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router import protocols
from production_stack_tpu.router.dynamic_config import (
    get_dynamic_config_watcher,
    initialize_dynamic_config_watcher,
)
from production_stack_tpu.router.experimental.feature_gates import (
    PII_DETECTION_GATE,
    SEMANTIC_CACHE_GATE,
    get_feature_gates,
    initialize_feature_gates,
)
from production_stack_tpu.router.parser import parse_args
from production_stack_tpu.router.resilience import (
    ResilienceConfig,
    get_resilience,
    initialize_resilience,
)
from production_stack_tpu.router.routing.logic import (
    initialize_routing_logic,
)
from production_stack_tpu.router.service_discovery import (
    get_service_discovery,
    initialize_service_discovery,
)
from production_stack_tpu.router.services.batch import (
    initialize_batch_processor,
)
from production_stack_tpu.router.services.files import initialize_storage
from production_stack_tpu.router.services.metrics_service import (
    render_exposition,
)
from production_stack_tpu.router.services.request_service import (
    route_general_request,
)
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    get_engine_stats_scraper,
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.log_stats import log_stats
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)
from production_stack_tpu.utils import (
    parse_comma_separated_urls,
    parse_comma_separated_values,
    set_ulimit,
)
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.version import __version__

logger = init_logger(__name__)

PROXY_PATHS = [
    "/v1/chat/completions",
    "/v1/completions",
    "/v1/embeddings",
    "/v1/rerank",
    "/rerank",
    "/v1/score",
    "/score",
]


# ---- handlers --------------------------------------------------------------

def _make_proxy_handler(path: str):
    async def handler(request: web.Request) -> web.StreamResponse:
        gates = get_feature_gates()
        if path == "/v1/chat/completions" and gates.enabled(
                SEMANTIC_CACHE_GATE):
            from production_stack_tpu.router.experimental.semantic_cache \
                import integration as sc
            hit = await sc.check_semantic_cache(request)
            if hit is not None:
                return hit
        if gates.enabled(PII_DETECTION_GATE):
            from production_stack_tpu.router.experimental.pii import (
                middleware as pii,
            )
            blocked = await pii.check_request(request)
            if blocked is not None:
                return blocked
        return await route_general_request(request, path)

    return handler


async def show_models(request: web.Request) -> web.Response:
    cards = {}
    try:
        endpoints = get_service_discovery().get_endpoint_info()
    except ValueError:
        endpoints = []
    for ep in endpoints:
        for model in ep.model_names:
            cards.setdefault(model, protocols.ModelCard(id=model))
    return web.json_response(
        protocols.ModelList(data=list(cards.values())).model_dump()
    )


async def health(request: web.Request) -> web.Response:
    try:
        discovery = get_service_discovery()
    except ValueError:
        return web.json_response(
            {"status": "starting"}, status=503
        )
    if not discovery.get_health():
        return web.json_response(
            {"status": "Service discovery module is down."}, status=503
        )
    if not get_engine_stats_scraper().get_health():
        return web.json_response(
            {"status": "Engine stats scraper is down."}, status=503
        )
    body = {"status": "healthy"}
    mgr = get_resilience()
    if mgr is not None:
        endpoints = discovery.get_endpoint_info(include_unhealthy=True)
        available = [
            ep.url for ep in endpoints
            if mgr.endpoint_available(ep.url)
        ]
        open_breakers = [
            url for url, br in mgr.breaker_snapshot().items()
            if int(br.state) != 0
        ]
        body["resilience"] = {
            "endpoints_total": len(endpoints),
            "endpoints_available": len(available),
            "tripped_breakers": sorted(open_breakers),
        }
    watcher = get_dynamic_config_watcher()
    if watcher is not None:
        config = watcher.get_current_config()
        body["dynamic_config"] = config.to_dict() if config else None
    return web.json_response(body)


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


async def metrics(request: web.Request) -> web.Response:
    payload, content_type = render_exposition()
    return web.Response(body=payload, content_type=content_type.split(";")[0])


async def cluster_status(request: web.Request) -> web.Response:
    """Single-JSON fleet rollup (docs/observability.md): the engine
    stats scrape loop, SLO ledger, drift sentinel and slow-archive
    counters folded into one snapshot. ``python -m
    production_stack_tpu.stacktop`` renders this."""
    from production_stack_tpu import obs
    from production_stack_tpu.obs.cluster_status import build_snapshot
    try:
        endpoints = get_service_discovery().get_endpoint_info(
            include_unhealthy=True)
    except ValueError:
        endpoints = []
    try:
        engine_stats = get_engine_stats_scraper().get_engine_stats()
    except ValueError:
        engine_stats = {}
    mgr = get_resilience()
    healthy = {ep.url: (mgr is None or mgr.endpoint_available(ep.url))
               for ep in endpoints}
    from production_stack_tpu.router.dynamic_config import (
        get_dynamic_config_watcher,
    )
    watcher = get_dynamic_config_watcher()
    config = watcher.get_current_config() if watcher else None
    rollout = config.rollout_status if config else None
    return web.json_response(build_snapshot(
        engine_stats, endpoints=endpoints, healthy=healthy,
        ledger=obs.get_slo_ledger(), archive=obs.get_slow_archive(),
        sentinel=obs.get_drift_sentinel(), rollout=rollout))


async def debug_slow(request: web.Request) -> web.Response:
    """Slow-request exemplar ring:
    ``GET /debug/slow?class=&model=&limit=`` (docs/observability.md)."""
    from production_stack_tpu import obs
    archive = obs.get_slow_archive()
    if archive is None:
        return web.json_response(
            {"error": {"message": "slow archive not initialized"}},
            status=503)
    try:
        limit = int(request.query.get("limit", 50))
    except ValueError:
        return web.json_response(
            {"error": {"message": "limit must be an integer"}},
            status=400)
    entries = archive.snapshot(
        priority_class=request.query.get("class") or None,
        model=request.query.get("model") or None,
        limit=limit)
    return web.json_response({
        "entries": entries,
        "depth": archive.depth(),
        "capacity": archive.capacity,
        "archived_total": archive.archived_total,
    })


# ---- files API -------------------------------------------------------------

def _user_id(request: web.Request) -> str:
    return request.headers.get("x-user-id", "anonymous")


async def upload_file(request: web.Request) -> web.Response:
    storage = request.app["file_storage"]
    reader = await request.multipart()
    filename, content, purpose = "upload", b"", "batch"
    async for part in reader:
        if part.name == "file":
            filename = part.filename or filename
            content = await part.read(decode=False)
        elif part.name == "purpose":
            purpose = (await part.text()).strip() or purpose
    file = await storage.save_file(
        _user_id(request), filename, content, purpose=purpose
    )
    return web.json_response(file.metadata())


async def list_files(request: web.Request) -> web.Response:
    storage = request.app["file_storage"]
    files = await storage.list_files(_user_id(request))
    return web.json_response(
        {"object": "list", "data": [f.metadata() for f in files]}
    )


async def get_file(request: web.Request) -> web.Response:
    storage = request.app["file_storage"]
    try:
        file = await storage.get_file(
            _user_id(request), request.match_info["file_id"]
        )
    except FileNotFoundError:
        return web.json_response(
            {"error": {"message": "File not found"}}, status=404
        )
    return web.json_response(file.metadata())


async def get_file_content(request: web.Request) -> web.Response:
    storage = request.app["file_storage"]
    try:
        content = await storage.get_file_content(
            _user_id(request), request.match_info["file_id"]
        )
    except FileNotFoundError:
        return web.json_response(
            {"error": {"message": "File not found"}}, status=404
        )
    return web.Response(body=content,
                        content_type="application/octet-stream")


async def delete_file(request: web.Request) -> web.Response:
    storage = request.app["file_storage"]
    file_id = request.match_info["file_id"]
    await storage.delete_file(_user_id(request), file_id)
    return web.json_response(
        {"id": file_id, "object": "file", "deleted": True}
    )


# ---- batch API -------------------------------------------------------------

def _batch_processor(request: web.Request):
    processor = request.app.get("batch_processor")
    if processor is None:
        raise web.HTTPNotImplemented(
            text='{"error": {"message": "Batch API disabled; start the '
                 'router with --enable-batch-api"}}',
            content_type="application/json",
        )
    return processor


async def create_batch(request: web.Request) -> web.Response:
    processor = _batch_processor(request)
    body = await request.json()
    try:
        info = await processor.create_batch(
            _user_id(request),
            input_file_id=body["input_file_id"],
            endpoint=body["endpoint"],
            completion_window=body.get("completion_window", "24h"),
            metadata=body.get("metadata"),
        )
    except KeyError as e:
        return web.json_response(
            {"error": {"message": f"Missing field: {e}"}}, status=400
        )
    return web.json_response(info.to_dict())


async def retrieve_batch(request: web.Request) -> web.Response:
    processor = _batch_processor(request)
    try:
        info = await processor.retrieve_batch(
            _user_id(request), request.match_info["batch_id"]
        )
    except FileNotFoundError:
        return web.json_response(
            {"error": {"message": "Batch not found"}}, status=404
        )
    return web.json_response(info.to_dict())


async def list_batches(request: web.Request) -> web.Response:
    processor = _batch_processor(request)
    batches = await processor.list_batches(_user_id(request))
    return web.json_response(
        {"object": "list", "data": [b.to_dict() for b in batches]}
    )


async def cancel_batch(request: web.Request) -> web.Response:
    processor = _batch_processor(request)
    try:
        info = await processor.cancel_batch(
            _user_id(request), request.match_info["batch_id"]
        )
    except FileNotFoundError:
        return web.json_response(
            {"error": {"message": "Batch not found"}}, status=404
        )
    return web.json_response(info.to_dict())


# ---- assembly --------------------------------------------------------------

def initialize_all(app: web.Application, args) -> None:
    if args.service_discovery == "static":
        initialize_service_discovery(
            "static",
            urls=parse_comma_separated_urls(args.static_backends),
            models=parse_comma_separated_values(args.static_models) or None,
            roles=parse_comma_separated_values(
                getattr(args, "static_roles", None)) or None,
        )
    else:
        initialize_service_discovery(
            "k8s", namespace=args.k8s_namespace, port=args.k8s_port,
            label_selector=args.k8s_label_selector,
        )
    initialize_resilience(ResilienceConfig(
        max_retries=args.max_retries,
        backend_connect_timeout=args.backend_connect_timeout,
        backend_timeout=args.backend_timeout,
        health_check_interval=args.health_check_interval,
        health_check_timeout=args.health_check_timeout,
        health_failure_threshold=args.health_failure_threshold,
        health_success_threshold=args.health_success_threshold,
        breaker_window=args.breaker_window,
        breaker_min_volume=args.breaker_min_volume,
        breaker_failure_rate=args.breaker_failure_rate,
        breaker_open_base_s=args.breaker_open_seconds,
        breaker_open_max_s=args.breaker_max_open_seconds,
    ))
    from production_stack_tpu.router.qos import (
        initialize_router_qos,
        RouterQoSConfig,
    )
    initialize_router_qos(RouterQoSConfig(
        tenant_rate=getattr(args, "qos_tenant_rate", 0.0),
        tenant_burst=getattr(args, "qos_tenant_burst", 20.0),
        degrade_max_tokens=getattr(args, "qos_degrade_max_tokens", 128),
        shed_deficit=getattr(args, "qos_shed_deficit", 10.0),
        max_concurrency=getattr(args, "qos_max_concurrency", 0),
    ))
    initialize_engine_stats_scraper(args.engine_stats_interval)
    initialize_request_stats_monitor(args.request_stats_window)
    initialize_routing_logic(args.routing_logic,
                             session_key=args.session_key)
    initialize_request_rewriter(args.request_rewriter)
    initialize_feature_gates(args.feature_gates)
    from production_stack_tpu.router.tracing import (
        initialize_span_logger,
    )
    initialize_span_logger(getattr(args, "request_span_log", None))
    # SLO ledger + slow-request archive + drift sentinel (obs/,
    # docs/observability.md). install() overwrites any previous
    # instances, so repeated initialize_all calls in test rigs reset
    # cleanly.
    from production_stack_tpu import obs
    # The slow archive only fills on SLO breaches, so it rides the
    # ledger: without --slo-spec, GET /debug/slow honestly 503s
    # instead of serving a forever-empty ring.
    has_slo = bool(getattr(args, "slo_spec", None))
    obs.install(
        ledger=(obs.SLOLedger(obs.SLOSpec.load(args.slo_spec))
                if has_slo else None),
        archive=(obs.SlowArchive(
            getattr(args, "slow_archive_size", 64) or 64)
            if has_slo else None),
        sentinel=(obs.DriftSentinel.load(args.perf_baseline)
                  if getattr(args, "perf_baseline", None) else None),
    )

    app["file_storage"] = initialize_storage(
        args.file_storage_class, args.file_storage_path
    )
    app["enable_batch_api"] = args.enable_batch_api
    app["batch_processor_kind"] = args.batch_processor

    if args.dynamic_config_json:
        initialize_dynamic_config_watcher(args.dynamic_config_json)
    if args.log_stats:
        log_stats(args.log_stats_interval)


def build_app(args=None) -> web.Application:
    app = web.Application(client_max_size=1024 ** 3)

    async def on_startup(app: web.Application):
        mgr = get_resilience()
        session_timeout = (
            mgr.config.client_timeout() if mgr is not None
            else aiohttp.ClientTimeout(total=None, sock_connect=30)
        )
        app["backend_session"] = aiohttp.ClientSession(
            timeout=session_timeout,
            connector=aiohttp.TCPConnector(limit=0),
        )
        if mgr is not None:
            await mgr.start()
        if app.get("enable_batch_api"):
            processor = initialize_batch_processor(
                app.get("batch_processor_kind", "local"),
                app["file_storage"],
            )
            await processor.initialize()
            app["batch_processor"] = processor

    async def on_cleanup(app: web.Application):
        mgr = get_resilience()
        if mgr is not None:
            await mgr.stop()
        processor = app.get("batch_processor")
        if processor is not None:
            await processor.close()
        session = app.get("backend_session")
        if session is not None:
            await session.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)

    for path in PROXY_PATHS:
        app.router.add_post(path, _make_proxy_handler(path))
    app.router.add_get("/v1/models", show_models)
    app.router.add_get("/health", health)
    app.router.add_get("/version", version)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/cluster/status", cluster_status)
    app.router.add_get("/debug/slow", debug_slow)

    app.router.add_post("/v1/files", upload_file)
    app.router.add_get("/v1/files", list_files)
    app.router.add_get("/v1/files/{file_id}", get_file)
    app.router.add_get("/v1/files/{file_id}/content", get_file_content)
    app.router.add_delete("/v1/files/{file_id}", delete_file)

    app.router.add_post("/v1/batches", create_batch)
    app.router.add_get("/v1/batches", list_batches)
    app.router.add_get("/v1/batches/{batch_id}", retrieve_batch)
    app.router.add_post("/v1/batches/{batch_id}/cancel", cancel_batch)

    if args is not None:
        initialize_all(app, args)
    return app


def main(argv=None) -> None:
    args = parse_args(argv)
    import logging
    logging.getLogger().setLevel(args.log_level.upper())
    set_ulimit()
    app = build_app(args)
    logger.info("tpu-router %s listening on %s:%d",
                __version__, args.host, args.port)
    web.run_app(app, host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
