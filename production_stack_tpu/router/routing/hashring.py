"""Consistent hash ring (replaces the reference's uhashring dependency).

Used by the session router for sticky sessions with minimal remapping when
the endpoint set changes: each node is placed at ``vnodes`` pseudo-random
points on a 2^64 ring; a key maps to the first node clockwise.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional


def _hash64(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )


class ConsistentHashRing:
    def __init__(self, vnodes: int = 128):
        self.vnodes = vnodes
        self._ring: List[int] = []  # sorted vnode positions
        self._owner: Dict[int, str] = {}  # position -> node
        self._nodes: set[str] = set()

    def get_nodes(self) -> List[str]:
        return sorted(self._nodes)

    def add_node(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self.vnodes):
            pos = _hash64(f"{node}#{i}")
            # On the (vanishingly rare) collision keep the lexicographically
            # smaller owner so add/remove order doesn't matter.
            if pos in self._owner:
                if node >= self._owner[pos]:
                    continue
                self._owner[pos] = node
                continue
            bisect.insort(self._ring, pos)
            self._owner[pos] = node

    def remove_node(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        for i in range(self.vnodes):
            pos = _hash64(f"{node}#{i}")
            if self._owner.get(pos) == node:
                del self._owner[pos]
                idx = bisect.bisect_left(self._ring, pos)
                if idx < len(self._ring) and self._ring[idx] == pos:
                    self._ring.pop(idx)

    def sync(self, nodes: List[str]) -> None:
        """Make the ring contain exactly *nodes* (minimal churn)."""
        target = set(nodes)
        for node in self._nodes - target:
            self.remove_node(node)
        for node in target - self._nodes:
            self.add_node(node)

    def get_node(self, key: str) -> Optional[str]:
        if not self._ring:
            return None
        pos = _hash64(key)
        idx = bisect.bisect_right(self._ring, pos)
        if idx == len(self._ring):
            idx = 0
        return self._owner[self._ring[idx]]
