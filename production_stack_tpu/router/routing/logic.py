"""Pluggable routing policies.

Capability parity with reference src/vllm_router/routers/routing_logic.py:
roundrobin (L50), session consistent-hash + QPS fallback (L88), llq
least-loaded (L186), hra head-room admission with SJF queue (L272), and the
work-estimate custom policy (L408). Fresh implementation: policies receive a
plain headers mapping (not a framework request object) and the HRA policy
returns an ``asyncio.Future`` the proxy awaits until admission.
"""

from __future__ import annotations

import abc
import asyncio
import enum
import heapq
import itertools
import random
import time
from dataclasses import dataclass, field as dataclass_field
from math import ceil
from typing import Dict, List, Mapping, Optional, Tuple, Union

from production_stack_tpu.kvecon.summary import (
    TOKENS_PER_BLOCK,
    chain_text,
    expected_hit_blocks,
)
from production_stack_tpu.router.routing.hashring import ConsistentHashRing
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.router.stats.engine_stats import EngineStats
from production_stack_tpu.router.stats.request_stats import (
    BLOCK_SIZE,
    DECODE_TO_PREFILL_RATIO,
    SAFETY_FRACTION,
    TOTAL_NUMBER_OF_BLOCKS,
    RequestStats,
    get_request_stats_monitor,
)
from production_stack_tpu.utils import SingletonABCMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

RouteResult = Union[str, "asyncio.Future[str]"]

# -- canary traffic weighting (fleet rollouts, docs/fleet.md) ---------------
# url -> dispatch traffic share for baking canaries, and the set of
# backends in a migrate-mode drain. Both are pushed by the dynamic
# config (apply_dynamic_config) whenever the fleet rewrites its file.
_canary_weights: Dict[str, float] = {}
_migrating_urls: frozenset = frozenset()
_canary_rng = random.Random()


def set_canary_weights(weights: Optional[Dict[str, float]]) -> None:
    global _canary_weights
    _canary_weights = dict(weights or {})


def set_migrating_urls(urls) -> None:
    global _migrating_urls
    _migrating_urls = frozenset(urls or ())


def get_migrating_urls() -> frozenset:
    """Backends whose mid-stream deaths are planned migrations: the
    failover path resumes their streams elsewhere under the
    ``migrated`` outcome instead of charging a crash."""
    return _migrating_urls


def canary_split(candidates: List[EndpointInfo]) -> List[EndpointInfo]:
    """Steer one dispatch between baking canaries and the stable set.

    With probability equal to its weight a canary takes the request
    (the candidate list collapses to canaries only); otherwise canaries
    drop out so the stable set keeps serving the remainder. Only the
    initial dispatch is weighted — retry/failover/resume paths pass
    their candidates straight to the policy so a struggling stable set
    can still fail over onto a healthy canary."""
    if not _canary_weights or not candidates:
        return candidates
    canaries = [ep for ep in candidates if ep.url in _canary_weights]
    if not canaries or len(canaries) == len(candidates):
        return candidates
    weight = max(_canary_weights[ep.url] for ep in canaries)
    if _canary_rng.random() < weight:
        return canaries
    return [ep for ep in candidates if ep.url not in _canary_weights]


def usable_endpoints(endpoints: List[EndpointInfo],
                     exclude=()) -> List[EndpointInfo]:
    """The endpoints a new attempt may target: not in *exclude* (URLs
    already tried by this request), not marked unhealthy by the active
    health checker, and not behind a tripped circuit breaker. With the
    resilience layer uninitialized this is just the exclude filter."""
    from production_stack_tpu.router.resilience import get_resilience
    pool = [ep for ep in endpoints if ep.url not in exclude]
    mgr = get_resilience()
    if mgr is None:
        return pool
    return [ep for ep in pool if mgr.endpoint_available(ep.url)]


def filter_by_role(endpoints: List[EndpointInfo],
                   role: str) -> List[EndpointInfo]:
    """Endpoints deployed as exactly *role*. Disagg dispatch
    (request_service._route_disagg) engages only when both a strict
    'prefill' and a strict 'decode' pool are non-empty; 'both'
    (monolithic) endpoints never join either hop — they serve the
    fallback path instead."""
    return [ep for ep in endpoints
            if getattr(ep, "role", "both") == role]


class RoutingLogic(str, enum.Enum):
    ROUND_ROBIN = "roundrobin"
    SESSION_BASED = "session"
    LEAST_LOADED = "llq"
    HRA = "hra"
    PREFIX_AWARE = "prefixaware"
    KV_STATE_AWARE = "kvstateaware"
    CUSTOM_LOGIC = "custom"


class RoutingPolicy(metaclass=SingletonABCMeta):
    """A routing decision: pick an engine URL for one request.

    ``route_request`` may return the URL directly, or (for admission-control
    policies) an asyncio Future resolving to the URL once admitted.
    """

    @abc.abstractmethod
    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats: Dict[str, EngineStats],
        request_stats: Dict[str, RequestStats],
        headers: Mapping[str, str],
        request_id: str,
        num_prefill_tokens: int = 0,
        prompt_text: Optional[str] = None,
    ) -> RouteResult:
        raise NotImplementedError

    # Policies that score the request's prompt text set this; the
    # proxy only pays the text extraction when someone will read it.
    uses_prompt_text = False

    def on_request_complete(self, engine_url: str) -> None:
        """Hook fired when any request finishes; admission policies use it."""


def _mark_routed(url: str, request_id: str, num_prefill_tokens: int) -> str:
    get_request_stats_monitor().on_request_routed(
        url, request_id, num_prefill_tokens
    )
    return url


class RoundRobinPolicy(RoutingPolicy):
    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self._counter = itertools.count()
        self._initialized = True

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None) -> str:
        ordered = sorted(endpoints, key=lambda e: e.url)
        url = ordered[next(self._counter) % len(ordered)].url
        return _mark_routed(url, request_id, num_prefill_tokens)


class SessionPolicy(RoutingPolicy):
    """Sticky sessions via consistent hashing on a header key.

    Requests without the session header fall back to lowest-QPS placement.
    """

    def __init__(self, session_key: Optional[str] = None):
        if getattr(self, "_initialized", False):
            return
        if not session_key:
            raise ValueError("SessionPolicy requires a session_key")
        self.session_key = session_key
        self._ring = ConsistentHashRing()
        self._initialized = True

    @staticmethod
    def _lowest_qps(endpoints, request_stats) -> str:
        best_url, best_qps = None, float("inf")
        for ep in endpoints:
            stat = request_stats.get(ep.url)
            if stat is None:
                return ep.url  # never seen traffic: coldest
            if stat.qps < best_qps:
                best_qps, best_url = stat.qps, ep.url
        return best_url

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None) -> str:
        self._ring.sync([ep.url for ep in endpoints])
        session_id = headers.get(self.session_key)
        if session_id is None:
            url = self._lowest_qps(endpoints, request_stats)
        else:
            url = self._ring.get_node(session_id)
        return _mark_routed(url, request_id, num_prefill_tokens)


class LeastLoadedPolicy(RoutingPolicy):
    """LLQ: route to the engine with the fewest in-flight requests.

    Ties break RANDOMLY among the least-loaded engines. A stable
    ``min()`` tie-break routed every equal-load arrival to the
    lowest-index engine, so consecutive arrivals burst onto one
    backend between count updates — measured 10-15% lower throughput
    and ~2x p99 TTFT vs roundrobin at 16 QPS on the fake-engine rig
    (benchmarks/results/llq_tiebreak.md). Randomizing the tie spreads
    those bursts without weakening the load signal.
    """

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        # Seeded so tests are reproducible; the tie population itself
        # is load-driven, the seed only orders equal choices.
        self._rng = random.Random(0x11A)
        self._initialized = True

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None) -> str:
        def load(url: str) -> int:
            stat = request_stats.get(url)
            if stat is None:
                return 0
            return stat.in_prefill_requests + stat.in_decoding_requests

        loads = [(load(ep.url), ep.url) for ep in endpoints]
        best = min(l for l, _ in loads)
        candidates = [u for l, u in loads if l == best]
        url = (candidates[0] if len(candidates) == 1
               else self._rng.choice(candidates))
        return _mark_routed(url, request_id, num_prefill_tokens)


@dataclass(order=True)
class _PendingAdmission:
    """Heap entry: ordering fields first so heapq compares SJF-then-FIFO
    ((prefill_tokens, seqno)) without ever comparing futures."""

    prefill_tokens: int
    seqno: int  # arrival order; also the FIFO tiebreak among equals
    arrived_at: float = dataclass_field(compare=False, default=0.0)
    endpoints: List[EndpointInfo] = dataclass_field(
        compare=False, default_factory=list)
    future: "asyncio.Future[str]" = dataclass_field(
        compare=False, default=None)
    request_id: str = dataclass_field(compare=False, default="")


class AdmissionError(Exception):
    """Raised (via the admission future) when a request can never fit."""


class HeadRoomAdmissionPolicy(RoutingPolicy):
    """HRA: block-budget admission control with an SJF queue.

    A request is only admitted to a replica whose projected KV-block usage
    (allocated + pending-reserved + this request's pessimistic demand)
    leaves at least ``SAFETY_FRACTION`` of the budget free. Inadmissible
    requests wait on a future; completions re-trigger scheduling. Shortest
    job first, FIFO among equals; head-of-line blocking is intentional
    (a short unschedulable request gates longer ones). Requests whose
    demand exceeds the budget of an *empty* engine are rejected outright
    rather than wedging the queue forever.

    The queue is a binary heap keyed (prefill_tokens, seqno): O(log n)
    per arrival/admission instead of the round-1 re-sort per arrival +
    list.pop(0) per admission — under burst churn (hundreds queued,
    tests/test_routing_logic.py churn test) drains stay cheap.
    """

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self._queue: List[_PendingAdmission] = []  # heapq
        self._seq = itertools.count()
        self._initialized = True

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None):
        # get_running_loop, not get_event_loop: the policy only ever
        # runs inside the router's serving loop, and under Python 3.12
        # semantics get_event_loop() from a coroutine without a set
        # loop deprecation-warns (and will raise) instead of returning
        # the running one.
        future: "asyncio.Future[str]" = (
            asyncio.get_running_loop().create_future()
        )
        max_admissible = int(
            TOTAL_NUMBER_OF_BLOCKS * (1 - SAFETY_FRACTION)
        )
        if self.block_demand(num_prefill_tokens) > max_admissible:
            future.set_exception(AdmissionError(
                f"Request needs {self.block_demand(num_prefill_tokens)} KV "
                f"blocks but at most {max_admissible} can ever be admitted"
            ))
            return future
        heapq.heappush(self._queue, _PendingAdmission(
            prefill_tokens=num_prefill_tokens,
            seqno=next(self._seq),
            arrived_at=time.time(),
            endpoints=list(endpoints),
            future=future,
            request_id=request_id,
        ))
        self._drain_queue()
        return future

    def on_request_complete(self, engine_url: str) -> None:
        self._drain_queue()

    @staticmethod
    def block_demand(prefill_tokens: int) -> int:
        return ceil(
            prefill_tokens * (1 + DECODE_TO_PREFILL_RATIO) / BLOCK_SIZE
        )

    def _drain_queue(self) -> None:
        if not self._queue:
            return
        monitor = get_request_stats_monitor()
        snapshot = monitor.get_request_stats(time.time())

        urls = {ep.url for p in self._queue for ep in p.endpoints}
        allocated = {u: monitor.estimate_allocated_blocks(u) for u in urls}
        reserved = {
            u: monitor.estimate_pending_reserved_blocks(u) for u in urls
        }
        qlen = {
            u: (snapshot[u].in_prefill_requests
                + snapshot[u].in_decoding_requests) if u in snapshot else 0
            for u in urls
        }
        headroom = int(TOTAL_NUMBER_OF_BLOCKS * SAFETY_FRACTION)

        while self._queue:
            pending = self._queue[0]
            if pending.future.done():
                # Client gave up (disconnect cancels the future): drop the
                # entry without registering a phantom reservation.
                heapq.heappop(self._queue)
                continue
            demand = self.block_demand(pending.prefill_tokens)
            fits = [
                ep.url for ep in pending.endpoints
                if (TOTAL_NUMBER_OF_BLOCKS
                    - (allocated[ep.url] + reserved[ep.url] + demand))
                >= headroom
            ]
            if not fits:
                break  # SJF head-of-line block
            heapq.heappop(self._queue)
            target = min(fits, key=lambda u: (qlen[u],
                                              allocated[u] + reserved[u]))
            monitor.on_request_routed(
                target, pending.request_id, pending.prefill_tokens
            )
            pending.future.set_result(target)
            reserved[target] += demand
            qlen[target] += 1


class PrefixAwarePolicy(RoutingPolicy):
    """KV-aware placement: route to the engine most likely to hold the
    request's prompt prefix in its paged KV cache.

    The engines' prefix caches are content-chained on token pages
    (engine/kv_cache.py); the router cannot tokenize, so it
    approximates the same structure on TEXT: the prompt is split into
    fixed-size character blocks and chain-hashed, and each engine
    carries a bounded LRU of the chains it has recently served. A new
    request scores every candidate by longest matching chain prefix
    and routes to the best (ties broken by fewest in-flight). Requests
    with no text or no match fall back to least-loaded.

    Affinity is LOAD-BOUNDED: the prefix match only wins while the
    preferred engine's in-flight count stays within
    ``SPILL_FACTOR x min + SPILL_SLACK`` of the least-loaded
    candidate; beyond that the request spills to the least-loaded
    engine and its chain is remembered THERE too (the spill target
    will hold the prefix after serving it), so a hot shared prefix
    replicates across engines instead of pinning the fleet's traffic
    to one replica forever.

    This is the BASELINE.md north-star "KV-aware routing" (the
    reference's roadmap item via LMCache-aware routing) built on this
    stack's own chain-hash prefix model — multi-round chats and
    shared-system-prompt fleets keep hitting a replica whose HBM
    already holds their context, without session headers.
    """

    BLOCK_CHARS = 256  # ~64 tokens per block at 4 chars/token
    MAX_CHAINS_PER_ENGINE = 4096
    SPILL_FACTOR = 2
    SPILL_SLACK = 4
    uses_prompt_text = True

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        # url -> ordered {chain_hash: None} acting as an LRU set.
        self._index: Dict[str, "OrderedDict[int, None]"] = {}
        self._initialized = True

    def _chain(self, text: str) -> List[int]:
        # Canonical implementation lives in kvecon.summary so the
        # router's text chains stay byte-identical to the hot chains
        # the engines advertise at GET /kv/summary (blake2b, not
        # builtin hash(), because str hashing is salted per process).
        return chain_text(text, self.BLOCK_CHARS)

    def _remember(self, url: str, chain: List[int]) -> None:
        from collections import OrderedDict
        lru = self._index.setdefault(url, OrderedDict())
        for h in chain:
            lru.pop(h, None)
            lru[h] = None
        while len(lru) > self.MAX_CHAINS_PER_ENGINE:
            lru.popitem(last=False)

    def _score(self, url: str, chain: List[int]) -> int:
        lru = self._index.get(url)
        if not lru:
            return 0
        n = 0
        for h in chain:
            if h not in lru:
                break
            n += 1
        return n

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None) -> str:
        def load(url: str) -> int:
            stat = request_stats.get(url)
            if stat is None:
                return 0
            return stat.in_prefill_requests + stat.in_decoding_requests

        # Engines that left the pool must not pin stale chains.
        live = {ep.url for ep in endpoints}
        for url in list(self._index):
            if url not in live:
                del self._index[url]

        chain = self._chain(prompt_text) if prompt_text else []
        loads = {ep.url: load(ep.url) for ep in endpoints}
        min_load = min(loads.values())
        if chain:
            scores = {ep.url: self._score(ep.url, chain)
                      for ep in endpoints}
            best = max(endpoints,
                       key=lambda ep: (scores[ep.url],
                                       -loads[ep.url])).url
            within_bound = loads[best] <= (
                self.SPILL_FACTOR * min_load + self.SPILL_SLACK)
            if scores[best] > 0 and within_bound:
                self._remember(best, chain)
                return _mark_routed(best, request_id,
                                    num_prefill_tokens)
        # Cold prefix, no text, or the preferred engine is overloaded:
        # least-loaded placement — and remember the chain there, so a
        # hot prefix replicates instead of pinning one engine.
        url = min(endpoints, key=lambda ep: loads[ep.url]).url
        if chain:
            self._remember(url, chain)
        return _mark_routed(url, request_id, num_prefill_tokens)


class KVStateAwarePolicy(RoutingPolicy):
    """Route on the KV state engines actually HOLD, not on chains the
    router remembers serving (docs/kv_economy.md).

    Each engine exports a rolling summary of its KV economy at
    ``GET /kv/summary`` — top-k hot chain hashes (hit-count-decayed),
    free-page headroom, kv_dtype — which rides the engine-stats scrape
    loop into ``EngineStats.kv_hot_chains`` / ``kv_free_page_headroom``.
    A request's prompt is chain-hashed with the same blake2b scheme
    and every candidate is scored:

        score = W_HIT * expected_hit_frac          # prefix reuse
              + W_HEADROOM * free_page_frac        # room to serve it
              - W_LOAD * load_frac                 # queue depth

    ``expected_hit_frac`` is the deepest chain hash of the prompt found
    in the engine's advertised hot set, over the prompt's block count.
    Unlike PrefixAwarePolicy's remembered-chain guess, this sees
    chains the engine computed for OTHER routers' traffic, chains it
    has evicted, and how much headroom is left — headroom varies
    1.9-3.55x with ``--kv-cache-dtype``, which remembered chains can't
    know.

    Summaries are trusted only within ``SUMMARY_STALENESS_S`` of their
    scrape; when NO candidate has a fresh summary (engines predate
    /kv/summary, scraper down) the policy degrades to a private
    PrefixAwarePolicy instance, which it keeps warm by recording every
    routed chain — the fallback starts with full affinity state, not
    cold.
    """

    SUMMARY_STALENESS_S = 30.0
    W_HIT = 2.0
    W_HEADROOM = 1.0
    W_LOAD = 0.25
    uses_prompt_text = True

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        # Private (non-singleton) fallback so configuring this policy
        # never registers a PrefixAwarePolicy in SingletonMeta.
        self._fallback = PrefixAwarePolicy.__new__(PrefixAwarePolicy)
        self._fallback._index = {}
        self._fallback._initialized = True
        # url -> expected prefix-hit tokens of the last request routed
        # there; exported as router gauge kv_route_expected_hit_tokens.
        self.expected_hit_tokens_by_url: Dict[str, float] = {}
        self._initialized = True

    def _summary_fresh(self, stats: Optional[EngineStats],
                       now: float) -> bool:
        return (stats is not None
                and stats.kv_summary_time > 0
                and now - stats.kv_summary_time
                <= self.SUMMARY_STALENESS_S)

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None) -> str:
        now = time.time()
        fresh = {ep.url for ep in endpoints
                 if self._summary_fresh(engine_stats.get(ep.url), now)}
        chain = chain_text(prompt_text) if prompt_text else []
        if not fresh:
            return self._fallback.route_request(
                endpoints, engine_stats, request_stats, headers,
                request_id, num_prefill_tokens, prompt_text)

        def load(url: str) -> int:
            stat = request_stats.get(url)
            if stat is None:
                return 0
            return stat.in_prefill_requests + stat.in_decoding_requests

        loads = {ep.url: load(ep.url) for ep in endpoints}
        max_load = max(loads.values()) or 1

        def score(url: str) -> Tuple[float, float]:
            es = engine_stats.get(url)
            hit_frac = 0.0
            headroom_frac = 0.5  # neutral when the engine is opaque
            if url in fresh:
                if chain:
                    hit_frac = expected_hit_blocks(
                        chain, es.kv_hot_chains) / len(chain)
                total = es.kv_total_pages
                if total > 0:
                    headroom_frac = min(
                        1.0, es.kv_free_page_headroom / total)
            s = (self.W_HIT * hit_frac
                 + self.W_HEADROOM * headroom_frac
                 - self.W_LOAD * loads[url] / max_load)
            return s, hit_frac

        scored = {ep.url: score(ep.url) for ep in endpoints}
        best = max(endpoints,
                   key=lambda ep: (scored[ep.url][0],
                                   -loads[ep.url], ep.url)).url
        self.expected_hit_tokens_by_url[best] = (
            scored[best][1] * len(chain) * TOKENS_PER_BLOCK)
        for url in list(self.expected_hit_tokens_by_url):
            if url not in loads:
                del self.expected_hit_tokens_by_url[url]
        if chain:
            # Keep the fallback's affinity index warm for degradation.
            self._fallback._remember(best, chain)
        return _mark_routed(best, request_id, num_prefill_tokens)


class WorkEstimatePolicy(RoutingPolicy):
    """'custom' policy: routes by estimated outstanding work per engine.

    Work = (queued prefills x avg decode length) + sum over decoding
    requests of max(age, avg decode length). Falls back to QPS while no
    decode-length estimate exists yet.
    """

    def __init__(self):
        if getattr(self, "_initialized", False):
            return
        self._initialized = True

    def route_request(self, endpoints, engine_stats, request_stats, headers,
                      request_id, num_prefill_tokens=0,
                      prompt_text=None) -> str:
        def work(url: str) -> float:
            stat = request_stats.get(url)
            if stat is None:
                return 0.0
            avg_dec = stat.avg_decoding_length
            if avg_dec < 0:
                return stat.qps
            queued = len(stat.ts_prefill_enqueue) * avg_dec
            decoding = sum(
                max(age, avg_dec) for age in stat.ts_decoding_enqueue
            )
            return queued + decoding

        url = min(endpoints, key=lambda ep: work(ep.url)).url
        return _mark_routed(url, request_id, num_prefill_tokens)


_POLICY_CLASSES = (
    RoundRobinPolicy, SessionPolicy, LeastLoadedPolicy,
    HeadRoomAdmissionPolicy, PrefixAwarePolicy, KVStateAwarePolicy,
    WorkEstimatePolicy,
)


def initialize_routing_logic(routing_logic: Union[str, RoutingLogic],
                             **kwargs) -> RoutingPolicy:
    logic = RoutingLogic(routing_logic)
    logger.info("Initializing routing logic: %s", logic.value)
    if logic == RoutingLogic.ROUND_ROBIN:
        return RoundRobinPolicy()
    if logic == RoutingLogic.SESSION_BASED:
        return SessionPolicy(kwargs.get("session_key"))
    if logic == RoutingLogic.LEAST_LOADED:
        return LeastLoadedPolicy()
    if logic == RoutingLogic.HRA:
        return HeadRoomAdmissionPolicy()
    if logic == RoutingLogic.PREFIX_AWARE:
        return PrefixAwarePolicy()
    if logic == RoutingLogic.KV_STATE_AWARE:
        return KVStateAwarePolicy()
    if logic == RoutingLogic.CUSTOM_LOGIC:
        return WorkEstimatePolicy()
    raise ValueError(f"Unknown routing logic: {routing_logic}")


def reconfigure_routing_logic(routing_logic: Union[str, RoutingLogic],
                              **kwargs) -> RoutingPolicy:
    from production_stack_tpu.utils import SingletonMeta
    for cls in _POLICY_CLASSES:
        SingletonMeta._instances.pop(cls, None)
    return initialize_routing_logic(routing_logic, **kwargs)


def get_routing_logic() -> RoutingPolicy:
    from production_stack_tpu.utils import SingletonMeta
    for cls in _POLICY_CLASSES:
        if cls in SingletonMeta._instances:
            return SingletonMeta._instances[cls]
    raise ValueError("Routing logic has not been initialized")
