"""Router-side QoS: per-tenant rate limiting, weighted fair queueing,
and the graceful degradation ladder (docs/qos.md).

The engine's shed gate (engine/server.py) protects one pod; this layer
protects the *fleet* from one tenant. A tenant is the ``x-api-key``
header value, falling back to the client's peer IP, falling back to
``"anonymous"`` — cheap, deterministic, and good enough to stop a
single greedy client from starving everyone else without an auth
subsystem.

Three cooperating mechanisms, applied in order on the proxy hot path
(services/request_service.py) before any backend is contacted:

1. **Token buckets** — one per tenant (``--qos-tenant-rate`` requests/s,
   ``--qos-tenant-burst`` burst). A request that fits its bucket passes
   untouched.
2. **Degradation ladder** — a tenant mildly over its bucket is served
   *degraded* rather than refused: ``max_tokens`` is clamped to
   ``--qos-degrade-max-tokens`` and the ``x-qos-spec-off`` header tells
   the engine to skip speculative drafting for the row (existing
   engine capability, zero new engine surface). Counted in
   ``vllm:tenant_throttled_total``.
3. **Shedding** — a tenant deeply over its bucket (deficit past
   ``--qos-shed-deficit`` request-units) gets an honest
   ``429 + Retry-After`` computed from the bucket's refill rate —
   never a silent drop, never a 5xx. ``interactive`` requests are
   degraded but NEVER rate-shed: a human at a prompt always gets an
   answer; the ladder takes its pound of flesh from max_tokens
   instead.

Optionally (``--qos-max-concurrency`` > 0) a stride-scheduled
``FairGate`` bounds concurrent proxied generations and dequeues
waiters weighted-fair across tenants (weights by priority class), so
one tenant's thousand queued requests cannot monopolize admission
order even when every request individually fits its bucket.

Disabled-by-default: ``get_router_qos()`` returns ``None`` until
``initialize_router_qos`` runs with a positive tenant rate, and the
hot path treats ``None`` as "no QoS" — the pre-QoS behavior.

Determinism: every time-dependent entry point takes an explicit
``now`` so tests drive a synthetic clock.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple

from production_stack_tpu.qos import (
    Priority,
    TokenBucket,
    priority_name,
    shed_counter_dict,
)
from production_stack_tpu.utils import SingletonMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Priority-class weights for the fair gate's stride scheduler: an
# interactive waiter advances its tenant's virtual pass 4x slower than
# a background one, so interactive tenants dequeue ~4x as often under
# contention.
PRIORITY_WEIGHTS = {
    Priority.INTERACTIVE: 4.0,
    Priority.BATCH: 2.0,
    Priority.BACKGROUND: 1.0,
}

# Bound on distinct tenants tracked before the least-recently-seen
# bucket is dropped (an adversary minting tenant ids must not grow
# router memory without bound; a dropped tenant just starts a fresh
# full bucket, which is the generous direction).
MAX_TRACKED_TENANTS = 10_000


@dataclass
class RouterQoSConfig:
    """Knobs, mirrored 1:1 by router CLI flags (see parser.py)."""

    # Sustained per-tenant admission rate (requests/s) and burst.
    tenant_rate: float = 10.0
    tenant_burst: float = 20.0
    # Ladder rung 1: clamp for over-bucket tenants' max_tokens.
    degrade_max_tokens: int = 128
    # Ladder rung 2: bucket deficit (request-units) past which
    # non-interactive requests are shed with 429.
    shed_deficit: float = 10.0
    # Fair gate: max concurrent proxied generations (0 = gate off).
    max_concurrency: int = 0


@dataclass
class QoSVerdict:
    """One admission decision for one request."""

    action: str  # "admit" | "degrade" | "shed"
    tenant: str
    priority: Priority
    # Set on "degrade": clamp the request's max_tokens to this.
    clamp_max_tokens: Optional[int] = None
    # Set on "degrade": forward x-qos-spec-off to the engine.
    spec_off: bool = False
    # Set on "shed": honest Retry-After seconds.
    retry_after_s: int = 0


@dataclass
class _TenantState:
    bucket: TokenBucket
    admitted_total: int = 0
    throttled_total: int = 0
    shed_total: int = 0
    pass_value: float = 0.0  # fair-gate virtual time


class RouterQoS:
    """Per-tenant rate limiting + degradation ladder + counters."""

    def __init__(self, config: Optional[RouterQoSConfig] = None):
        self.config = config or RouterQoSConfig()
        self._tenants: "OrderedDict[str, _TenantState]" = OrderedDict()
        # Router-wide counters exported at /metrics
        # (services/metrics_service.py).
        self.tenant_throttled_total = 0
        self.shed_by_class = shed_counter_dict()
        self.gate: Optional[FairGate] = (
            FairGate(self.config.max_concurrency, self)
            if self.config.max_concurrency > 0 else None
        )

    # -- tenant identity ----------------------------------------------------

    @staticmethod
    def tenant_of(headers, remote: Optional[str]) -> str:
        """x-api-key header, else peer IP, else "anonymous"."""
        from production_stack_tpu.qos import TENANT_HEADER
        key = headers.get(TENANT_HEADER)
        if key:
            return f"key:{key}"
        if remote:
            return f"ip:{remote}"
        return "anonymous"

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = _TenantState(bucket=TokenBucket(
                rate=self.config.tenant_rate,
                burst=self.config.tenant_burst,
            ))
            self._tenants[tenant] = st
            while len(self._tenants) > MAX_TRACKED_TENANTS:
                self._tenants.popitem(last=False)
        else:
            self._tenants.move_to_end(tenant)
        return st

    # -- the ladder ---------------------------------------------------------

    def decide(self, tenant: str, priority: Priority,
               now: Optional[float] = None) -> QoSVerdict:
        """One request costs one bucket unit. In-bucket -> admit;
        mildly over -> degrade (clamp + spec-off); deeply over and
        non-interactive -> shed. Interactive is never rate-shed."""
        if now is None:
            now = time.monotonic()
        st = self._state(tenant)
        if st.bucket.take(1.0, now):
            st.admitted_total += 1
            return QoSVerdict("admit", tenant, priority)
        deficit = st.bucket.deficit(now)
        if (priority == Priority.INTERACTIVE
                or deficit < self.config.shed_deficit):
            # Degraded requests still cost real engine work, so they
            # charge the bucket into debt: a tenant that keeps
            # hammering crosses the shed line; one that backs off pays
            # the (bounded) debt down at the refill rate.
            st.bucket.charge(
                1.0, now,
                max_debt=self.config.shed_deficit
                + self.config.tenant_burst)
            st.throttled_total += 1
            self.tenant_throttled_total += 1
            return QoSVerdict(
                "degrade", tenant, priority,
                clamp_max_tokens=self.config.degrade_max_tokens,
                spec_off=True,
            )
        st.shed_total += 1
        self.shed_by_class[priority_name(priority)] += 1
        return QoSVerdict(
            "shed", tenant, priority,
            retry_after_s=max(1, int(st.bucket.retry_after_s(now))),
        )

    def tenant_snapshot(self) -> Dict[str, _TenantState]:
        return dict(self._tenants)


class FairGate:
    """Stride-scheduled concurrency gate: at most ``max_concurrency``
    requests proxy at once; excess waiters queue per tenant and are
    dequeued by lowest tenant virtual pass, advancing the winner's
    pass by 1/weight(priority). FIFO within a tenant.

    Single-event-loop discipline (same as the rest of the router): all
    state is touched from the router loop, no locks. ``release`` must
    be called exactly once per successful ``acquire``.
    """

    def __init__(self, max_concurrency: int, qos: RouterQoS):
        self.max_concurrency = max(1, int(max_concurrency))
        self._qos = qos
        self.active = 0
        self._global_pass = 0.0
        # tenant -> FIFO of (priority, future)
        self._waiting: Dict[
            str, Deque[Tuple[Priority, "asyncio.Future"]]] = {}

    @property
    def queued(self) -> int:
        return sum(len(q) for q in self._waiting.values())

    def _charge(self, tenant: str, priority: Priority) -> None:
        st = self._qos._state(tenant)
        # Classic stride join rule: a tenant resumes at the current
        # global virtual time, never earlier — an idle tenant cannot
        # bank unbounded credit while others worked.
        pass_value = max(st.pass_value, self._global_pass)
        self._global_pass = pass_value
        st.pass_value = pass_value + 1.0 / PRIORITY_WEIGHTS[priority]

    async def acquire(self, tenant: str, priority: Priority) -> None:
        if self.active < self.max_concurrency and not self._waiting:
            self.active += 1
            self._charge(tenant, priority)
            return
        fut: "asyncio.Future" = asyncio.get_event_loop().create_future()
        self._waiting.setdefault(tenant, deque()).append((priority, fut))
        try:
            await fut
        except asyncio.CancelledError:
            # Abandoned waiter (client gone): unlink so release() never
            # wakes a dead future.
            q = self._waiting.get(tenant)
            if q is not None:
                try:
                    q.remove((priority, fut))
                except ValueError:
                    pass
                if not q:
                    self._waiting.pop(tenant, None)
            raise

    def release(self) -> None:
        self.active = max(0, self.active - 1)
        while self._waiting and self.active < self.max_concurrency:
            # Lowest virtual pass wins; ties break by tenant name for
            # determinism.
            tenant = min(
                self._waiting,
                key=lambda t: (self._qos._state(t).pass_value, t),
            )
            q = self._waiting[tenant]
            priority, fut = q.popleft()
            if not q:
                del self._waiting[tenant]
            if fut.cancelled():
                continue
            self.active += 1
            self._charge(tenant, priority)
            fut.set_result(None)


class _QoSHolder(metaclass=SingletonMeta):
    """SingletonMeta so the test harness resets it between tests."""

    def __init__(self):
        self.instance: Optional[RouterQoS] = None


def initialize_router_qos(
        config: Optional[RouterQoSConfig] = None) -> Optional[RouterQoS]:
    holder = _QoSHolder()
    cfg = config or RouterQoSConfig()
    holder.instance = RouterQoS(cfg) if cfg.tenant_rate > 0 else None
    return holder.instance


def get_router_qos() -> Optional[RouterQoS]:
    """None until initialized with a positive tenant rate: the proxy
    path applies no tenant fairness or shedding — pre-QoS behavior."""
    return _QoSHolder().instance


def shutdown_router_qos() -> None:
    _QoSHolder().instance = None
