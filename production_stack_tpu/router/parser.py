"""Router CLI flags (parity: src/vllm_router/parsers/parser.py:30-209)."""

import argparse

from production_stack_tpu.utils import (
    parse_comma_separated_urls,
    parse_comma_separated_values,
)
from production_stack_tpu.version import __version__


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="tpu-router",
        description="OpenAI-compatible router for TPU serving engines",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8001)

    parser.add_argument(
        "--service-discovery", choices=["static", "k8s"], default="static"
    )
    parser.add_argument(
        "--static-backends", default=None,
        help="Comma-separated engine URLs (static discovery)",
    )
    parser.add_argument(
        "--static-models", default=None,
        help="Comma-separated model names, aligned with --static-backends",
    )
    parser.add_argument(
        "--static-roles", default=None,
        help="Comma-separated engine roles (prefill|decode|both), aligned "
             "with --static-backends; enables two-hop disaggregated "
             "dispatch when both a prefill and a decode backend exist "
             "(docs/disaggregation.md)",
    )
    parser.add_argument("--k8s-namespace", default="default")
    parser.add_argument("--k8s-port", type=int, default=8000)
    parser.add_argument("--k8s-label-selector", default="")

    parser.add_argument(
        "--routing-logic",
        choices=["roundrobin", "session", "llq", "hra",
                 "prefixaware", "kvstateaware", "custom"],
        default="roundrobin",
    )
    parser.add_argument(
        "--session-key", default=None,
        help="Header key for session-sticky routing",
    )

    # Resilience: retry-with-failover, backend timeouts, active health
    # checking, circuit breaking (router/resilience.py; docs/resilience.md).
    parser.add_argument(
        "--max-retries", type=int, default=2,
        help="Extra endpoints to try after a pre-first-byte failure "
             "(0 disables failover)",
    )
    parser.add_argument(
        "--backend-connect-timeout", type=float, default=30.0,
        help="Seconds to establish a backend connection (0 = unbounded)",
    )
    parser.add_argument(
        "--backend-timeout", type=float, default=600.0,
        help="Seconds a backend may stall any single read (waiting for "
             "the response or between streamed chunks) before the "
             "request is aborted; streams that keep producing are "
             "never cut off (0 = unbounded)",
    )
    parser.add_argument(
        "--health-check-interval", type=float, default=10.0,
        help="Seconds between active /health probes of every endpoint "
             "(0 disables active health checking)",
    )
    parser.add_argument("--health-check-timeout", type=float, default=2.0)
    parser.add_argument(
        "--health-failure-threshold", type=int, default=3,
        help="Consecutive failed probes before an endpoint leaves rotation",
    )
    parser.add_argument(
        "--health-success-threshold", type=int, default=1,
        help="Consecutive successful probes before it returns",
    )
    parser.add_argument(
        "--breaker-failure-rate", type=float, default=0.5,
        help="Failure fraction over the outcome window that opens an "
             "endpoint's circuit breaker",
    )
    parser.add_argument(
        "--breaker-min-volume", type=int, default=3,
        help="Minimum outcomes in the window before the breaker may open",
    )
    parser.add_argument("--breaker-window", type=int, default=20)
    parser.add_argument(
        "--breaker-open-seconds", type=float, default=2.0,
        help="Base open duration before a half-open probe; doubles per "
             "consecutive open (jittered, capped by "
             "--breaker-max-open-seconds)",
    )
    parser.add_argument("--breaker-max-open-seconds", type=float,
                        default=60.0)

    # QoS (router/qos.py; docs/qos.md): per-tenant token-bucket rate
    # limiting, the degradation ladder (clamp max_tokens -> spec-off ->
    # shed 429), and weighted-fair admission across tenants.
    parser.add_argument(
        "--qos-tenant-rate", type=float, default=0.0,
        help="Sustained per-tenant request rate (req/s) before the "
             "degradation ladder engages; tenant = x-api-key header, "
             "else client IP (0 disables router QoS entirely)",
    )
    parser.add_argument(
        "--qos-tenant-burst", type=float, default=20.0,
        help="Token-bucket burst per tenant (requests)",
    )
    parser.add_argument(
        "--qos-degrade-max-tokens", type=int, default=128,
        help="max_tokens clamp applied to over-rate tenants' requests "
             "(ladder rung 1, with speculative decoding forced off)",
    )
    parser.add_argument(
        "--qos-shed-deficit", type=float, default=10.0,
        help="Bucket deficit (request-units) past which non-interactive "
             "requests are shed with 429 + Retry-After; interactive "
             "requests are degraded but never rate-shed",
    )
    parser.add_argument(
        "--qos-max-concurrency", type=int, default=0,
        help="Concurrent proxied generations admitted at once; excess "
             "waiters dequeue weighted-fair across tenants (stride "
             "scheduling, priority-class weights). 0 disables the gate",
    )

    parser.add_argument("--engine-stats-interval", type=float, default=30.0)
    parser.add_argument("--request-stats-window", type=float, default=60.0)
    parser.add_argument("--log-stats", action="store_true")
    parser.add_argument("--log-stats-interval", type=float, default=10.0)

    parser.add_argument(
        "--dynamic-config-json", default=None,
        help="Path to hot-reloaded dynamic config JSON",
    )
    parser.add_argument(
        "--feature-gates", default=None,
        help="Comma-separated Name=true|false feature gates",
    )

    parser.add_argument("--enable-batch-api", action="store_true")
    parser.add_argument(
        "--file-storage-class", default="local_file",
        choices=["local_file"],
    )
    parser.add_argument("--file-storage-path", default="/tmp/pstpu_files")
    parser.add_argument(
        "--batch-processor", default="local", choices=["local"]
    )

    parser.add_argument(
        "--request-rewriter", default="noop", choices=["noop"]
    )
    parser.add_argument(
        "--request-span-log", default=None,
        help="Emit one JSON span per request to this file "
             "('-' = router log); disabled when unset",
    )

    # Cluster SLO ledger + slow-request archive + drift sentinel
    # (production_stack_tpu/obs/; docs/observability.md).
    parser.add_argument(
        "--slo-spec", default=None,
        help="Path to an SLO spec JSON (per-class / per-model TTFT, "
             "ITL and e2e targets plus objective fraction); enables "
             "the SLO ledger, burn-rate gauges and slow-request "
             "exemplar capture",
    )
    parser.add_argument(
        "--perf-baseline", default=None,
        help="Path to a committed per-phase step-time baseline JSON "
             "(observability/perf_baseline.json); enables the drift "
             "sentinel and the vllm:perf_drift gauge",
    )
    parser.add_argument(
        "--slow-archive-size", type=int, default=64,
        help="Ring capacity of the slow-request exemplar archive "
             "served at GET /debug/slow",
    )
    parser.add_argument(
        "--log-level", default="info",
        choices=["debug", "info", "warning", "error", "critical"],
    )
    parser.add_argument(
        "--version", action="version",
        version=f"%(prog)s {__version__}",
    )
    args = parser.parse_args(argv)
    validate_args(args)
    return args


def validate_args(args: argparse.Namespace) -> None:
    if args.service_discovery == "static":
        urls = parse_comma_separated_urls(args.static_backends)
        if not urls:
            raise ValueError(
                "--static-backends is required with static discovery"
            )
        models = parse_comma_separated_values(args.static_models)
        if models and len(models) != len(urls):
            raise ValueError(
                "--static-models must align with --static-backends"
            )
        roles = parse_comma_separated_values(args.static_roles)
        if roles and len(roles) != len(urls):
            raise ValueError(
                "--static-roles must align with --static-backends"
            )
        for role in roles or []:
            if role not in ("prefill", "decode", "both"):
                raise ValueError(
                    "--static-roles values must be prefill, decode or both"
                )
    if args.routing_logic == "session" and not args.session_key:
        raise ValueError("--session-key is required with session routing")
    if args.max_retries < 0:
        raise ValueError("--max-retries must be >= 0")
    for name in ("backend_connect_timeout", "backend_timeout",
                 "health_check_interval", "health_check_timeout",
                 "breaker_open_seconds", "breaker_max_open_seconds"):
        if getattr(args, name) < 0:
            raise ValueError(f"--{name.replace('_', '-')} must be >= 0")
    if not 0.0 < args.breaker_failure_rate <= 1.0:
        raise ValueError("--breaker-failure-rate must be in (0, 1]")
    if args.qos_tenant_rate < 0:
        raise ValueError("--qos-tenant-rate must be >= 0")
    if args.qos_tenant_rate > 0:
        if args.qos_tenant_burst <= 0:
            raise ValueError("--qos-tenant-burst must be > 0")
        if args.qos_degrade_max_tokens < 1:
            raise ValueError("--qos-degrade-max-tokens must be >= 1")
        if args.qos_shed_deficit <= 0:
            raise ValueError("--qos-shed-deficit must be > 0")
    if args.qos_max_concurrency < 0:
        raise ValueError("--qos-max-concurrency must be >= 0")
    if args.slow_archive_size < 1:
        raise ValueError("--slow-archive-size must be >= 1")
