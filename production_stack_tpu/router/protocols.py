"""OpenAI-compatible wire models (parity: src/vllm_router/protocols.py:7-51)."""

import time
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, Field


class ModelCard(BaseModel):
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "production-stack-tpu"
    root: Optional[str] = None
    parent: Optional[str] = None


class ModelList(BaseModel):
    object: str = "list"
    data: List[ModelCard] = Field(default_factory=list)


class ErrorInfo(BaseModel):
    message: str
    type: str = "invalid_request_error"
    param: Optional[str] = None
    code: Optional[int] = None


class ErrorResponse(BaseModel):
    error: ErrorInfo

    @classmethod
    def make(cls, message: str, type: str = "invalid_request_error",
             code: Optional[int] = None) -> "ErrorResponse":
        return cls(error=ErrorInfo(message=message, type=type, code=code))


class ChatMessage(BaseModel):
    role: str
    content: Any = None
    name: Optional[str] = None

    model_config = {"extra": "allow"}


class ChatCompletionRequest(BaseModel):
    model: str
    messages: List[ChatMessage]
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    stream: bool = False
    stop: Optional[Any] = None
    n: int = 1
    user: Optional[str] = None

    model_config = {"extra": "allow"}


class CompletionRequest(BaseModel):
    model: str
    prompt: Any
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    max_tokens: Optional[int] = None
    stream: bool = False
    stop: Optional[Any] = None
    n: int = 1

    model_config = {"extra": "allow"}


def model_dump(obj: BaseModel) -> Dict[str, Any]:
    return obj.model_dump(exclude_none=True)
