"""Engine /metrics scraper.

Capability parity with reference src/vllm_router/stats/engine_stats.py:
a daemon thread polls every serving engine's Prometheus ``/metrics``
endpoint and keeps the latest physical-load numbers per engine URL.

Metric names are the vLLM exposition names, which our TPU engine also
emits (engine/metrics.py), so the router works against either backend.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import requests
from prometheus_client.parser import text_string_to_metric_families

from production_stack_tpu.utils import SingletonMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_SCRAPE_TIMEOUT_S = 5.0

# Exposition name -> EngineStats attribute. Counter samples keep
# their rendered ``_total`` names through the parser, so the map keys
# them as exposed.
_METRIC_MAP = {
    "vllm:num_requests_running": "num_running_requests",
    "vllm:num_requests_waiting": "num_queuing_requests",
    "vllm:gpu_prefix_cache_hit_rate": "kv_cache_hit_rate",
    "vllm:gpu_cache_usage_perc": "kv_usage_perc",
    "vllm:spec_decode_num_draft_tokens_total":
        "spec_decode_num_draft_tokens",
    "vllm:spec_decode_num_accepted_tokens_total":
        "spec_decode_num_accepted_tokens",
    "vllm:engine_step_host_seconds_total":
        "engine_step_host_seconds",
    "vllm:engine_step_device_wait_seconds_total":
        "engine_step_device_wait_seconds",
    "vllm:engine_device_idle_seconds_total":
        "engine_device_idle_seconds",
    "vllm:engine_pipeline_steps_total": "engine_pipeline_steps",
    "vllm:engine_pipeline_ahead_steps_total":
        "engine_pipeline_ahead_steps",
    "vllm:engine_async_inflight_depth": "engine_async_inflight_depth",
    # Unified ragged step occupancy (engine docs/unified_step.md):
    # per-step row split gauges plus cumulative row totals; pad ratio
    # = pad_rows / rows when rows > 0.
    "vllm:engine_step_prefill_rows": "engine_step_prefill_rows",
    "vllm:engine_step_decode_rows": "engine_step_decode_rows",
    "vllm:engine_step_pad_rows": "engine_step_pad_rows",
    "vllm:engine_ragged_steps_total": "engine_ragged_steps",
    "vllm:engine_ragged_rows_total": "engine_ragged_rows",
    "vllm:engine_ragged_pad_rows_total": "engine_ragged_pad_rows",
    # KV quantization telemetry (engine docs/kv_quantization.md):
    # post-expansion page budget and worst-case bytes written per
    # decode step. The storage dtype itself travels as a label on
    # vllm:engine_kv_cache_dtype (handled in from_prometheus_text).
    "vllm:engine_kv_cache_page_capacity":
        "engine_kv_cache_page_capacity",
    "vllm:engine_kv_bytes_per_decode_step":
        "engine_kv_bytes_per_decode_step",
    # Disaggregated serving (docs/disaggregation.md): per-role request
    # counters, KV bytes shipped over the handoff wire, and the
    # AWAITING_KV queue depth on decode-role engines.
    "vllm:disagg_prefill_requests_total": "disagg_prefill_requests",
    "vllm:disagg_decode_requests_total": "disagg_decode_requests",
    "vllm:disagg_kv_bytes_shipped_total": "disagg_kv_bytes_shipped",
    "vllm:disagg_awaiting_kv_requests": "disagg_awaiting_kv_requests",
    # Zero-loss drain (docs/fleet.md): 1 while the engine rejects new
    # admissions and finishes its in-flight sequences.
    "vllm:engine_draining": "engine_draining",
    # Topology observability (docs/parallelism.md): which slice this
    # engine process's devices belong to; the labeled mesh-shape and
    # per-slice-liveness families are handled in from_prometheus_text.
    "vllm:engine_slice_id": "engine_slice_id",
    # Device performance observatory (docs/observability.md): the
    # unlabeled MFU gauge; the labeled compile/HBM/step-time families
    # are handled in from_prometheus_text.
    "vllm:engine_mfu": "engine_mfu",
    # KV economy (docs/kv_economy.md): summary gauges mirrored off
    # GET /kv/summary (the scraper also fetches the summary body for
    # the hot-chain hashes themselves) plus the engine-side cluster
    # cache counters.
    "vllm:kv_summary_hot_chains": "kv_summary_hot_chains",
    "vllm:kv_free_page_headroom": "kv_free_page_headroom",
    "vllm:kv_total_pages": "kv_total_pages",
    "vllm:kv_cluster_hits_total": "kv_cluster_hits",
    "vllm:kv_cluster_misses_total": "kv_cluster_misses",
    "vllm:kv_cluster_admissions_total": "kv_cluster_admissions",
    "vllm:kv_cluster_rejections_total": "kv_cluster_rejections",
    # Self-tuning (docs/autotuning.md): controllers currently allowed
    # to act on this engine; the labeled frozen/knob families are
    # handled in from_prometheus_text.
    "vllm:autotune_active_controllers": "autotune_active_controllers",
}

# Engine latency histograms the scraper summarizes: it keeps each
# one's running sum/count (exposition name -> EngineStats field
# prefix, fields ``<prefix>_sum``/``<prefix>_count``) so the router
# can re-export a mean; buckets stay with cluster Prometheus. Covers
# the handoff-admission latency and the per-phase request histograms
# (queue / prefill-compute / awaiting-KV / decode,
# docs/observability.md).
_SUMMARY_HISTS = {
    "vllm:disagg_handoff_latency_seconds": "disagg_handoff_latency",
    "vllm:request_queue_time_seconds": "request_queue_time",
    "vllm:request_prefill_time_seconds": "request_prefill_time",
    "vllm:request_awaiting_kv_time_seconds": "request_awaiting_kv_time",
    "vllm:request_decode_time_seconds": "request_decode_time",
    # Preempt-to-offload restore latency (docs/qos.md): allocate +
    # fetch_many + write_page time when a preempted victim's KV comes
    # back from the offload tier instead of being recomputed.
    "vllm:preempt_restore_latency_seconds": "preempt_restore_latency",
}

# Engine metrics the router deliberately does NOT scrape: request
# latency histograms and lifecycle counters are read by cluster
# Prometheus straight off each engine's /metrics (the router's
# per-request stats monitor computes its own latency view from live
# traffic). Listed here so the staticcheck metrics-contract analyzer
# can tell a decided drop from silent drift — a NEW engine metric
# must be added to _METRIC_MAP, _SUMMARY_HISTS, or this set.
_ROUTER_UNSCRAPED = frozenset({
    "vllm:time_to_first_token_seconds",
    "vllm:time_per_output_token_seconds",
    "vllm:e2e_request_latency_seconds",
    "vllm:prompt_tokens_total",
    "vllm:generation_tokens_total",
    "vllm:request_success_total",
    "vllm:request_failure_total",
    "vllm:num_preemptions_total",
    # Autotune decision counts are an operator/dashboard rate, not a
    # routing signal — cluster Prometheus reads them directly.
    "vllm:autotune_decisions_total",
})


@dataclass
class EngineStats:
    num_running_requests: int = 0
    num_queuing_requests: int = 0
    kv_cache_hit_rate: float = 0.0
    kv_usage_perc: float = 0.0
    # Speculative decoding counters (engine docs/speculative.md);
    # acceptance rate = accepted / drafted when drafted > 0.
    spec_decode_num_draft_tokens: float = 0.0
    spec_decode_num_accepted_tokens: float = 0.0
    # Async execution pipeline counters (engine
    # docs/async_pipeline.md): host vs device-wait step time, device
    # idle gap, and ahead-dispatched step counts. Overlap fraction =
    # 1 - idle / host when host > 0.
    engine_step_host_seconds: float = 0.0
    engine_step_device_wait_seconds: float = 0.0
    engine_device_idle_seconds: float = 0.0
    engine_pipeline_steps: float = 0.0
    engine_pipeline_ahead_steps: float = 0.0
    engine_async_inflight_depth: float = 0.0
    # Unified ragged step occupancy (engine docs/unified_step.md):
    # last mixed dispatch's prefill/decode/pad row split and the
    # cumulative row totals behind the pad ratio.
    engine_step_prefill_rows: float = 0.0
    engine_step_decode_rows: float = 0.0
    engine_step_pad_rows: float = 0.0
    engine_ragged_steps: float = 0.0
    engine_ragged_rows: float = 0.0
    engine_ragged_pad_rows: float = 0.0
    # KV page storage (engine docs/kv_quantization.md): page budget
    # after any int8 expansion, worst-case KV write bytes per decode
    # step, and the storage dtype ("bf16"/"int8"; "" until scraped).
    engine_kv_cache_page_capacity: float = 0.0
    engine_kv_bytes_per_decode_step: float = 0.0
    engine_kv_cache_dtype: str = ""
    # Disaggregated serving (docs/disaggregation.md): role counters,
    # shipped KV volume, AWAITING_KV depth, and the handoff-latency
    # histogram's running sum/count (mean = sum / count when > 0).
    disagg_prefill_requests: float = 0.0
    disagg_decode_requests: float = 0.0
    disagg_kv_bytes_shipped: float = 0.0
    disagg_awaiting_kv_requests: float = 0.0
    disagg_handoff_latency_sum: float = 0.0
    disagg_handoff_latency_count: float = 0.0
    # Per-phase request latency histograms (docs/observability.md):
    # running sum/count per phase; mean = sum / count when count > 0.
    request_queue_time_sum: float = 0.0
    request_queue_time_count: float = 0.0
    request_prefill_time_sum: float = 0.0
    request_prefill_time_count: float = 0.0
    request_awaiting_kv_time_sum: float = 0.0
    request_awaiting_kv_time_count: float = 0.0
    request_decode_time_sum: float = 0.0
    request_decode_time_count: float = 0.0
    # Zero-loss drain (docs/fleet.md): 1 while the engine is draining.
    engine_draining: float = 0.0
    # QoS under overload (docs/qos.md): labeled counters — requests
    # shed at the engine's 429 gate per priority class
    # (vllm:qos_shed_total{class=...}), preemptions per outcome
    # (vllm:preempt_offload_total{outcome="offloaded"|"recompute"}) —
    # and the preempt-restore latency histogram's running sum/count.
    qos_shed_by_class: Dict[str, float] = field(default_factory=dict)
    preempt_offload_by_outcome: Dict[str, float] = field(
        default_factory=dict)
    preempt_restore_latency_sum: float = 0.0
    preempt_restore_latency_count: float = 0.0
    # Device performance observatory (docs/observability.md): per-kind
    # compile events/seconds (vllm:engine_compile_events_total{kind},
    # vllm:engine_compile_seconds_total{kind}), live executable-cache
    # sizes (vllm:engine_executable_cache_size{kind}), the analytic
    # HBM breakdown (vllm:engine_hbm_bytes{category}), per-kind device
    # step time (vllm:engine_step_device_seconds_total{kind}), the
    # scalar MFU gauge, and the resolved attention impl per phase
    # (vllm:engine_attention_impl{phase,impl} one-hot).
    compile_events_by_kind: Dict[str, float] = field(
        default_factory=dict)
    compile_seconds_by_kind: Dict[str, float] = field(
        default_factory=dict)
    executable_cache_size_by_kind: Dict[str, float] = field(
        default_factory=dict)
    hbm_bytes_by_category: Dict[str, float] = field(
        default_factory=dict)
    step_device_seconds_by_kind: Dict[str, float] = field(
        default_factory=dict)
    # Median recent step duration per kind
    # (vllm:engine_step_time_median_seconds{kind}) — the drift
    # sentinel's input (obs/drift.py, docs/observability.md).
    step_time_median_by_kind: Dict[str, float] = field(
        default_factory=dict)
    engine_mfu: float = 0.0
    attention_impl_by_phase: Dict[str, str] = field(
        default_factory=dict)
    # Topology observability (docs/parallelism.md): the engine's mesh
    # axis sizes (vllm:engine_mesh_shape{axis="dp|pp|sp|tp"}), the
    # slice its devices sit on (vllm:engine_slice_id), and per-slice
    # liveness from the multihost bridge
    # (vllm:engine_slice_live{slice}) — a dead host shows up here as
    # ONE slice going 0.0 while the rest of the mesh stays 1.0.
    mesh_shape_by_axis: Dict[str, float] = field(default_factory=dict)
    engine_slice_id: float = 0.0
    slice_live_by_id: Dict[str, float] = field(default_factory=dict)
    # KV economy (docs/kv_economy.md): the engine's rolling KV-state
    # summary. Gauges mirror GET /kv/summary; kv_hot_chains carries
    # the advertised chain hashes themselves (hash -> decayed hits),
    # fetched alongside /metrics by the scraper, and kv_summary_time
    # stamps when that fetch happened so KVStateAwarePolicy can bound
    # staleness. The kv_cluster_* counters are the engine's view of
    # the shared cache tier (hits/misses on fetch, admission verdicts
    # on write-through).
    kv_summary_hot_chains: float = 0.0
    kv_free_page_headroom: float = 0.0
    kv_total_pages: float = 0.0
    kv_cluster_hits: float = 0.0
    kv_cluster_misses: float = 0.0
    kv_cluster_admissions: float = 0.0
    kv_cluster_rejections: float = 0.0
    kv_hot_chains: Dict[int, float] = field(default_factory=dict)
    kv_summary_time: float = 0.0
    # Self-tuning (docs/autotuning.md): count of controllers allowed
    # to act (0 in off/shadow), latched guardrail freezes per
    # controller (vllm:autotune_frozen{controller}), and live knob
    # values (vllm:autotune_knob_value{controller}) — stacktop's
    # AUTOTUNE column and the fleet dashboard read these.
    autotune_active_controllers: float = 0.0
    autotune_frozen_by_controller: Dict[str, float] = field(
        default_factory=dict)
    autotune_knob_by_controller: Dict[str, float] = field(
        default_factory=dict)

    @classmethod
    def from_prometheus_text(cls, text: str) -> "EngineStats":
        stats = cls()
        for family in text_string_to_metric_families(text):
            for sample in family.samples:
                base, _, suffix = sample.name.rpartition("_")
                if (suffix in ("sum", "count")
                        and base in _SUMMARY_HISTS):
                    setattr(stats,
                            f"{_SUMMARY_HISTS[base]}_{suffix}",
                            sample.value)
                    continue
                if sample.name == "vllm:qos_shed_total":
                    stats.qos_shed_by_class[
                        sample.labels.get("class", "")] = sample.value
                    continue
                if sample.name == "vllm:preempt_offload_total":
                    stats.preempt_offload_by_outcome[
                        sample.labels.get("outcome", "")] = sample.value
                    continue
                if sample.name == "vllm:engine_compile_events_total":
                    stats.compile_events_by_kind[
                        sample.labels.get("kind", "")] = sample.value
                    continue
                if sample.name == "vllm:engine_compile_seconds_total":
                    stats.compile_seconds_by_kind[
                        sample.labels.get("kind", "")] = sample.value
                    continue
                if sample.name == "vllm:engine_executable_cache_size":
                    stats.executable_cache_size_by_kind[
                        sample.labels.get("kind", "")] = sample.value
                    continue
                if sample.name == "vllm:engine_hbm_bytes":
                    stats.hbm_bytes_by_category[
                        sample.labels.get("category", "")
                    ] = sample.value
                    continue
                if (sample.name
                        == "vllm:engine_step_device_seconds_total"):
                    stats.step_device_seconds_by_kind[
                        sample.labels.get("kind", "")] = sample.value
                    continue
                if (sample.name
                        == "vllm:engine_step_time_median_seconds"):
                    stats.step_time_median_by_kind[
                        sample.labels.get("kind", "")] = sample.value
                    continue
                if sample.name == "vllm:engine_mesh_shape":
                    stats.mesh_shape_by_axis[
                        sample.labels.get("axis", "")] = sample.value
                    continue
                if sample.name == "vllm:engine_slice_live":
                    stats.slice_live_by_id[
                        sample.labels.get("slice", "")] = sample.value
                    continue
                if sample.name == "vllm:autotune_frozen":
                    stats.autotune_frozen_by_controller[
                        sample.labels.get("controller", "")
                    ] = sample.value
                    continue
                if sample.name == "vllm:autotune_knob_value":
                    stats.autotune_knob_by_controller[
                        sample.labels.get("controller", "")
                    ] = sample.value
                    continue
                if (sample.name == "vllm:engine_attention_impl"
                        and sample.value == 1.0):
                    # One-hot labeled info gauge: phase -> impl.
                    stats.attention_impl_by_phase[
                        sample.labels.get("phase", "")
                    ] = sample.labels.get("impl", "")
                    continue
                if (sample.name == "vllm:engine_kv_cache_dtype"
                        and sample.value == 1.0):
                    # One-hot labeled gauge: the label carries the
                    # dtype string.
                    stats.engine_kv_cache_dtype = sample.labels.get(
                        "kv_dtype", "")
                    continue
                attr = _METRIC_MAP.get(sample.name)
                if attr is not None:
                    current = getattr(stats, attr)
                    setattr(stats, attr, type(current)(sample.value))
        return stats


class EngineStatsScraper(metaclass=SingletonMeta):
    """Daemon thread scraping every discovered engine at a fixed interval."""

    def __init__(self, scrape_interval: Optional[float] = None):
        if getattr(self, "_initialized", False):
            return
        if scrape_interval is None:
            raise ValueError("EngineStatsScraper needs scrape_interval")
        self.scrape_interval = float(scrape_interval)
        self._stats: Dict[str, EngineStats] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="engine-stats-scraper"
        )
        self._thread.start()
        self._initialized = True

    def _engine_urls(self):
        # Imported lazily to avoid a circular import at module load.
        from production_stack_tpu.router.service_discovery import (
            get_service_discovery,
        )
        try:
            discovery = get_service_discovery()
        except ValueError:
            return []
        return [ep.url for ep in discovery.get_endpoint_info()]

    def _scrape_one(self, url: str) -> Optional[EngineStats]:
        try:
            resp = requests.get(f"{url}/metrics", timeout=_SCRAPE_TIMEOUT_S)
            resp.raise_for_status()
            stats = EngineStats.from_prometheus_text(resp.text)
        except Exception as e:
            logger.warning("Failed to scrape %s/metrics: %s", url, e)
            return None
        self._scrape_kv_summary(url, stats)
        return stats

    def _scrape_kv_summary(self, url: str, stats: EngineStats) -> None:
        """Fetch GET /kv/summary for the hot-chain hashes themselves.

        Best-effort: engines that predate the KV economy 404 here and
        ``kv_summary_time`` stays 0, which KVStateAwarePolicy reads as
        "no summary" and degrades to prefix-affinity."""
        try:
            resp = requests.get(f"{url}/kv/summary",
                                timeout=_SCRAPE_TIMEOUT_S)
            if resp.status_code != 200:
                return
            body = resp.json()
        except Exception as e:
            logger.debug("No /kv/summary from %s: %s", url, e)
            return
        try:
            stats.kv_hot_chains = {
                int(h): float(v) for h, v in body.get("hot_chains", [])
            }
            stats.kv_summary_hot_chains = float(len(stats.kv_hot_chains))
            stats.kv_free_page_headroom = float(
                body.get("free_pages", stats.kv_free_page_headroom))
            stats.kv_total_pages = float(
                body.get("total_pages", stats.kv_total_pages))
            if body.get("kv_dtype"):
                stats.engine_kv_cache_dtype = str(body["kv_dtype"])
            stats.kv_summary_time = time.time()
        except (TypeError, ValueError) as e:
            logger.warning("Malformed /kv/summary from %s: %s", url, e)

    def scrape_once(self) -> None:
        """One synchronous scrape pass over the discovered engines.

        The daemon thread calls this on its interval; tests and the
        fleet bench rig call it directly for a deterministic refresh.
        """
        urls = self._engine_urls()
        fresh: Dict[str, EngineStats] = {}
        for url in urls:
            stats = self._scrape_one(url)
            if stats is not None:
                fresh[url] = stats
        with self._lock:
            # Drop engines that disappeared from discovery.
            self._stats = {
                u: fresh.get(u, self._stats.get(u, EngineStats()))
                for u in urls
            }

    def _run(self) -> None:
        while not self._stop.wait(self.scrape_interval):
            self.scrape_once()

    def get_engine_stats(self) -> Dict[str, EngineStats]:
        with self._lock:
            return dict(self._stats)

    def get_health(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()


def initialize_engine_stats_scraper(scrape_interval: float) -> EngineStatsScraper:
    return EngineStatsScraper(scrape_interval)


def get_engine_stats_scraper() -> EngineStatsScraper:
    return EngineStatsScraper()
