"""Periodic human-readable stats dump (parity: stats/log_stats.py)."""

import threading
import time

from production_stack_tpu.utils.log import init_logger

logger = init_logger("production_stack_tpu.stats")


def format_stats_report() -> str:
    from production_stack_tpu.router.service_discovery import (
        get_service_discovery,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        get_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        get_request_stats_monitor,
    )

    lines = ["", "==== Router Stats ===="]
    try:
        endpoints = get_service_discovery().get_endpoint_info()
    except ValueError:
        endpoints = []
    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats(time.time())
    for ep in endpoints:
        lines.append(f"{ep.url} (models={ep.model_names})")
        es = engine_stats.get(ep.url)
        if es:
            lines.append(
                f"  engine: running={es.num_running_requests} "
                f"waiting={es.num_queuing_requests} "
                f"kv_usage={es.kv_usage_perc:.1%} "
                f"prefix_hit={es.kv_cache_hit_rate:.1%}"
            )
        rs = request_stats.get(ep.url)
        if rs:
            lines.append(
                f"  requests: qps={rs.qps:.2f} ttft={rs.ttft:.3f}s "
                f"prefill={rs.in_prefill_requests} "
                f"decode={rs.in_decoding_requests} "
                f"finished={rs.finished_requests} "
                f"blocks(alloc/reserved/free)={rs.allocated_blocks}/"
                f"{rs.pending_reserved_blocks}/{rs.num_free_blocks}"
            )
    lines.append("======================")
    return "\n".join(lines)


def log_stats(interval_s: float = 10.0) -> threading.Thread:
    def _loop():
        while True:
            time.sleep(interval_s)
            try:
                logger.info(format_stats_report())
            except Exception as e:  # keep the reporter alive
                logger.warning("Stats report failed: %s", e)

    thread = threading.Thread(target=_loop, daemon=True, name="stats-logger")
    thread.start()
    return thread
