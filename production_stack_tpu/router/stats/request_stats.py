"""Per-engine request-level statistics.

Re-implements the capability of reference
src/vllm_router/stats/request_stats.py (lifecycle events L144-315, sliding
window monitors L61-100, fork's KV-block accounting L399-457) with:

- a single coarse lock (the reference relies on the GIL; we are explicit),
- TPU-calibrated block-budget defaults, overridable via environment
  (``PSTPU_KV_BLOCK_SIZE``, ``PSTPU_KV_TOTAL_BLOCKS``, ...). The defaults
  model a v5e chip (16 GiB HBM) serving Llama-3-8B bf16 KV.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Set, Tuple

from production_stack_tpu.utils import SingletonMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# KV block-budget model used by HRA admission control.
# Reference constants (request_stats.py:9-12) model an A10 GPU; ours model a
# TPU v5e chip: 16 GiB HBM - ~16 GiB model/weights budget split leaves
# ~4 GiB KV for Llama-3-8B bf16 (8 kv-heads * 128 dim * 2 bytes * 2 (k+v)
# * 32 layers = 128 KiB/token -> ~2048 tokens/GiB). With page size 16:
BLOCK_SIZE = int(os.environ.get("PSTPU_KV_BLOCK_SIZE", 16))
TOTAL_NUMBER_OF_BLOCKS = int(os.environ.get("PSTPU_KV_TOTAL_BLOCKS", 2048))
DECODE_TO_PREFILL_RATIO = float(os.environ.get("PSTPU_DECODE_PREFILL_RATIO", 0.25))
SAFETY_FRACTION = float(os.environ.get("PSTPU_KV_SAFETY_FRACTION", 0.05))


@dataclass
class RequestStats:
    """Snapshot of request-level performance of one engine."""

    qps: float = -1.0
    ttft: float = -1.0
    # Tail latencies over the same sliding window — the fleet
    # autoscaler's SLO signals (docs/fleet.md). -1 until observed.
    ttft_p99: float = -1.0
    itl_p99: float = -1.0
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    # Ages (seconds) of requests currently in prefill / decode.
    ts_prefill_enqueue: List[float] = field(default_factory=list)
    ts_decoding_enqueue: List[float] = field(default_factory=list)
    finished_requests: int = 0
    uptime: float = 0.0
    avg_decoding_length: float = -1.0
    avg_latency: float = -1.0
    avg_itl: float = -1.0
    num_swapped_requests: int = 0
    # Router-side queueing delay (arrival -> routed admission), the
    # reference dashboard's "Router-side Queueing Delay" metric.
    queueing_delay: float = -1.0
    # Average prompt length of recently routed requests (tokens).
    avg_prefill_length: float = -1.0
    # KV block accounting (fork feature).
    allocated_blocks: int = 0
    pending_reserved_blocks: int = 0
    num_free_blocks: int = TOTAL_NUMBER_OF_BLOCKS


class SlidingWindow:
    """Time-windowed series supporting average and sum."""

    def __init__(self, window_s: float):
        self.window_s = window_s
        self._ts: Deque[float] = deque()
        self._vals: Deque[float] = deque()

    def observe(self, timestamp: float, value: float) -> None:
        self._ts.append(timestamp)
        self._vals.append(value)
        self._evict(timestamp)

    def advance(self, timestamp: float) -> None:
        self._evict(timestamp)

    def _evict(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._ts and self._ts[0] < cutoff:
            self._ts.popleft()
            self._vals.popleft()

    def average(self) -> float:
        return sum(self._vals) / len(self._vals) if self._vals else -1.0

    def total(self) -> float:
        return sum(self._vals)

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile of the windowed values (-1 if empty)."""
        if not self._vals:
            return -1.0
        ordered = sorted(self._vals)
        rank = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
        return ordered[rank]


class RequestStatsMonitor(metaclass=SingletonMeta):
    """Tracks the lifecycle of every proxied request, per engine.

    Event flow (mirrors reference request_stats.py event API):
    arrival -> routed (prefill set + reserved blocks) -> start (qps)
    -> response(first_token) (prefill->decode, ttft) -> response(...) per
    token chunk (decode token count -> allocated blocks) -> complete
    (latency, decode length) | kill (cleanup on disconnect/error).
    """

    def __init__(self, sliding_window_size: Optional[float] = None):
        if getattr(self, "_initialized", False):
            return
        if sliding_window_size is None:
            raise ValueError("RequestStatsMonitor needs sliding_window_size")
        self.window_s = float(sliding_window_size)
        self._lock = threading.Lock()

        self._qps: Dict[str, SlidingWindow] = {}
        self._ttft: Dict[str, SlidingWindow] = {}
        self._latency: Dict[str, SlidingWindow] = {}
        self._decode_len: Dict[str, SlidingWindow] = {}
        self._queue_delay: Dict[str, SlidingWindow] = {}
        self._prefill_len: Dict[str, SlidingWindow] = {}
        self._itl: Dict[str, SlidingWindow] = {}

        self._arrival_time: Dict[str, float] = {}
        # QoS attribution (docs/observability.md): priority class and
        # tenant per in-flight request, plus running per-class arrival
        # counts — the labels the SLO ledger and spans carry.
        self._req_class: Dict[str, str] = {}
        self._req_tenant: Dict[str, str] = {}
        self.arrivals_by_class: Dict[str, int] = {}
        self._first_token_time: Dict[Tuple[str, str], float] = {}
        self._in_prefill: Dict[str, Set[str]] = {}
        self._in_decode: Dict[str, Set[str]] = {}
        self._finished: Dict[str, int] = {}
        self._swapped: Dict[str, int] = {}
        # engine_url -> request_id -> token counts
        self._decode_tokens: Dict[str, Dict[str, int]] = {}
        self._prefill_tokens: Dict[str, Dict[str, int]] = {}

        self._first_query_time: Optional[float] = None
        self._initialized = True

    # ---- lifecycle events -------------------------------------------------

    def on_request_arrival(self, request_id: str, timestamp: float,
                           priority_class: Optional[str] = None,
                           tenant: Optional[str] = None) -> None:
        with self._lock:
            self._arrival_time[request_id] = timestamp
            if priority_class is not None:
                self._req_class[request_id] = priority_class
                self.arrivals_by_class[priority_class] = (
                    self.arrivals_by_class.get(priority_class, 0) + 1)
            if tenant is not None:
                self._req_tenant[request_id] = tenant
            if self._first_query_time is None:
                self._first_query_time = timestamp

    def request_attribution(self, request_id: str
                            ) -> Tuple[Optional[str], Optional[str]]:
        """(priority class, tenant) recorded at arrival, while the
        request is still in flight."""
        with self._lock:
            return (self._req_class.get(request_id),
                    self._req_tenant.get(request_id))

    def on_request_routed(self, engine_url: str, request_id: str,
                          prefill_tokens: int,
                          timestamp: Optional[float] = None) -> None:
        """Admission decision made: account reserved prefill tokens,
        record the router-side queueing delay (arrival -> admission —
        nonzero mainly under HRA's future-based admission queue) and
        the prompt length."""
        now = time.time() if timestamp is None else timestamp
        with self._lock:
            self._prefill_tokens.setdefault(engine_url, {})[request_id] = (
                prefill_tokens
            )
            self._in_prefill.setdefault(engine_url, set()).add(request_id)
            arrived = self._arrival_time.get(request_id)
            if arrived is not None:
                self._queue_delay.setdefault(
                    engine_url, SlidingWindow(self.window_s)
                ).observe(now, max(0.0, now - arrived))
            if prefill_tokens > 0:
                self._prefill_len.setdefault(
                    engine_url, SlidingWindow(self.window_s)
                ).observe(now, float(prefill_tokens))

    def on_request_start(self, engine_url: str, request_id: str,
                         timestamp: float) -> None:
        with self._lock:
            self._qps.setdefault(
                engine_url, SlidingWindow(self.window_s)
            ).observe(timestamp, 1.0)

    def on_request_response(self, engine_url: str, request_id: str,
                            timestamp: float, is_first_token: bool) -> None:
        with self._lock:
            toks = self._decode_tokens.setdefault(engine_url, {})
            toks[request_id] = toks.get(request_id, 0) + 1
            if not is_first_token:
                return
            if request_id not in self._arrival_time:
                self._cleanup_locked(engine_url, request_id)
                return
            self._in_prefill.setdefault(engine_url, set()).discard(request_id)
            self._in_decode.setdefault(engine_url, set()).add(request_id)
            self._first_token_time[(engine_url, request_id)] = timestamp
            ttft = timestamp - self._arrival_time[request_id]
            self._ttft.setdefault(
                engine_url, SlidingWindow(self.window_s)
            ).observe(timestamp, ttft)

    def on_request_complete(self, engine_url: str, request_id: str,
                            timestamp: float) -> None:
        with self._lock:
            if (request_id not in self._arrival_time
                    or (engine_url, request_id) not in self._first_token_time):
                self._cleanup_locked(engine_url, request_id)
                return
            self._in_decode.setdefault(engine_url, set()).discard(request_id)
            self._finished[engine_url] = self._finished.get(engine_url, 0) + 1
            lat = timestamp - self._arrival_time[request_id]
            self._latency.setdefault(
                engine_url, SlidingWindow(self.window_s)
            ).observe(timestamp, lat)
            dec = timestamp - self._first_token_time[(engine_url, request_id)]
            self._decode_len.setdefault(
                engine_url, SlidingWindow(self.window_s)
            ).observe(timestamp, dec)
            n_tokens = self._decode_tokens.get(engine_url, {}).get(
                request_id, 0)
            if n_tokens > 1:
                self._itl.setdefault(
                    engine_url, SlidingWindow(self.window_s)
                ).observe(timestamp, dec / (n_tokens - 1))
            self._cleanup_locked(engine_url, request_id)

    def on_request_kill(self, engine_url: str, request_id: str) -> None:
        """Request died mid-flight (client disconnect, engine error)."""
        with self._lock:
            self._cleanup_locked(engine_url, request_id)

    def on_request_swapped(self, engine_url: str, request_id: str,
                           timestamp: float) -> None:
        with self._lock:
            self._swapped[engine_url] = self._swapped.get(engine_url, 0) + 1

    def _cleanup_locked(self, engine_url: str, request_id: str) -> None:
        self._arrival_time.pop(request_id, None)
        self._req_class.pop(request_id, None)
        self._req_tenant.pop(request_id, None)
        self._first_token_time.pop((engine_url, request_id), None)
        if engine_url in self._in_prefill:
            self._in_prefill[engine_url].discard(request_id)
        if engine_url in self._in_decode:
            self._in_decode[engine_url].discard(request_id)
        for table in (self._decode_tokens, self._prefill_tokens):
            if engine_url in table:
                table[engine_url].pop(request_id, None)
                if not table[engine_url]:
                    del table[engine_url]

    # ---- KV block model (fork parity, request_stats.py:399-457) -----------

    def estimate_allocated_blocks(self, engine_url: str) -> int:
        """Blocks held by requests actively decoding on *engine_url*."""
        with self._lock:
            return self._allocated_locked(engine_url)

    def _allocated_locked(self, engine_url: str) -> int:
        decode_ids = self._in_decode.get(engine_url, set())
        toks = self._decode_tokens.get(engine_url, {})
        prefills = self._prefill_tokens.get(engine_url, {})
        total = 0
        for rid in decode_ids:
            n = prefills.get(rid, 0) + toks.get(rid, 0)
            total += math.ceil(n / BLOCK_SIZE)
        return total

    def estimate_pending_reserved_blocks(self, engine_url: str) -> int:
        """Blocks to reserve for requests still in prefill (pessimistic)."""
        with self._lock:
            return self._reserved_locked(engine_url)

    def _reserved_locked(self, engine_url: str) -> int:
        prefill_ids = self._in_prefill.get(engine_url, set())
        prefills = self._prefill_tokens.get(engine_url, {})
        total_prefill = sum(prefills.get(rid, 0) for rid in prefill_ids)
        expected = total_prefill * (1 + DECODE_TO_PREFILL_RATIO)
        return math.ceil(expected / BLOCK_SIZE)

    # ---- snapshot ---------------------------------------------------------

    @staticmethod
    def _window_avg(table: Dict[str, SlidingWindow], url: str,
                    now: float) -> float:
        win = table.get(url)
        if win is None:
            return -1.0
        win.advance(now)
        return win.average()

    @staticmethod
    def _window_p99(table: Dict[str, SlidingWindow], url: str,
                    now: float) -> float:
        win = table.get(url)
        if win is None:
            return -1.0
        win.advance(now)
        return win.percentile(0.99)

    def get_request_stats(self, current_time: float) -> Dict[str, RequestStats]:
        with self._lock:
            out: Dict[str, RequestStats] = {}
            urls = set(self._in_prefill) | set(self._in_decode)
            for url in urls:
                qps = -1.0
                if url in self._qps:
                    self._qps[url].advance(current_time)
                    qps = self._qps[url].total() / self.window_s
                ttft = self._window_avg(self._ttft, url, current_time)
                avg_dec = self._window_avg(self._decode_len, url,
                                           current_time)
                avg_lat = self._window_avg(self._latency, url,
                                           current_time)
                qdelay = self._window_avg(self._queue_delay, url,
                                          current_time)
                avg_plen = self._window_avg(self._prefill_len, url,
                                            current_time)
                avg_itl = self._window_avg(self._itl, url, current_time)

                prefill_ids = self._in_prefill.get(url, set())
                decode_ids = self._in_decode.get(url, set())
                allocated = self._allocated_locked(url)
                reserved = self._reserved_locked(url)
                out[url] = RequestStats(
                    qps=qps,
                    ttft=ttft,
                    ttft_p99=self._window_p99(self._ttft, url,
                                              current_time),
                    itl_p99=self._window_p99(self._itl, url,
                                             current_time),
                    in_prefill_requests=len(prefill_ids),
                    in_decoding_requests=len(decode_ids),
                    ts_prefill_enqueue=[
                        current_time - self._arrival_time[r]
                        for r in prefill_ids if r in self._arrival_time
                    ],
                    ts_decoding_enqueue=[
                        current_time - self._first_token_time[(url, r)]
                        for r in decode_ids
                        if (url, r) in self._first_token_time
                    ],
                    finished_requests=self._finished.get(url, 0),
                    uptime=(current_time - self._first_query_time
                            if self._first_query_time else 0.0),
                    avg_decoding_length=avg_dec,
                    avg_latency=avg_lat,
                    avg_itl=avg_itl,
                    queueing_delay=qdelay,
                    avg_prefill_length=avg_plen,
                    num_swapped_requests=self._swapped.get(url, 0),
                    allocated_blocks=allocated,
                    pending_reserved_blocks=reserved,
                    num_free_blocks=(
                        TOTAL_NUMBER_OF_BLOCKS - allocated - reserved
                    ),
                )
            return out


def initialize_request_stats_monitor(
        sliding_window_size: float) -> RequestStatsMonitor:
    return RequestStatsMonitor(sliding_window_size)


def get_request_stats_monitor() -> RequestStatsMonitor:
    return RequestStatsMonitor()
