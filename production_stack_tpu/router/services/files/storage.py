"""File storage abstraction (parity: files_service/storage.py)."""

import abc
from typing import List

from production_stack_tpu.router.services.files.openai_files import OpenAIFile

DEFAULT_STORAGE_PATH = "/tmp/pstpu_files"


class Storage(abc.ABC):
    @abc.abstractmethod
    async def save_file(self, user_id: str, filename: str, content: bytes,
                        purpose: str = "batch") -> OpenAIFile:
        ...

    @abc.abstractmethod
    async def get_file(self, user_id: str, file_id: str) -> OpenAIFile:
        ...

    @abc.abstractmethod
    async def get_file_content(self, user_id: str, file_id: str) -> bytes:
        ...

    @abc.abstractmethod
    async def list_files(self, user_id: str) -> List[OpenAIFile]:
        ...

    @abc.abstractmethod
    async def delete_file(self, user_id: str, file_id: str) -> None:
        ...


def initialize_storage(storage_type: str = "local_file",
                       base_path: str = DEFAULT_STORAGE_PATH) -> Storage:
    if storage_type == "local_file":
        from production_stack_tpu.router.services.files.file_storage import (
            FileStorage,
        )
        return FileStorage(base_path)
    raise ValueError(f"Unknown storage type: {storage_type}")
