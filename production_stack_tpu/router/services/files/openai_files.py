"""OpenAI Files API wire object (parity: files_service/openai_files.py)."""

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class OpenAIFile:
    id: str
    filename: str
    bytes: int
    purpose: str = "batch"
    created_at: int = field(default_factory=lambda: int(time.time()))
    object: str = "file"
    user_id: Optional[str] = None

    def metadata(self) -> dict:
        return {
            "id": self.id,
            "object": self.object,
            "bytes": self.bytes,
            "created_at": self.created_at,
            "filename": self.filename,
            "purpose": self.purpose,
        }
