from production_stack_tpu.router.services.files.file_storage import (
    FileStorage,
)
from production_stack_tpu.router.services.files.openai_files import (
    OpenAIFile,
)
from production_stack_tpu.router.services.files.storage import (
    Storage,
    initialize_storage,
)

__all__ = ["FileStorage", "OpenAIFile", "Storage", "initialize_storage"]
