"""Local-disk file storage (parity: files_service/file_storage.py).

Layout: ``<base>/<user_id>/<file_id>`` for content plus a ``.meta.json``
sidecar holding the OpenAI file metadata.
"""

import asyncio
import json
import os
import re
import uuid
from typing import List

try:
    import aiofiles
    import aiofiles.os as aio_os
except ImportError:  # env without aiofiles: thread-offloaded stdlib IO
    aiofiles = None
    aio_os = None

from production_stack_tpu.router.services.files.openai_files import OpenAIFile
from production_stack_tpu.router.services.files.storage import (
    DEFAULT_STORAGE_PATH,
    Storage,
)


async def _read_file(path: str, mode: str):
    if aiofiles is None:
        def _read():
            with open(path, mode) as f:
                return f.read()
        return await asyncio.to_thread(_read)
    async with aiofiles.open(path, mode) as f:
        return await f.read()


async def _write_file(path: str, data, mode: str) -> None:
    if aiofiles is None:
        def _write():
            with open(path, mode) as f:
                f.write(data)
        await asyncio.to_thread(_write)
        return
    async with aiofiles.open(path, mode) as f:
        await f.write(data)


async def _remove_file(path: str) -> None:
    if aio_os is None:
        await asyncio.to_thread(os.remove, path)
    else:
        await aio_os.remove(path)


class FileStorage(Storage):
    def __init__(self, base_path: str = DEFAULT_STORAGE_PATH):
        self.base_path = base_path
        os.makedirs(base_path, exist_ok=True)

    @staticmethod
    def _sanitize(component: str) -> str:
        """One path component: no separators, no traversal."""
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", component)
        if safe.strip(".") == "":  # '', '.', '..', '...'
            return "anonymous"
        return safe

    def _user_dir(self, user_id: str) -> str:
        path = os.path.join(self.base_path, self._sanitize(user_id))
        os.makedirs(path, exist_ok=True)
        return path

    def _paths(self, user_id: str, file_id: str) -> tuple[str, str]:
        d = self._user_dir(user_id)
        file_id = self._sanitize(file_id)
        return os.path.join(d, file_id), os.path.join(
            d, f"{file_id}.meta.json"
        )

    async def save_file(self, user_id: str, filename: str, content: bytes,
                        purpose: str = "batch") -> OpenAIFile:
        file_id = f"file-{uuid.uuid4().hex[:24]}"
        content_path, meta_path = self._paths(user_id, file_id)
        file = OpenAIFile(
            id=file_id, filename=filename, bytes=len(content),
            purpose=purpose, user_id=user_id,
        )
        await _write_file(content_path, content, "wb")
        await _write_file(meta_path, json.dumps(file.metadata()), "w")
        return file

    async def get_file(self, user_id: str, file_id: str) -> OpenAIFile:
        _, meta_path = self._paths(user_id, file_id)
        try:
            meta = json.loads(await _read_file(meta_path, "r"))
        except FileNotFoundError:
            raise FileNotFoundError(f"File {file_id} not found") from None
        return OpenAIFile(
            id=meta["id"], filename=meta["filename"], bytes=meta["bytes"],
            purpose=meta["purpose"], created_at=meta["created_at"],
            user_id=user_id,
        )

    async def get_file_content(self, user_id: str, file_id: str) -> bytes:
        content_path, _ = self._paths(user_id, file_id)
        try:
            return await _read_file(content_path, "rb")
        except FileNotFoundError:
            raise FileNotFoundError(f"File {file_id} not found") from None

    async def list_files(self, user_id: str) -> List[OpenAIFile]:
        d = self._user_dir(user_id)
        files = []
        for name in sorted(os.listdir(d)):
            if name.endswith(".meta.json"):
                files.append(
                    await self.get_file(user_id, name[: -len(".meta.json")])
                )
        return files

    async def delete_file(self, user_id: str, file_id: str) -> None:
        for path in self._paths(user_id, file_id):
            try:
                await _remove_file(path)
            except FileNotFoundError:
                pass
