"""Request-body rewriting hook (parity: request_service/rewriter.py).

Rewriters run before the request is forwarded; useful for prompt
injection-hardening, model aliasing, or header-driven overrides.
"""

import abc
from typing import Optional


class RequestRewriter(abc.ABC):
    @abc.abstractmethod
    def rewrite_request(self, request_body: bytes, model: str,
                        endpoint: str) -> bytes:
        raise NotImplementedError


class NoopRequestRewriter(RequestRewriter):
    def rewrite_request(self, request_body: bytes, model: str,
                        endpoint: str) -> bytes:
        return request_body


_REWRITERS = {"noop": NoopRequestRewriter}
_active: Optional[RequestRewriter] = None


def initialize_request_rewriter(kind: str, **kwargs) -> RequestRewriter:
    global _active
    try:
        _active = _REWRITERS[kind](**kwargs)
    except KeyError:
        raise ValueError(f"Unknown request rewriter: {kind}") from None
    return _active


def get_request_rewriter() -> RequestRewriter:
    global _active
    if _active is None:
        _active = NoopRequestRewriter()
    return _active
