"""Router-side Prometheus gauges.

Exposition names match the reference
(src/vllm_router/services/metrics_service/__init__.py) so the shipped
Grafana dashboard and prometheus-adapter HPA rules keep working unchanged.
"""

import time

from prometheus_client import CONTENT_TYPE_LATEST, Gauge, generate_latest

_LBL = ["server"]

num_requests_running = Gauge(
    "vllm:num_requests_running", "Number of running requests", _LBL)
num_requests_waiting = Gauge(
    "vllm:num_requests_waiting", "Number of waiting requests", _LBL)
current_qps = Gauge("vllm:current_qps", "Current Queries Per Second", _LBL)
avg_decoding_length = Gauge(
    "vllm:avg_decoding_length", "Average Decoding Length", _LBL)
num_prefill_requests = Gauge(
    "vllm:num_prefill_requests", "Number of Prefill Requests", _LBL)
num_decoding_requests = Gauge(
    "vllm:num_decoding_requests", "Number of Decoding Requests", _LBL)
healthy_pods_total = Gauge(
    "vllm:healthy_pods_total", "Number of healthy engine pods", _LBL)
avg_latency = Gauge(
    "vllm:avg_latency", "Average end-to-end request latency", _LBL)
avg_itl = Gauge("vllm:avg_itl", "Average Inter-Token Latency", _LBL)
ttft_p99 = Gauge(
    "vllm:ttft_p99_seconds",
    "p99 time-to-first-token over the stats window (fleet autoscaler "
    "SLO signal)", _LBL)
itl_p99 = Gauge(
    "vllm:itl_p99_seconds",
    "p99 inter-token latency over the stats window (fleet autoscaler "
    "SLO signal)", _LBL)
num_requests_swapped = Gauge(
    "vllm:num_requests_swapped", "Number of swapped requests", _LBL)
allocated_blocks = Gauge(
    "vllm:allocated_blocks", "Number of allocated KV blocks", _LBL)
pending_reserved_blocks = Gauge(
    "vllm:pending_reserved_blocks", "Number of pending reserved KV blocks",
    _LBL)
num_free_blocks = Gauge(
    "vllm:num_free_blocks", "Number of free KV blocks", _LBL)
router_queueing_delay = Gauge(
    "vllm:router_queueing_delay_seconds",
    "Router-side queueing delay (arrival to admission)", _LBL)
avg_prefill_length = Gauge(
    "vllm:avg_prefill_length",
    "Average prompt length of routed requests (tokens)", _LBL)

# -- scraped engine counters (stats/engine_stats.py) ------------------------
engine_prefix_cache_hit_rate = Gauge(
    "vllm:engine_gpu_prefix_cache_hit_rate",
    "Engine-reported prefix-cache hit rate (scraped)", _LBL)
engine_num_requests_running = Gauge(
    "vllm:engine_num_requests_running",
    "Engine-reported running requests (scraped; the unlabeled "
    "vllm:num_requests_running is the router's own live-traffic "
    "view)", _LBL)
engine_gpu_cache_usage_perc = Gauge(
    "vllm:engine_gpu_cache_usage_perc",
    "Engine-reported KV cache usage fraction (scraped)", _LBL)
spec_decode_num_draft_tokens = Gauge(
    "vllm:spec_decode_num_draft_tokens",
    "Engine-reported speculative draft tokens (scraped)", _LBL)
spec_decode_num_accepted_tokens = Gauge(
    "vllm:spec_decode_num_accepted_tokens",
    "Engine-reported accepted speculative tokens (scraped)", _LBL)
engine_step_host_seconds = Gauge(
    "vllm:engine_step_host_seconds",
    "Engine-reported cumulative host-side step seconds (scraped)",
    _LBL)
engine_step_device_wait_seconds = Gauge(
    "vllm:engine_step_device_wait_seconds",
    "Engine-reported cumulative device-readback wait seconds "
    "(scraped)", _LBL)
engine_device_idle_seconds = Gauge(
    "vllm:engine_device_idle_seconds",
    "Engine-reported cumulative device-idle gap seconds (scraped)",
    _LBL)
engine_pipeline_steps = Gauge(
    "vllm:engine_pipeline_steps",
    "Engine-reported total engine steps (scraped)", _LBL)
engine_pipeline_ahead_steps = Gauge(
    "vllm:engine_pipeline_ahead_steps",
    "Engine-reported steps whose successor was dispatched before "
    "readback (scraped)", _LBL)
engine_async_inflight_depth = Gauge(
    "vllm:engine_async_inflight_depth",
    "Engine-reported dispatched-but-unread decode steps (scraped)",
    _LBL)
engine_step_prefill_rows = Gauge(
    "vllm:engine_step_prefill_rows",
    "Engine-reported prefill rows in the last unified ragged step "
    "(scraped)", _LBL)
engine_step_decode_rows = Gauge(
    "vllm:engine_step_decode_rows",
    "Engine-reported decode rows in the last unified ragged step "
    "(scraped)", _LBL)
engine_step_pad_rows = Gauge(
    "vllm:engine_step_pad_rows",
    "Engine-reported pad rows in the last unified ragged step "
    "(scraped)", _LBL)
engine_ragged_steps = Gauge(
    "vllm:engine_ragged_steps",
    "Engine-reported unified ragged steps executed (scraped)", _LBL)
engine_ragged_rows = Gauge(
    "vllm:engine_ragged_rows",
    "Engine-reported cumulative unified-step row slots (scraped)",
    _LBL)
engine_ragged_pad_rows = Gauge(
    "vllm:engine_ragged_pad_rows",
    "Engine-reported cumulative unified-step pad rows (scraped)",
    _LBL)
engine_kv_cache_page_capacity = Gauge(
    "vllm:engine_kv_cache_page_capacity",
    "Engine-reported KV page budget after any int8 expansion "
    "(scraped)", _LBL)
engine_kv_bytes_per_decode_step = Gauge(
    "vllm:engine_kv_bytes_per_decode_step",
    "Engine-reported worst-case KV bytes written per decode step "
    "(scraped)", _LBL)
engine_kv_cache_dtype = Gauge(
    "vllm:engine_kv_cache_dtype",
    "Engine-reported KV page storage dtype as a one-hot labeled "
    "gauge (scraped)", ["server", "kv_dtype"])
engine_disagg_prefill_requests = Gauge(
    "vllm:engine_disagg_prefill_requests",
    "Engine-reported disagg prefill handoffs served (scraped)", _LBL)
engine_disagg_decode_requests = Gauge(
    "vllm:engine_disagg_decode_requests",
    "Engine-reported disagg handoffs accepted for decode (scraped)",
    _LBL)
engine_disagg_kv_bytes_shipped = Gauge(
    "vllm:engine_disagg_kv_bytes_shipped",
    "Engine-reported KV bytes shipped to the offload tier on handoff "
    "(scraped)", _LBL)
engine_disagg_awaiting_kv = Gauge(
    "vllm:engine_disagg_awaiting_kv_requests",
    "Engine-reported sequences parked awaiting handed-off KV "
    "(scraped)", _LBL)
engine_disagg_handoff_latency_mean = Gauge(
    "vllm:engine_disagg_handoff_latency_mean_seconds",
    "Mean handoff-admission latency from the engine's histogram "
    "sum/count (scraped)", _LBL)
# Per-phase request latency means (docs/observability.md): each
# engine's phase histogram sum/count re-exported as a mean so the
# dashboard decomposes TTFT/e2e without scraping every engine.
engine_request_queue_time_mean = Gauge(
    "vllm:engine_request_queue_time_mean_seconds",
    "Mean time in the engine waiting queue, arrival to first "
    "scheduled (scraped histogram sum/count)", _LBL)
engine_request_prefill_time_mean = Gauge(
    "vllm:engine_request_prefill_time_mean_seconds",
    "Mean prefill compute time, first scheduled to first token "
    "(scraped histogram sum/count)", _LBL)
engine_request_awaiting_kv_time_mean = Gauge(
    "vllm:engine_request_awaiting_kv_time_mean_seconds",
    "Mean time parked in AWAITING_KV on disagg decode engines "
    "(scraped histogram sum/count)", _LBL)
engine_request_decode_time_mean = Gauge(
    "vllm:engine_request_decode_time_mean_seconds",
    "Mean decode time, first token to finish (scraped histogram "
    "sum/count)", _LBL)
engine_draining = Gauge(
    "vllm:engine_draining",
    "Engine-reported draining state: 1 while the engine rejects new "
    "admissions and finishes in-flight sequences (scraped)", _LBL)
# QoS under overload (docs/qos.md): engine shed/preemption counters
# re-exported with their class/outcome labels, plus the mean
# preempt-restore latency from the engine's histogram sum/count.
engine_qos_shed = Gauge(
    "vllm:engine_qos_shed",
    "Engine-reported requests shed with 429 at the QoS gate, per "
    "priority class (scraped)", ["server", "class"])
engine_preempt_offload = Gauge(
    "vllm:engine_preempt_offload",
    "Engine-reported preemptions per outcome: 'offloaded' (victim KV "
    "shipped to the offload tier) vs 'recompute' (scraped)",
    ["server", "outcome"])
engine_preempt_restore_latency_mean = Gauge(
    "vllm:engine_preempt_restore_latency_mean_seconds",
    "Mean time to restore a preempted victim's KV pages from the "
    "offload tier on re-admission (scraped histogram sum/count)",
    _LBL)
# Device performance observatory (docs/observability.md): re-exported
# compile ledger, HBM breakdown, step-time/MFU, and attention-impl
# info gauge. Counter families drop their _total suffix here (router
# Gauges, same idiom as engine_ragged_steps).
engine_compile_events = Gauge(
    "vllm:engine_compile_events",
    "Engine-reported jit compile events per program kind (scraped)",
    ["server", "kind"])
engine_compile_seconds = Gauge(
    "vllm:engine_compile_seconds",
    "Engine-reported cumulative compile wall seconds per program "
    "kind (scraped)", ["server", "kind"])
engine_executable_cache_size = Gauge(
    "vllm:engine_executable_cache_size",
    "Engine-reported live jit executable-cache size per program kind "
    "(scraped)", ["server", "kind"])
engine_hbm_bytes = Gauge(
    "vllm:engine_hbm_bytes",
    "Engine-reported analytic HBM bytes per category: weights, "
    "kv_pages, kv_scales, step_buffers (scraped)",
    ["server", "category"])
engine_step_device_seconds = Gauge(
    "vllm:engine_step_device_seconds",
    "Engine-reported cumulative device step seconds per step kind "
    "(scraped)", ["server", "kind"])
engine_mfu = Gauge(
    "vllm:engine_mfu",
    "Engine-reported useful-token model FLOPs utilization against "
    "the device peak; 0 when the peak is unknown (scraped)", _LBL)
engine_attention_impl = Gauge(
    "vllm:engine_attention_impl",
    "Engine-reported resolved attention impl per phase as a one-hot "
    "labeled info gauge — alarms the silent XLA fallback (scraped)",
    ["server", "phase", "impl"])
# Topology observability (docs/parallelism.md): each engine's mesh
# axis sizes, the slice its devices sit on, and per-slice liveness
# from its multihost bridge, re-exported per server.
engine_mesh_shape = Gauge(
    "vllm:engine_mesh_shape",
    "Engine-reported mesh axis size per axis (dp/pp/sp/tp) "
    "(scraped)", ["server", "axis"])
engine_slice_id = Gauge(
    "vllm:engine_slice_id",
    "Engine-reported topology slice of the engine's devices "
    "(scraped)", _LBL)
engine_slice_live = Gauge(
    "vllm:engine_slice_live",
    "Engine-reported per-slice liveness from the multihost step "
    "bridge; a dead host drops exactly one slice to 0 (scraped)",
    ["server", "slice"])
# KV economy (docs/kv_economy.md): each engine's KV-state summary and
# its view of the shared cluster cache tier, re-exported per server,
# plus the routing policy's expected-hit signal.
engine_kv_summary_hot_chains = Gauge(
    "vllm:engine_kv_summary_hot_chains",
    "Engine-reported hot prefix chains advertised at GET /kv/summary "
    "(scraped)", _LBL)
engine_kv_free_page_headroom = Gauge(
    "vllm:engine_kv_free_page_headroom",
    "Engine-reported free KV pages available to new prefixes "
    "(scraped)", _LBL)
engine_kv_headroom_frac = Gauge(
    "vllm:engine_kv_headroom_frac",
    "Engine-reported free-page headroom over total pages — varies "
    "1.9-3.55x with --kv-cache-dtype (scraped)", _LBL)
engine_kv_summary_age = Gauge(
    "vllm:engine_kv_summary_age_seconds",
    "Age of the engine's last successful /kv/summary fetch; "
    "KVStateAwarePolicy distrusts summaries older than its staleness "
    "bound (scraped)", _LBL)
engine_kv_cluster_hits = Gauge(
    "vllm:engine_kv_cluster_hits",
    "Engine-reported pages fetched from the shared cluster cache "
    "(scraped)", _LBL)
engine_kv_cluster_misses = Gauge(
    "vllm:engine_kv_cluster_misses",
    "Engine-reported shared cluster cache fetch/probe misses "
    "(scraped)", _LBL)
engine_kv_cluster_admissions = Gauge(
    "vllm:engine_kv_cluster_admissions",
    "Engine-reported write-throughs the shared cache admitted "
    "(scraped)", _LBL)
engine_kv_cluster_rejections = Gauge(
    "vllm:engine_kv_cluster_rejections",
    "Engine-reported write-throughs the shared cache declined pending "
    "demand promotion (scraped)", _LBL)
kv_route_expected_hit_tokens = Gauge(
    "vllm:kv_route_expected_hit_tokens",
    "Expected prefix-hit tokens of the last request KVStateAwarePolicy "
    "routed to this engine", _LBL)
# Self-tuning controllers (docs/autotuning.md): per-engine active
# count, latched guardrail freezes, and live knob values, re-exported
# for stacktop's AUTOTUNE column and the Self-Tuning dashboard row.
engine_autotune_active = Gauge(
    "vllm:engine_autotune_active_controllers",
    "Engine-reported self-tuning controllers currently allowed to "
    "act; 0 in off/shadow mode (scraped)", _LBL)
engine_autotune_frozen = Gauge(
    "vllm:engine_autotune_frozen",
    "Engine-reported guardrail freeze per controller; 1 latches "
    "until POST /autotune/reset (scraped)", ["server", "controller"])
engine_autotune_knob = Gauge(
    "vllm:engine_autotune_knob_value",
    "Engine-reported live knob value per self-tuning controller "
    "(scraped)", ["server", "controller"])

# -- fleet manager (production_stack_tpu/fleet/, docs/fleet.md) -------------
# Set by an in-process fleet manager (or its embedded exporter); the
# router re-exports them off the shared default registry so one scrape
# target carries both SLO signals and the replica-count decisions made
# from them.
fleet_desired_replicas = Gauge(
    "vllm:fleet_desired_replicas",
    "Fleet-manager desired replica count per pool", ["pool"])
fleet_live_replicas = Gauge(
    "vllm:fleet_live_replicas",
    "Fleet-manager live (spawned and registered) replicas per pool",
    ["pool"])
fleet_scale_events = Gauge(
    "vllm:fleet_scale_events_total",
    "Fleet-manager scale decisions applied per pool and direction",
    ["pool", "direction"])

# -- canary rollouts (fleet/rollout.py, docs/fleet.md) ----------------------
rollout_phase = Gauge(
    "vllm:rollout_phase",
    "Rollout controller phase per pool as a one-hot labeled gauge "
    "(idle/canary/bake/roll/paused/rolled_back)", ["pool", "phase"])
rollout_replicas = Gauge(
    "vllm:rollout_replicas",
    "Replica count per pool and build revision during rollouts",
    ["pool", "revision"])
rollout_rollbacks = Gauge(
    "vllm:rollout_rollbacks_total",
    "Automatic rollbacks the rollout controller has executed per pool",
    ["pool"])
rollout_alarm = Gauge(
    "vllm:rollout_alarm",
    "1 while a pool's rollout is frozen behind a failed canary; "
    "latched until an operator resumes or aborts (docs/fleet.md)",
    ["pool"])
server_revision = Gauge(
    "vllm:server_revision",
    "Build revision serving on each endpoint as a one-hot labeled "
    "info gauge", ["server", "revision"])

# -- resilience layer (router/resilience.py) --------------------------------
circuit_breaker_state = Gauge(
    "vllm:circuit_breaker_state",
    "Circuit breaker state per endpoint (0=closed, 1=half-open, 2=open)",
    _LBL)
circuit_breaker_opens = Gauge(
    "vllm:circuit_breaker_opens_total",
    "Times this endpoint's circuit breaker has opened", _LBL)
server_errors = Gauge(
    "vllm:server_errors_total",
    "Failures the router has charged to this endpoint's circuit "
    "breaker (the rollout judge reads the canary's bake-window delta)",
    _LBL)
endpoint_healthy = Gauge(
    "vllm:endpoint_healthy",
    "Active health-probe verdict per endpoint (1=healthy)", _LBL)
health_probe_failures = Gauge(
    "vllm:health_probe_failures_total",
    "Failed active health probes per endpoint", _LBL)
request_retries = Gauge(
    "vllm:request_retries_total",
    "Proxy attempts that failed pre-first-byte and were retried/failed "
    "over (router-wide)", [])
request_failovers = Gauge(
    "vllm:request_failovers_total",
    "Requests that succeeded on a backend other than the first choice "
    "(router-wide)", [])
requests_shed = Gauge(
    "vllm:requests_shed_total",
    "Requests answered 503 because no endpoint was admittable "
    "(router-wide)", [])

# -- router QoS (router/qos.py, docs/qos.md) --------------------------------
tenant_throttled = Gauge(
    "vllm:tenant_throttled_total",
    "Requests served degraded (max_tokens clamped, speculation off) "
    "because their tenant was over its rate bucket (router-wide)", [])
router_qos_shed = Gauge(
    "vllm:router_qos_shed_total",
    "Requests shed with 429 at the router's tenant rate limiter, per "
    "priority class (router-wide)", ["class"])

# -- disaggregated dispatch (services/request_service.py) -------------------
router_disagg_handoffs = Gauge(
    "vllm:router_disagg_handoffs_total",
    "Requests served via the two-hop prefill->decode disagg path "
    "(router-wide)", [])
router_disagg_fallbacks = Gauge(
    "vllm:router_disagg_fallbacks_total",
    "Requests that attempted the disagg path but were served "
    "monolithically instead (router-wide)", [])

# -- crash recovery (docs/crash_recovery.md) --------------------------------
stream_resumes = Gauge(
    "vllm:stream_resumes_total",
    "Mid-stream failover outcomes: streams resumed byte-exactly on a "
    "replacement engine vs ended with a terminal error event "
    "(router-wide)", ["outcome"])
fleet_crash_respawns = Gauge(
    "vllm:fleet_crash_respawns_total",
    "Fleet-manager respawns of replicas that exited without a drain, "
    "per pool", ["pool"])
fleet_poison_quarantines = Gauge(
    "vllm:fleet_poison_quarantines_total",
    "Requests quarantined after crashing multiple engines "
    "(router-wide)", [])

# -- cluster SLO ledger + drift sentinel (production_stack_tpu/obs/) --------
slo_attainment = Gauge(
    "vllm:slo_attainment",
    "Good fraction per (class, model) over the attainment window "
    "against the --slo-spec targets (docs/observability.md)",
    ["class", "model"])
slo_burn_rate = Gauge(
    "vllm:slo_burn_rate",
    "Error-budget burn rate per SRE window; above 1.0 the budget "
    "empties before the window does", ["window"])
slo_good_requests = Gauge(
    "vllm:slo_good_requests_total",
    "Requests that met their resolved SLO target, per (class, model)",
    ["class", "model"])
slo_bad_requests = Gauge(
    "vllm:slo_bad_requests_total",
    "Requests that breached their resolved SLO target, per "
    "(class, model)", ["class", "model"])
slow_archive_depth = Gauge(
    "vllm:slow_archive_depth",
    "Slow-request exemplars currently held in the GET /debug/slow "
    "ring", [])
perf_drift = Gauge(
    "vllm:perf_drift",
    "1 while any server's step-time median sits outside the "
    "--perf-baseline band for this phase", ["phase"])
engine_step_time_median = Gauge(
    "vllm:engine_step_time_median_seconds",
    "Engine-reported median device step time per kind over the "
    "observatory's recent-step ring (scraped)", ["server", "kind"])


def _set_or_clear(gauge, server: str, value: float) -> None:
    """-1 is RequestStats' "no observation yet" sentinel. Rendering it
    would leak an impossible negative latency into Prometheus on idle
    servers (poisoning p99 alert rules), so the stale label child is
    removed from the exposition instead."""
    if value >= 0:
        gauge.labels(server=server).set(value)
        return
    try:
        gauge.remove(server)
    except KeyError:
        pass


def refresh_gauges() -> None:
    """Pull the latest snapshots into the gauge registry."""
    from production_stack_tpu.router.service_discovery import (
        get_service_discovery,
    )
    from production_stack_tpu.router.stats.request_stats import (
        get_request_stats_monitor,
    )

    stats = get_request_stats_monitor().get_request_stats(time.time())
    for server, stat in stats.items():
        current_qps.labels(server=server).set(stat.qps)
        avg_decoding_length.labels(server=server).set(stat.avg_decoding_length)
        num_prefill_requests.labels(server=server).set(
            stat.in_prefill_requests)
        num_decoding_requests.labels(server=server).set(
            stat.in_decoding_requests)
        num_requests_running.labels(server=server).set(
            stat.in_prefill_requests + stat.in_decoding_requests)
        avg_latency.labels(server=server).set(stat.avg_latency)
        avg_itl.labels(server=server).set(stat.avg_itl)
        _set_or_clear(ttft_p99, server, stat.ttft_p99)
        _set_or_clear(itl_p99, server, stat.itl_p99)
        num_requests_swapped.labels(server=server).set(
            stat.num_swapped_requests)
        allocated_blocks.labels(server=server).set(stat.allocated_blocks)
        pending_reserved_blocks.labels(server=server).set(
            stat.pending_reserved_blocks)
        num_free_blocks.labels(server=server).set(stat.num_free_blocks)
        router_queueing_delay.labels(server=server).set(
            stat.queueing_delay)
        avg_prefill_length.labels(server=server).set(
            stat.avg_prefill_length)
    from production_stack_tpu.router.stats.engine_stats import (
        get_engine_stats_scraper,
    )
    try:
        engine_stats = get_engine_stats_scraper().get_engine_stats()
    except ValueError:  # scraper not initialized (some test rigs)
        engine_stats = {}
    for server, es in engine_stats.items():
        engine_prefix_cache_hit_rate.labels(server=server).set(
            es.kv_cache_hit_rate)
        # Engine-authoritative queue/occupancy numbers: waiting depth
        # backs the declared num_requests_waiting gauge (the router
        # cannot see an engine's internal queue from its own traffic),
        # running/usage re-export under engine_* names beside the
        # router-computed views.
        num_requests_waiting.labels(server=server).set(
            es.num_queuing_requests)
        engine_num_requests_running.labels(server=server).set(
            es.num_running_requests)
        engine_gpu_cache_usage_perc.labels(server=server).set(
            es.kv_usage_perc)
        spec_decode_num_draft_tokens.labels(server=server).set(
            es.spec_decode_num_draft_tokens)
        spec_decode_num_accepted_tokens.labels(server=server).set(
            es.spec_decode_num_accepted_tokens)
        engine_step_host_seconds.labels(server=server).set(
            es.engine_step_host_seconds)
        engine_step_device_wait_seconds.labels(server=server).set(
            es.engine_step_device_wait_seconds)
        engine_device_idle_seconds.labels(server=server).set(
            es.engine_device_idle_seconds)
        engine_pipeline_steps.labels(server=server).set(
            es.engine_pipeline_steps)
        engine_pipeline_ahead_steps.labels(server=server).set(
            es.engine_pipeline_ahead_steps)
        engine_async_inflight_depth.labels(server=server).set(
            es.engine_async_inflight_depth)
        engine_step_prefill_rows.labels(server=server).set(
            es.engine_step_prefill_rows)
        engine_step_decode_rows.labels(server=server).set(
            es.engine_step_decode_rows)
        engine_step_pad_rows.labels(server=server).set(
            es.engine_step_pad_rows)
        engine_ragged_steps.labels(server=server).set(
            es.engine_ragged_steps)
        engine_ragged_rows.labels(server=server).set(
            es.engine_ragged_rows)
        engine_ragged_pad_rows.labels(server=server).set(
            es.engine_ragged_pad_rows)
        engine_kv_cache_page_capacity.labels(server=server).set(
            es.engine_kv_cache_page_capacity)
        engine_kv_bytes_per_decode_step.labels(server=server).set(
            es.engine_kv_bytes_per_decode_step)
        if es.engine_kv_cache_dtype:
            engine_kv_cache_dtype.labels(
                server=server,
                kv_dtype=es.engine_kv_cache_dtype).set(1)
        engine_disagg_prefill_requests.labels(server=server).set(
            es.disagg_prefill_requests)
        engine_disagg_decode_requests.labels(server=server).set(
            es.disagg_decode_requests)
        engine_disagg_kv_bytes_shipped.labels(server=server).set(
            es.disagg_kv_bytes_shipped)
        engine_disagg_awaiting_kv.labels(server=server).set(
            es.disagg_awaiting_kv_requests)
        if es.disagg_handoff_latency_count > 0:
            engine_disagg_handoff_latency_mean.labels(server=server).set(
                es.disagg_handoff_latency_sum
                / es.disagg_handoff_latency_count)
        if es.request_queue_time_count > 0:
            engine_request_queue_time_mean.labels(server=server).set(
                es.request_queue_time_sum
                / es.request_queue_time_count)
        if es.request_prefill_time_count > 0:
            engine_request_prefill_time_mean.labels(server=server).set(
                es.request_prefill_time_sum
                / es.request_prefill_time_count)
        if es.request_awaiting_kv_time_count > 0:
            engine_request_awaiting_kv_time_mean.labels(
                server=server).set(
                es.request_awaiting_kv_time_sum
                / es.request_awaiting_kv_time_count)
        if es.request_decode_time_count > 0:
            engine_request_decode_time_mean.labels(server=server).set(
                es.request_decode_time_sum
                / es.request_decode_time_count)
        engine_draining.labels(server=server).set(es.engine_draining)
        for cls, value in es.qos_shed_by_class.items():
            engine_qos_shed.labels(
                **{"server": server, "class": cls}).set(value)
        for outcome, value in es.preempt_offload_by_outcome.items():
            engine_preempt_offload.labels(
                server=server, outcome=outcome).set(value)
        if es.preempt_restore_latency_count > 0:
            engine_preempt_restore_latency_mean.labels(
                server=server).set(
                es.preempt_restore_latency_sum
                / es.preempt_restore_latency_count)
        for kind, value in es.compile_events_by_kind.items():
            engine_compile_events.labels(
                server=server, kind=kind).set(value)
        for kind, value in es.compile_seconds_by_kind.items():
            engine_compile_seconds.labels(
                server=server, kind=kind).set(value)
        for kind, value in es.executable_cache_size_by_kind.items():
            engine_executable_cache_size.labels(
                server=server, kind=kind).set(value)
        for category, value in es.hbm_bytes_by_category.items():
            engine_hbm_bytes.labels(
                server=server, category=category).set(value)
        for kind, value in es.step_device_seconds_by_kind.items():
            engine_step_device_seconds.labels(
                server=server, kind=kind).set(value)
        for kind, value in es.step_time_median_by_kind.items():
            engine_step_time_median.labels(
                server=server, kind=kind).set(value)
        engine_mfu.labels(server=server).set(es.engine_mfu)
        for phase, impl in es.attention_impl_by_phase.items():
            engine_attention_impl.labels(
                server=server, phase=phase, impl=impl).set(1)
        for axis, value in es.mesh_shape_by_axis.items():
            engine_mesh_shape.labels(
                server=server, axis=axis).set(value)
        engine_slice_id.labels(server=server).set(es.engine_slice_id)
        for slice_id, live in es.slice_live_by_id.items():
            engine_slice_live.labels(
                server=server, slice=slice_id).set(live)
        engine_kv_summary_hot_chains.labels(server=server).set(
            es.kv_summary_hot_chains or len(es.kv_hot_chains))
        engine_kv_free_page_headroom.labels(server=server).set(
            es.kv_free_page_headroom)
        if es.kv_total_pages > 0:
            engine_kv_headroom_frac.labels(server=server).set(
                es.kv_free_page_headroom / es.kv_total_pages)
        if es.kv_summary_time > 0:
            engine_kv_summary_age.labels(server=server).set(
                max(0.0, time.time() - es.kv_summary_time))
        engine_kv_cluster_hits.labels(server=server).set(
            es.kv_cluster_hits)
        engine_kv_cluster_misses.labels(server=server).set(
            es.kv_cluster_misses)
        engine_kv_cluster_admissions.labels(server=server).set(
            es.kv_cluster_admissions)
        engine_kv_cluster_rejections.labels(server=server).set(
            es.kv_cluster_rejections)
        engine_autotune_active.labels(server=server).set(
            es.autotune_active_controllers)
        for controller, value in \
                es.autotune_frozen_by_controller.items():
            engine_autotune_frozen.labels(
                server=server, controller=controller).set(value)
        for controller, value in \
                es.autotune_knob_by_controller.items():
            engine_autotune_knob.labels(
                server=server, controller=controller).set(value)
    from production_stack_tpu.router.routing.logic import (
        KVStateAwarePolicy,
        get_routing_logic,
    )
    try:
        policy = get_routing_logic()
    except ValueError:
        policy = None
    if isinstance(policy, KVStateAwarePolicy):
        for server, toks in policy.expected_hit_tokens_by_url.items():
            kv_route_expected_hit_tokens.labels(server=server).set(toks)
    from production_stack_tpu.router.services import request_service
    router_disagg_handoffs.set(request_service.disagg_handoffs_total)
    router_disagg_fallbacks.set(request_service.disagg_fallbacks_total)
    for outcome, value in \
            request_service.stream_resumes_by_outcome.items():
        stream_resumes.labels(outcome=outcome).set(value)
    fleet_poison_quarantines.set(
        request_service.poison_quarantines_total)
    from production_stack_tpu.router.resilience import get_resilience
    mgr = get_resilience()
    try:
        for ep in get_service_discovery().get_endpoint_info(
                include_unhealthy=True):
            up = mgr is None or mgr.endpoint_available(ep.url)
            healthy_pods_total.labels(server=ep.url).set(1 if up else 0)
            if getattr(ep, "revision", ""):
                server_revision.labels(
                    server=ep.url, revision=ep.revision).set(1)
    except ValueError:
        pass
    if mgr is not None:
        for url, breaker in mgr.breaker_snapshot().items():
            circuit_breaker_state.labels(server=url).set(
                int(breaker.state))
            circuit_breaker_opens.labels(server=url).set(
                breaker.opens_total)
            server_errors.labels(server=url).set(
                breaker.failures_total)
        if mgr.health is not None:
            for url, st in mgr.health.snapshot().items():
                endpoint_healthy.labels(server=url).set(
                    1 if st.healthy else 0)
                health_probe_failures.labels(server=url).set(
                    st.failures_total)
        request_retries.set(mgr.retries_total)
        request_failovers.set(mgr.failovers_total)
        requests_shed.set(mgr.shed_requests_total)
    from production_stack_tpu.router.qos import get_router_qos
    rqos = get_router_qos()
    if rqos is not None:
        tenant_throttled.set(rqos.tenant_throttled_total)
        for cls, value in rqos.shed_by_class.items():
            router_qos_shed.labels(**{"class": cls}).set(value)
    from production_stack_tpu import obs
    ledger = obs.get_slo_ledger()
    if ledger is not None:
        for (cls, mdl), frac in ledger.attainments().items():
            slo_attainment.labels(
                **{"class": cls, "model": mdl}).set(frac)
        for window, rate in ledger.burn_rates().items():
            slo_burn_rate.labels(window=window).set(rate)
        totals = ledger.totals()
        for (cls, mdl), n in totals["good"].items():
            slo_good_requests.labels(
                **{"class": cls, "model": mdl}).set(n)
        for (cls, mdl), n in totals["bad"].items():
            slo_bad_requests.labels(
                **{"class": cls, "model": mdl}).set(n)
    archive = obs.get_slow_archive()
    if archive is not None:
        slow_archive_depth.set(archive.depth())
    sentinel = obs.get_drift_sentinel()
    if sentinel is not None:
        medians = {server: es.step_time_median_by_kind
                   for server, es in engine_stats.items()}
        for phase, flag in sentinel.flags(medians).items():
            perf_drift.labels(phase=phase).set(flag)


def render_exposition() -> tuple[bytes, str]:
    refresh_gauges()
    return generate_latest(), CONTENT_TYPE_LATEST
