"""The proxy hot path: pick a backend, stream the response through.

Capability parity with reference
src/vllm_router/services/request_service/request.py (route_general_request
L137, process_request L46): body parse + model filter, rewriter hook,
stats lifecycle events per streamed chunk, fork's ``x-prefill-tokens``
hint header (L199-203), HRA future await (L210-213), cleanup on
disconnect. Implemented on aiohttp: the backend stream is forwarded
chunk-by-chunk into a ``web.StreamResponse`` with no buffering.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.service_discovery import (
    get_service_discovery,
)
from production_stack_tpu.router.services.rewriter import (
    get_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    get_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    get_request_stats_monitor,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Fork feature: clients may pre-declare prompt size for admission control.
PREFILL_TOKENS_HEADER = "x-prefill-tokens"

# Hop-by-hop headers never forwarded in either direction.
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding",
    "upgrade", "host", "content-length",
}
# aiohttp auto-decompresses the backend body, so advertising the
# backend's encoding downstream would corrupt every response.
_RESPONSE_DROP_HEADERS = _HOP_HEADERS | {"content-encoding"}

# Cap on response bytes buffered for the semantic cache store path.
_CACHE_STORE_MAX_BYTES = 4 * 1024 * 1024


def _client_session(app: web.Application) -> aiohttp.ClientSession:
    return app["backend_session"]


def _estimate_prefill_tokens(request: web.Request, body: bytes) -> int:
    hint = request.headers.get(PREFILL_TOKENS_HEADER)
    if hint is not None:
        try:
            return max(0, int(hint))
        except ValueError:
            logger.warning("Bad %s header: %r", PREFILL_TOKENS_HEADER, hint)
    # ~4 bytes/token heuristic when the client does not hint.
    return len(body) // 4


def _routable_prompt_text(payload: dict) -> "str | None":
    """Stable text rendering of the request's prompt for prefix-aware
    routing (chat history or completion prompt; None when the body
    carries neither)."""
    messages = payload.get("messages")
    if isinstance(messages, list):
        parts = []
        for m in messages:
            if isinstance(m, dict) and isinstance(m.get("content"), str):
                parts.append(f"{m.get('role', '')}\x1f{m['content']}")
        return "\x1e".join(parts) if parts else None
    prompt = payload.get("prompt")
    if isinstance(prompt, str):
        return prompt
    if isinstance(prompt, list) and prompt and \
            all(isinstance(p, str) for p in prompt):
        return "\x1e".join(prompt)
    return None


def _error(status: int, message: str) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": "invalid_request_error"}},
        status=status,
    )


async def route_general_request(request: web.Request,
                                endpoint_path: str) -> web.StreamResponse:
    """Proxy one OpenAI-API request to a chosen engine, streaming back."""
    from production_stack_tpu.router.routing.logic import get_routing_logic

    in_router_time = time.time()
    request_id = request.headers.get("x-request-id") or str(uuid.uuid4())
    body = await request.read()
    try:
        payload = json.loads(body) if body else {}
    except json.JSONDecodeError:
        return _error(400, "Request body is not valid JSON")
    model = payload.get("model")
    if not model:
        return _error(400, "Request body must contain a 'model' field")

    rewriter = get_request_rewriter()
    rewritten = rewriter.rewrite_request(body, model, endpoint_path)
    if rewritten is not body:
        body = rewritten

    endpoints = [
        ep for ep in get_service_discovery().get_endpoint_info()
        if ep.serves_model(model)
    ]
    if not endpoints:
        return _error(
            400, f"Model {model} not found on any serving engine"
        )

    engine_stats = get_engine_stats_scraper().get_engine_stats()
    monitor = get_request_stats_monitor()
    request_stats = monitor.get_request_stats(time.time())
    monitor.on_request_arrival(request_id, in_router_time)

    from production_stack_tpu.router.tracing import start_span
    span = start_span(request_id, model, endpoint_path)

    num_prefill_tokens = _estimate_prefill_tokens(request, body)

    policy = get_routing_logic()
    choice = policy.route_request(
        endpoints, engine_stats, request_stats, request.headers,
        request_id, num_prefill_tokens,
        prompt_text=(_routable_prompt_text(payload)
                     if policy.uses_prompt_text else None),
    )
    if hasattr(choice, "__await__"):
        try:
            server_url = await choice
        except Exception as e:  # admission rejected (e.g. can never fit)
            monitor.on_request_kill("<unrouted>", request_id)
            if span is not None:
                from production_stack_tpu.router.tracing import (
                    get_span_logger,
                )
                span.finish("rejected")
                sink = get_span_logger()
                if sink is not None:
                    sink.emit(span)
            return _error(429, f"Request not admitted: {e}")
    else:
        server_url = choice
    if span is not None:
        span.on_routed(server_url)
    queue_delay = time.time() - in_router_time
    logger.debug("Routing %s to %s (queued %.1f ms)",
                 request_id, server_url, queue_delay * 1e3)

    store_callback = _semantic_cache_store_callback(endpoint_path, payload)
    return await _proxy_stream(
        request, server_url, endpoint_path, body, request_id, policy,
        store_callback, span=span,
    )


def _semantic_cache_store_callback(endpoint_path: str, payload: dict):
    """Build a response-store hook when the semantic cache should learn
    from this request (non-streaming chat completions, gate enabled)."""
    if endpoint_path != "/v1/chat/completions" or payload.get("stream"):
        return None
    from production_stack_tpu.router.experimental.feature_gates import (
        SEMANTIC_CACHE_GATE,
        get_feature_gates,
    )
    if not get_feature_gates().enabled(SEMANTIC_CACHE_GATE):
        return None
    model, messages = payload.get("model"), payload.get("messages")
    if not model or not messages:
        return None

    def store(response_bytes: bytes) -> None:
        from production_stack_tpu.router.experimental.semantic_cache \
            import integration as sc
        try:
            sc.store_in_semantic_cache(
                model, messages, json.loads(response_bytes)
            )
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass

    return store


async def _proxy_stream(request: web.Request, server_url: str,
                        endpoint_path: str, body: bytes, request_id: str,
                        policy, store_callback=None,
                        span=None) -> web.StreamResponse:
    monitor = get_request_stats_monitor()
    session = _client_session(request.app)
    fwd_headers = {
        k: v for k, v in request.headers.items()
        if k.lower() not in _HOP_HEADERS
    }
    fwd_headers["x-request-id"] = request_id

    start_time = time.time()
    monitor.on_request_start(server_url, request_id, start_time)
    completed = False
    response: Optional[web.StreamResponse] = None
    try:
        async with session.request(
            request.method, f"{server_url}{endpoint_path}",
            data=body, headers=fwd_headers,
        ) as backend:
            response = web.StreamResponse(
                status=backend.status,
                headers={
                    k: v for k, v in backend.headers.items()
                    if k.lower() not in _RESPONSE_DROP_HEADERS
                },
            )
            await response.prepare(request)
            first_chunk = True
            cache_buffer = bytearray() if store_callback else None
            async for chunk in backend.content.iter_any():
                if not chunk:
                    continue
                monitor.on_request_response(
                    server_url, request_id, time.time(),
                    is_first_token=first_chunk,
                )
                first_chunk = False
                if span is not None:
                    span.on_chunk()
                if (cache_buffer is not None
                        and len(cache_buffer) < _CACHE_STORE_MAX_BYTES):
                    cache_buffer.extend(chunk)
                await response.write(chunk)
            monitor.on_request_complete(server_url, request_id, time.time())
            completed = True
            await response.write_eof()
            if (cache_buffer is not None and backend.status == 200
                    and len(cache_buffer) < _CACHE_STORE_MAX_BYTES):
                store_callback(bytes(cache_buffer))
            return response
    except Exception as e:
        logger.warning("Proxy error for %s via %s: %s",
                       request_id, server_url, e)
        if response is None:
            return _error(502, f"Upstream engine error: {e}")
        raise
    finally:
        if not completed:
            monitor.on_request_kill(server_url, request_id)
        policy.on_request_complete(server_url)
        if span is not None:
            from production_stack_tpu.router.tracing import (
                get_span_logger,
            )
            span.finish("ok" if completed else "killed")
            sink = get_span_logger()
            if sink is not None:
                sink.emit(span)
