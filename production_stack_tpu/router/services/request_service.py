"""The proxy hot path: pick a backend, stream the response through.

Capability parity with reference
src/vllm_router/services/request_service/request.py (route_general_request
L137, process_request L46): body parse + model filter, rewriter hook,
stats lifecycle events per streamed chunk, fork's ``x-prefill-tokens``
hint header (L199-203), HRA future await (L210-213), cleanup on
disconnect. Implemented on aiohttp: the backend stream is forwarded
chunk-by-chunk into a ``web.StreamResponse`` with no buffering.

Resilience (router/resilience.py) threads through this path: candidate
endpoints are filtered by health + circuit breaker, a pre-first-byte
failure (connect error, timeout, 5xx) fails over to the next-best
endpoint within a retry budget, per-request connect and read-stall
timeouts bound every backend call (reads, not the total exchange — long
generations that keep streaming are never cut off), and exhaustion
returns 503 + ``Retry-After`` when no endpoint is currently admittable
(vs 502 when attempts genuinely failed). A stream that has already sent
its first byte downstream is NEVER retried. Every breaker admission
(``on_attempt``) is balanced by exactly one success / failure / release
in ``_proxy_stream``'s finally, so half-open probe slots cannot leak.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.kvecon.summary import (
    routable_text as kvecon_routable_text,
)
from production_stack_tpu.qos import (
    classify_request,
    DEFAULT_PRIORITY,
    parse_priority,
    PRIORITY_HEADER,
    SPEC_OFF_HEADER,
)
from production_stack_tpu.router.qos import get_router_qos
from production_stack_tpu.router.resilience import get_resilience
from production_stack_tpu.router.service_discovery import (
    get_service_discovery,
)
from production_stack_tpu.router.services.rewriter import (
    get_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    get_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    get_request_stats_monitor,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Fork feature: clients may pre-declare prompt size for admission control.
PREFILL_TOKENS_HEADER = "x-prefill-tokens"

# Hop-by-hop headers never forwarded in either direction.
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding",
    "upgrade", "host", "content-length",
}
# aiohttp auto-decompresses the backend body, so advertising the
# backend's encoding downstream would corrupt every response.
_RESPONSE_DROP_HEADERS = _HOP_HEADERS | {"content-encoding"}

# Cap on response bytes buffered for the semantic cache store path.
_CACHE_STORE_MAX_BYTES = 4 * 1024 * 1024

# Network failure classes eligible for failover when raised before the
# first response byte has been streamed to the client.
_NETWORK_ERRORS = (
    aiohttp.ClientError, asyncio.TimeoutError, TimeoutError,
    ConnectionError, OSError,
)

# Disaggregated two-hop outcomes (docs/disaggregation.md), re-exported
# at the router's /metrics by services/metrics_service.py. "handoffs"
# counts requests served prefill-engine -> decode-engine; "fallbacks"
# counts requests that attempted the disagg path but ended on the
# monolithic one (still served — never dropped).
disagg_handoffs_total = 0
disagg_fallbacks_total = 0

# Crash-recovery accounting (docs/crash_recovery.md), re-exported at
# the router's /metrics by services/metrics_service.py:
# mid-stream failover outcomes and poison-request quarantines.
stream_resumes_by_outcome: dict = {}
poison_quarantines_total = 0
# Request ids observed in mid-stream backend crashes. A request whose
# id has crashed >= POISON_CRASH_LIMIT engines is quarantined: no
# further resume, terminal error — one request must not be able to
# crash-loop the whole pool.
POISON_CRASH_LIMIT = 2
_poison_crashes: dict = {}


def _note_crash(request_id: str) -> int:
    # Bounded: the ledger only matters for requests crashing *now*; a
    # hard reset at the cap beats unbounded growth on a long-lived
    # router.
    if len(_poison_crashes) > 4096:
        _poison_crashes.clear()
    count = _poison_crashes.get(request_id, 0) + 1
    _poison_crashes[request_id] = count
    return count


def _bump_resume(outcome: str) -> None:
    stream_resumes_by_outcome[outcome] = (
        stream_resumes_by_outcome.get(outcome, 0) + 1)


class _SseRelay:
    """SSE-aware forwarding state for one proxied stream.

    Buffers backend bytes and releases only whole ``\\n\\n``-delimited
    events, so a mid-stream backend death never leaves a half-written
    event on the client socket (a resumed stream can then continue
    byte-exactly). Checkpoint comment frames (``: checkpoint {json}``)
    are captured as the latest resume descriptor and stripped — they
    are engine->router control traffic, not client payload. Forwarded
    ``data:`` events have their content text measured so a resume can
    tell the replacement engine exactly how much the client already
    has (docs/crash_recovery.md)."""

    _CKPT_PREFIX = b": checkpoint "
    # In-band cut marker written by a migrate-draining engine right
    # before it severs the connection (docs/fleet.md). The router's
    # dynamic-config migrating list races the engine's cut (the engine
    # closes milliseconds after the drain POST; the config watcher
    # polls), so the marker travels in the stream itself.
    _MIGRATE_MARKER = b": migrating"

    def __init__(self):
        self.buf = bytearray()
        self.descriptor: Optional[dict] = None
        self.delivered_chars = 0
        self.migrating = False

    def feed(self, chunk: bytes) -> bytes:
        self.buf.extend(chunk)
        out = bytearray()
        while True:
            idx = self.buf.find(b"\n\n")
            if idx < 0:
                break
            event = bytes(self.buf[:idx + 2])
            del self.buf[:idx + 2]
            if event.startswith(self._CKPT_PREFIX):
                try:
                    self.descriptor = json.loads(
                        event[len(self._CKPT_PREFIX):].decode())
                except (ValueError, UnicodeDecodeError):
                    pass
                continue
            if event.rstrip(b"\n") == self._MIGRATE_MARKER:
                self.migrating = True
                continue
            self._count(event)
            out.extend(event)
        return bytes(out)

    def _count(self, event: bytes) -> None:
        for line in event.split(b"\n"):
            if not line.startswith(b"data:"):
                continue
            payload = line[5:].strip()
            if not payload or payload == b"[DONE]":
                continue
            try:
                obj = json.loads(payload)
                choice = (obj.get("choices") or [{}])[0]
                text = (choice.get("delta") or {}).get("content")
                if text is None:
                    text = choice.get("text")
                if isinstance(text, str):
                    self.delivered_chars += len(text)
            except (ValueError, AttributeError, IndexError, TypeError):
                pass


class RetryableUpstreamError(Exception):
    """Backend failed — or, for 429, refused — before the first byte
    reached the client: connect error, timeout, 5xx status, or a QoS
    shed (429). Safe to re-route elsewhere. A 429 carries the engine's
    ``Retry-After`` so exhaustion can answer the client honestly, and
    is NOT breaker blame: a saturated engine is healthy, and opening
    breakers on overload turns one hot spot into a routing storm."""

    def __init__(self, reason: str, status: Optional[int] = None,
                 retry_after: Optional[int] = None):
        super().__init__(reason)
        self.status = status
        self.retry_after = retry_after


class _BackendStreamError(Exception):
    """Backend died after bytes were already streamed downstream: the
    breaker hears about it, but the request must not be retried.
    Carries the prepared (partial) client response so the handler can
    end the request without tripping aiohttp's unhandled-error path,
    plus the SSE relay (when the stream was SSE) whose captured
    checkpoint descriptor lets ``_failover_stream`` resume the stream
    on a healthy replacement (docs/crash_recovery.md)."""

    def __init__(self, reason: str, response: web.StreamResponse,
                 relay: "Optional[_SseRelay]" = None,
                 url: Optional[str] = None):
        super().__init__(reason)
        self.response = response
        self.relay = relay
        # The backend that died: _failover_stream classifies a death
        # on a migrate-draining backend as a planned migration.
        self.url = url


class _ClientDisconnectedError(Exception):
    """The downstream client went away: not the backend's fault, so no
    breaker blame and no retry. ``response`` is the prepared client
    response when the disconnect happened mid-write, None when the
    client vanished before the response could even be prepared."""

    def __init__(self, reason: str,
                 response: Optional[web.StreamResponse] = None):
        super().__init__(reason)
        self.response = response


def _client_session(app: web.Application) -> aiohttp.ClientSession:
    return app["backend_session"]


def _request_timeout(mgr) -> aiohttp.ClientTimeout:
    if mgr is not None:
        return mgr.config.client_timeout()
    # Pre-resilience defaults (matches the session built in app.py).
    return aiohttp.ClientTimeout(total=None, sock_connect=30)


def _estimate_prefill_tokens(request: web.Request, body: bytes) -> int:
    hint = request.headers.get(PREFILL_TOKENS_HEADER)
    if hint is not None:
        try:
            return max(0, int(hint))
        except ValueError:
            logger.warning("Bad %s header: %r", PREFILL_TOKENS_HEADER, hint)
    # ~4 bytes/token heuristic when the client does not hint.
    return len(body) // 4


def _routable_prompt_text(payload: dict) -> "str | None":
    """Stable text rendering of the request's prompt for prefix-aware /
    KV-state-aware routing. Canonical implementation lives in kvecon so
    the engine's summary tracker observes the exact same text the
    router hashes (docs/kv_economy.md)."""
    return kvecon_routable_text(payload)


def _error(status: int, message: str,
           err_type: str = "invalid_request_error",
           headers: Optional[dict] = None) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": err_type}},
        status=status, headers=headers,
    )


def _finish_span(span, status: str) -> None:
    if span is None:
        return
    from production_stack_tpu.router.tracing import get_span_logger
    span.finish(status)
    sink = get_span_logger()
    if sink is not None:
        sink.emit(span)


def _observe_slo(app: web.Application, slo_ctx: Optional[dict],
                 server_url: str, request_id: str, span,
                 first_chunk_ts: Optional[float], n_chunks: int,
                 end_ts: float) -> None:
    """Classify one completed request against the SLO ledger
    (docs/observability.md). A breach schedules exemplar capture: the
    engine's flight-recorder timeline is pulled and the stitched
    router+engine waterfall archived, so the request that moved the
    burn-rate gauge is retrievable at GET /debug/slow."""
    from production_stack_tpu import obs
    ledger = obs.get_slo_ledger()
    if ledger is None or slo_ctx is None:
        return
    arrival = slo_ctx["arrival"]
    ttft = (first_chunk_ts - arrival
            if first_chunk_ts is not None else None)
    itl = ((end_ts - first_chunk_ts) / (n_chunks - 1)
           if first_chunk_ts is not None and n_chunks > 1 else None)
    breaches = ledger.observe(
        slo_ctx["class"], slo_ctx["model"], server_url,
        ttft_s=ttft, itl_s=itl, e2e_s=end_ts - arrival)
    if not breaches or obs.get_slow_archive() is None:
        return
    if span is not None:
        router_span = json.loads(span.to_json())
    else:
        # Span logging off: synthesize the router span from the
        # timings at hand so the archived waterfall still renders.
        def ms(t):
            return (None if t is None
                    else round((t - arrival) * 1e3, 2))
        router_span = {
            "span": "request", "request_id": request_id,
            "model": slo_ctx["model"],
            "path": None,
            "priority_class": slo_ctx["class"],
            "tenant": slo_ctx["tenant"],
            "backend": server_url,
            "arrival_ts": round(arrival, 6),
            "queue_delay_ms": None,
            "ttft_ms": ms(first_chunk_ts),
            "latency_ms": ms(end_ts),
            "chunks": n_chunks, "status": "ok",
        }
    entry = {"request_id": request_id, "class": slo_ctx["class"],
             "model": slo_ctx["model"], "server": server_url,
             "breach": breaches}
    asyncio.create_task(_capture_slow_exemplar(
        app, server_url, request_id, router_span, entry))


async def _capture_slow_exemplar(app: web.Application, server_url: str,
                                 request_id: str, router_span: dict,
                                 entry: dict) -> None:
    """Best-effort: fetch the engine flight-recorder timeline for one
    breaching request and archive the stitched waterfall. Never raises
    — the ledger already counted the breach; the exemplar is gravy."""
    from production_stack_tpu import obs
    from production_stack_tpu.traceview import render_waterfall
    archive = obs.get_slow_archive()
    if archive is None:
        return
    engine_spans: list = []
    try:
        session = _client_session(app)
        async with session.get(
            f"{server_url}/debug/trace/{request_id}",
            timeout=aiohttp.ClientTimeout(total=5),
        ) as resp:
            if resp.status == 200:
                payload = await resp.json()
                engine_spans = [s for s in payload.get("spans", [])
                                if isinstance(s, dict)]
    except asyncio.CancelledError:
        # The capture task raced the replica's exit (a drain tore the
        # session down): the router-side half still archives below.
        logger.debug("Slow-exemplar trace fetch from %s for %s "
                     "cancelled mid-pull", server_url, request_id)
    except Exception as e:
        logger.debug("Slow-exemplar trace fetch from %s for %s "
                     "failed: %s", server_url, request_id, e)
    spans = [router_span] + engine_spans
    try:
        entry["spans"] = spans
        entry["waterfall"] = render_waterfall(spans, request_id)
    except Exception as e:
        # Malformed engine spans must not cost the exemplar: fall back
        # to the router-side waterfall alone.
        logger.debug("Slow-exemplar waterfall stitch for %s failed "
                     "(%s); archiving router span only", request_id, e)
        entry["spans"] = [router_span]
        entry["waterfall"] = render_waterfall([router_span], request_id)
    archive.add(entry)


def _disagg_eligible(payload: dict) -> bool:
    """Conservative gate for the two-hop disagg path: only plain
    single-choice generate requests. Anything exotic (multi-choice,
    logprobs, structured output, completion echo/suffix) stays on the
    monolithic path; the prefill engine applies its own finer checks
    (guided decoding, LoRA) and answers 400, which also falls back."""
    if (payload.get("n") or 1) != 1:
        return False
    if payload.get("best_of") not in (None, 1):
        return False
    for key in ("echo", "suffix", "logprobs", "top_logprobs",
                "response_format"):
        if payload.get(key):
            return False
    return True


async def route_general_request(request: web.Request,
                                endpoint_path: str) -> web.StreamResponse:
    """Proxy one OpenAI-API request to a chosen engine, streaming back."""
    from production_stack_tpu.router.routing.logic import (
        canary_split,
        filter_by_role,
        get_routing_logic,
        usable_endpoints,
    )
    global disagg_fallbacks_total

    in_router_time = time.time()
    request_id = request.headers.get("x-request-id") or str(uuid.uuid4())
    body = await request.read()
    try:
        payload = json.loads(body) if body else {}
    except json.JSONDecodeError:
        return _error(400, "Request body is not valid JSON")
    model = payload.get("model")
    if not model:
        return _error(400, "Request body must contain a 'model' field")

    # Observability attribution (docs/observability.md): priority class
    # and tenant are stamped on every request — span, request stats and
    # SLO ledger — whether or not the QoS fairness layer is on.
    priority_class, tenant = classify_request(request.headers,
                                              request.remote)
    slo_ctx = {"class": priority_class, "tenant": tenant,
               "model": model, "arrival": in_router_time}

    # Router QoS (docs/qos.md): tenant identification, per-tenant rate
    # limiting, and the degradation ladder — applied before any backend
    # work. Shed answers are honest 429 + Retry-After; degrade clamps
    # max_tokens and marks the request spec-off for the engine.
    qos = get_router_qos()
    qos_verdict = None
    qos_headers: Optional[dict] = None
    if qos is not None and endpoint_path in ("/v1/chat/completions",
                                             "/v1/completions"):
        raw_priority = request.headers.get(PRIORITY_HEADER)
        try:
            priority = (parse_priority(raw_priority)
                        if raw_priority is not None else DEFAULT_PRIORITY)
        except ValueError as e:
            return _error(400, str(e))
        tenant = qos.tenant_of(request.headers, request.remote)
        qos_verdict = qos.decide(tenant, priority)
        if qos_verdict.action == "shed":
            return _error(
                429,
                f"tenant over rate limit; retry after "
                f"{qos_verdict.retry_after_s}s",
                err_type="overloaded_error",
                headers={"Retry-After": str(qos_verdict.retry_after_s)},
            )
        if qos_verdict.action == "degrade":
            clamp = qos_verdict.clamp_max_tokens
            changed = False
            for key in ("max_tokens", "max_completion_tokens"):
                current = payload.get(key)
                if isinstance(current, int) and current > clamp:
                    payload[key] = clamp
                    changed = True
            if ("max_tokens" not in payload
                    and "max_completion_tokens" not in payload):
                # Unset means the engine applies the OpenAI default
                # (256), which the ladder must still clamp.
                payload["max_tokens"] = clamp
                changed = True
            if changed:
                body = json.dumps(payload).encode()
            if qos_verdict.spec_off:
                qos_headers = {SPEC_OFF_HEADER: "1"}

    rewriter = get_request_rewriter()
    rewritten = rewriter.rewrite_request(body, model, endpoint_path)
    if rewritten is not body:
        body = rewritten

    discovery = get_service_discovery()
    # Unknown model (404) is judged against every *discovered* endpoint;
    # "known but currently unservable" (503 below) against healthy ones.
    serving = [
        ep for ep in discovery.get_endpoint_info(include_unhealthy=True)
        if ep.serves_model(model)
    ]
    if not serving:
        return _error(
            404, f"Model {model} not found on any serving engine",
            err_type="not_found_error",
        )
    healthy = [
        ep for ep in discovery.get_endpoint_info()
        if ep.serves_model(model)
    ]

    mgr = get_resilience()
    monitor = get_request_stats_monitor()
    monitor.on_request_arrival(request_id, in_router_time,
                               priority_class=priority_class,
                               tenant=tenant)

    from production_stack_tpu.router.tracing import start_span
    span = start_span(request_id, model, endpoint_path,
                      priority_class=priority_class, tenant=tenant)

    num_prefill_tokens = _estimate_prefill_tokens(request, body)
    policy = get_routing_logic()
    prompt_text = (_routable_prompt_text(payload)
                   if policy.uses_prompt_text else None)
    store_callback = _semantic_cache_store_callback(endpoint_path, payload)

    # Disaggregated dispatch: with both a prefill-role and a decode-role
    # pool discovered, eligible generate requests take the two-hop path
    # (prefill engine computes KV + first token, decode engine streams
    # the rest). Any failure there falls through to the monolithic loop
    # below — degraded to a recompute, never dropped.
    if endpoint_path in ("/v1/chat/completions", "/v1/completions"):
        prefill_pool = filter_by_role(healthy, "prefill")
        decode_pool = filter_by_role(healthy, "decode")
        if prefill_pool and decode_pool and _disagg_eligible(payload):
            response = await _route_disagg(
                request, body, payload, request_id,
                prefill_pool, decode_pool, num_prefill_tokens,
                span=span, mgr=mgr, slo_ctx=slo_ctx,
            )
            if response is not None:
                return response
            disagg_fallbacks_total += 1
            logger.warning(
                "Disagg dispatch for %s fell back to the monolithic "
                "path", request_id)

    max_attempts = 1 + (mgr.config.max_retries if mgr is not None else 0)

    async def _dispatch() -> web.StreamResponse:
        tried: set = set()
        last_error: Optional[RetryableUpstreamError] = None
        attempts = 0
        # QoS 429 accounting (docs/qos.md): a saturated engine's 429 is
        # retried on another backend, but when EVERY attempt came back
        # 429 the fleet is saturated — answer 429 with the largest
        # engine-provided Retry-After rather than hammering backends
        # (no failover storm) or lying with a 5xx.
        failed_attempts = 0
        saturated_attempts = 0
        throttle_hints: list = []
        while attempts < max_attempts:
            candidates = usable_endpoints(healthy, exclude=tried)
            if not candidates:
                break
            if attempts == 0:
                # Canary traffic weighting applies to the initial
                # dispatch only; failover keeps the whole pool.
                candidates = canary_split(candidates)
            engine_stats = get_engine_stats_scraper().get_engine_stats()
            request_stats = monitor.get_request_stats(time.time())
            choice = policy.route_request(
                candidates, engine_stats, request_stats, request.headers,
                request_id, num_prefill_tokens, prompt_text=prompt_text,
            )
            if hasattr(choice, "__await__"):
                try:
                    server_url = await choice
                except Exception as e:  # admission rejected (can never fit)
                    monitor.on_request_kill("<unrouted>", request_id)
                    _finish_span(span, "rejected")
                    return _error(429, f"Request not admitted: {e}")
            else:
                server_url = choice
            if mgr is not None and not mgr.on_attempt(server_url):
                # Lost the half-open probe-slot race between the
                # usable_endpoints filter and dispatch (a concurrent
                # request took the probe): skip this endpoint without
                # burning retry budget.
                monitor.on_request_kill(server_url, request_id)
                policy.on_request_complete(server_url)
                tried.add(server_url)
                continue
            if span is not None:
                span.on_routed(server_url)
            if attempts:
                logger.info("Failover attempt %d: re-routing %s to %s",
                            attempts, request_id, server_url)
            queue_delay = time.time() - in_router_time
            logger.debug("Routing %s to %s (queued %.1f ms)",
                         request_id, server_url, queue_delay * 1e3)
            attempts += 1
            try:
                response = await _proxy_stream(
                    request, server_url, endpoint_path, body, request_id,
                    policy, store_callback, span=span, mgr=mgr,
                    extra_headers=qos_headers, slo_ctx=slo_ctx,
                )
            except RetryableUpstreamError as e:
                last_error = e
                tried.add(server_url)
                failed_attempts += 1
                if e.status == 429:
                    saturated_attempts += 1
                    throttle_hints.append(max(1, int(e.retry_after or 1)))
                if mgr is not None:
                    mgr.retries_total += 1
                logger.warning(
                    "Pre-stream failure from %s for %s (%s); %s",
                    server_url, request_id, e,
                    "failing over" if attempts < max_attempts
                    else "retry budget exhausted")
                continue
            except _BackendStreamError as e:
                # Bytes already reached the client: the attempt cannot
                # be re-routed, but a checkpointed SSE stream can be
                # RESUMED on a healthy replacement; otherwise the
                # stream ends with a terminal in-band error event —
                # never a silent truncation (docs/crash_recovery.md).
                return await _failover_stream(
                    request, e, request_id, healthy,
                    tried | {server_url}, mgr, model=model)
            except _ClientDisconnectedError as e:
                # Routine client disconnect: nothing to send and nobody
                # to send it to — end quietly instead of surfacing a 500.
                if e.response is not None:
                    return e.response
                return web.Response(status=499,
                                    reason="Client Closed Request")
            if mgr is not None and attempts > 1:
                mgr.failovers_total += 1
            return response

        # Retry budget or candidate pool exhausted.
        monitor.on_request_kill("<unrouted>", request_id)
        if failed_attempts and failed_attempts == saturated_attempts:
            # Every attempted engine said 429: the fleet is saturated,
            # not broken. Relay the longest backoff any engine asked
            # for so clients respect it instead of re-storming.
            _finish_span(span, "rejected")
            hint = max(throttle_hints) if throttle_hints else 1
            return _error(
                429,
                f"all {len(tried)} engine(s) serving model {model} "
                f"are saturated; retry after {hint}s",
                err_type="overloaded_error",
                headers={"Retry-After": str(hint)},
            )
        _finish_span(span, "error")
        if not usable_endpoints(healthy):
            # Every serving endpoint is unhealthy or breaker-open: shed
            # with a hint for when a probe slot next opens, so clients
            # and autoscalers can tell "no capacity" from "broken
            # upstream".
            if mgr is not None:
                mgr.shed_requests_total += 1
            hint = (mgr.retry_after_hint(
                        [ep.url for ep in healthy or serving])
                    if mgr is not None else 1)
            return _error(
                503,
                f"No healthy endpoint currently serves model {model}",
                err_type="service_unavailable_error",
                headers={"Retry-After": str(hint)},
            )
        return _error(
            502,
            f"Upstream engine error after {len(tried)} attempt(s): "
            f"{last_error}",
            err_type="upstream_error",
        )

    # Weighted-fair admission (docs/qos.md): with --qos-max-concurrency
    # set, the whole dispatch (including the stream) holds one gate
    # slot; waiters dequeue stride-fair across tenants.
    gate = qos.gate if (qos is not None
                        and qos_verdict is not None) else None
    if gate is None:
        return await _dispatch()
    await gate.acquire(qos_verdict.tenant, qos_verdict.priority)
    try:
        return await _dispatch()
    finally:
        gate.release()


async def _route_disagg(request: web.Request, body: bytes, payload: dict,
                        request_id: str, prefill_pool, decode_pool,
                        num_prefill_tokens: int, span=None,
                        mgr=None,
                        slo_ctx=None) -> Optional[web.StreamResponse]:
    """Two-hop disaggregated dispatch (docs/disaggregation.md).

    Hop 1 POSTs the original body to a prefill-role engine's
    ``/v1/disagg/prefill`` and collects the handoff descriptor (KV
    already shipped to the offload tier, first token sampled). Hop 2
    submits the descriptor to a decode-role engine's
    ``/v1/disagg/handoff`` and streams its response to the client.

    Resilience mirrors the monolithic loop: each hop retries across
    its pool within the retry budget, breaker admissions are balanced,
    and any unrecoverable outcome — empty pool, exhausted budget, a
    409 (descriptor KV not restorable on this decode pool: kv_dtype
    mismatch, retrying elsewhere in the pool is pointless) — returns
    None so the caller serves the request monolithically instead."""
    from production_stack_tpu.router.routing.logic import (
        get_routing_logic,
        usable_endpoints,
    )
    global disagg_handoffs_total
    policy = get_routing_logic()
    monitor = get_request_stats_monitor()
    session = _client_session(request.app)
    max_attempts = 1 + (mgr.config.max_retries if mgr is not None else 0)

    def least_loaded(candidates) -> str:
        stats = monitor.get_request_stats(time.time())

        def load(url: str) -> int:
            stat = stats.get(url)
            if stat is None:
                return 0
            return stat.in_prefill_requests + stat.in_decoding_requests

        return min(candidates, key=lambda ep: (load(ep.url), ep.url)).url

    descriptor = None
    tried: set = set()
    attempts = 0
    while attempts < max_attempts and descriptor is None:
        candidates = usable_endpoints(prefill_pool, exclude=tried)
        if not candidates:
            break
        url = least_loaded(candidates)
        tried.add(url)
        attempts += 1
        if mgr is not None and not mgr.on_attempt(url):
            continue
        # True = backend's fault, False = clean answer, None = no
        # verdict; balances the on_attempt admission exactly once.
        blame = None
        try:
            async with session.post(
                f"{url}/v1/disagg/prefill", data=body,
                headers={"content-type": "application/json",
                         "x-request-id": request_id},
                timeout=_request_timeout(mgr),
            ) as resp:
                if resp.status == 200:
                    blame = False
                    desc = (await resp.json()).get("descriptor")
                    if not isinstance(desc, dict):
                        return None
                    descriptor = desc
                elif resp.status >= 500:
                    blame = True  # includes 503 queue-full: next pod
                else:
                    # 4xx: the backend is healthy but this request (or
                    # an engine without the endpoint, 404) cannot take
                    # the disagg path — monolithic immediately.
                    blame = False
                    return None
        except _NETWORK_ERRORS as e:
            blame = True
            logger.warning("Disagg prefill hop to %s failed for %s: %s",
                           url, request_id, e)
        finally:
            if mgr is not None:
                if blame is True:
                    mgr.record_failure(url)
                elif blame is False:
                    mgr.record_success(url)
                else:
                    mgr.release_attempt(url)
        if descriptor is None and mgr is not None:
            mgr.retries_total += 1
    if descriptor is None:
        return None
    if span is not None:
        # Hop fields, not on_routed: the prefill->decode transition
        # is two-hop dispatch, never a failover retry.
        span.on_prefill_routed(url)

    handoff_body = json.dumps({
        "descriptor": descriptor,
        "stream": bool(payload.get("stream")),
    }).encode()
    tried = set()
    attempts = 0
    while attempts < max_attempts:
        candidates = usable_endpoints(decode_pool, exclude=tried)
        if not candidates:
            break
        server_url = least_loaded(candidates)
        attempts += 1
        monitor.on_request_routed(server_url, request_id,
                                  num_prefill_tokens)
        if mgr is not None and not mgr.on_attempt(server_url):
            monitor.on_request_kill(server_url, request_id)
            policy.on_request_complete(server_url)
            tried.add(server_url)
            continue
        if span is not None:
            span.on_decode_routed(server_url)
        try:
            response = await _proxy_stream(
                request, server_url, "/v1/disagg/handoff", handoff_body,
                request_id, policy, span=span, mgr=mgr,
                reject_statuses=(409,), slo_ctx=slo_ctx,
            )
        except RetryableUpstreamError as e:
            tried.add(server_url)
            if mgr is not None:
                mgr.retries_total += 1
            if e.status == 409:
                logger.warning(
                    "Decode pool cannot restore handoff KV for %s "
                    "(%s); falling back to monolithic", request_id, e)
                return None
            logger.warning(
                "Disagg handoff hop to %s failed for %s (%s); %s",
                server_url, request_id, e,
                "trying next decode backend" if attempts < max_attempts
                else "decode retry budget exhausted")
            continue
        except _BackendStreamError as e:
            # Bytes already reached the client: resume on another
            # decode engine when a checkpoint was captured, else end
            # with a terminal error event — same as the monolithic
            # path.
            return await _failover_stream(
                request, e, request_id, decode_pool,
                tried | {server_url}, mgr,
                model=payload.get("model"))
        except _ClientDisconnectedError as e:
            if e.response is not None:
                return e.response
            return web.Response(status=499,
                                reason="Client Closed Request")
        disagg_handoffs_total += 1
        return response
    return None


async def _terminal_sse_error(request: web.Request,
                              response: web.StreamResponse,
                              relay: "Optional[_SseRelay]",
                              message: str) -> web.StreamResponse:
    """End an unrecoverable mid-stream failure honestly. For an SSE
    stream: a terminal in-band ``error`` event plus ``[DONE]``, so the
    client sees an explicit failure instead of a silently truncated
    stream it could mistake for completion. For non-SSE bodies there
    is no in-band channel — abort the connection so the truncation is
    at least detectable."""
    if relay is None:
        if request.transport is not None:
            request.transport.close()
        return response
    try:
        payload = {"error": {"message": message,
                             "type": "upstream_error"}}
        await response.write(
            f"data: {json.dumps(payload)}\n\n".encode())
        await response.write(b"data: [DONE]\n\n")
        await response.write_eof()
    except Exception:
        pass
    return response


async def _pipe_resume(request: web.Request, server_url: str,
                       relay: "_SseRelay",
                       response: web.StreamResponse,
                       request_id: str, mgr) -> None:
    """POST the captured checkpoint descriptor to ``server_url``'s
    ``/v1/resume`` and pipe the replacement SSE stream into the
    already-prepared client response. The relay keeps tracking
    checkpoint frames and delivered chars, so a second crash on the
    replacement resumes again. Raises ``RetryableUpstreamError`` when
    the replacement refused the resume (try another candidate),
    ``_BackendStreamError`` when it too died mid-stream, and
    ``_ClientDisconnectedError`` when the downstream client went
    away."""
    session = _client_session(request.app)
    body = json.dumps({
        "descriptor": relay.descriptor,
        "delivered_text_chars": relay.delivered_chars,
        "stream": True,
    }).encode()
    # Any half-event from the dead backend is re-emitted whole by the
    # replacement (delivered_chars only counts complete events).
    relay.buf.clear()
    # Fresh leg, fresh verdict: only this leg's own migrate marker may
    # classify its death as a planned migration.
    relay.migrating = False
    blame: Optional[bool] = None
    try:
        async with session.post(
            f"{server_url}/v1/resume", data=body,
            headers={"content-type": "application/json",
                     "x-request-id": request_id},
            timeout=_request_timeout(mgr),
        ) as backend:
            if backend.status != 200:
                blame = backend.status >= 500
                raise RetryableUpstreamError(
                    f"resume rejected with {backend.status}",
                    status=backend.status,
                )
            stream = backend.content.iter_any()
            while True:
                try:
                    chunk = await stream.__anext__()
                except StopAsyncIteration:
                    break
                except _NETWORK_ERRORS as e:
                    blame = True
                    raise _BackendStreamError(
                        f"{type(e).__name__}: {e}", response,
                        relay=relay, url=server_url) from e
                out = relay.feed(chunk)
                if not out:
                    continue
                try:
                    await response.write(out)
                except _NETWORK_ERRORS as e:
                    raise _ClientDisconnectedError(
                        f"{type(e).__name__}: {e}", response) from e
            try:
                if relay.buf:
                    await response.write(bytes(relay.buf))
                    relay.buf.clear()
                await response.write_eof()
            except _NETWORK_ERRORS as e:
                raise _ClientDisconnectedError(
                    f"{type(e).__name__}: {e}", response) from e
            blame = False
    except (RetryableUpstreamError, _BackendStreamError,
            _ClientDisconnectedError):
        raise
    except _NETWORK_ERRORS as e:
        blame = True
        raise RetryableUpstreamError(
            f"{type(e).__name__}: {e}") from e
    finally:
        if mgr is not None:
            if blame is True:
                mgr.record_failure(server_url)
            elif blame is False:
                mgr.record_success(server_url)
            else:
                mgr.release_attempt(server_url)


async def _failover_stream(request: web.Request,
                           err: _BackendStreamError, request_id: str,
                           pool, exclude: set,
                           mgr, model: Optional[str] = None
                           ) -> web.StreamResponse:
    """Mid-stream failover (docs/crash_recovery.md): the backend died
    after bytes reached the client. When the relay captured a
    checkpoint descriptor, resume the stream byte-exactly on a healthy
    replacement (repeating across crashes); a request id seen in
    ``POISON_CRASH_LIMIT`` crashes is quarantined instead — one poison
    request must not take down the whole pool. Every unrecoverable
    path ends the stream with a terminal in-band error event."""
    from production_stack_tpu.router.routing.logic import (
        get_migrating_urls,
        usable_endpoints,
    )
    global poison_quarantines_total
    response, relay = err.response, err.relay
    exclude = set(exclude)
    roles = {getattr(ep, "role", "both") for ep in pool}

    def live_pool():
        """Resume candidates must come from *live* discovery, not the
        dispatch-time snapshot: replicas added after dispatch — exactly
        the new-revision replicas a migrate-mode rollout drains onto
        (docs/fleet.md) — are invisible to the snapshot. Falls back to
        the snapshot when discovery is empty or unavailable."""
        try:
            live = [ep for ep in get_service_discovery().get_endpoint_info()
                    if getattr(ep, "role", "both") in roles
                    and (model is None or ep.serves_model(model))]
        except Exception:
            return pool
        return live or pool

    try:
        while True:
            # A stream cut by a migrate-draining backend is a planned
            # migration (fleet rollouts, docs/fleet.md): no crash blame
            # toward poison quarantine, and the resume lands under the
            # "migrated" outcome. The in-band marker from the engine's
            # drain cut is authoritative; the dynamic-config migrating
            # list backs it up for engines that predate the marker.
            migration = ((relay is not None
                          and getattr(relay, "migrating", False))
                         or (err.url is not None
                             and err.url in get_migrating_urls()))
            if migration:
                crashes = _poison_crashes.get(request_id, 0)
            else:
                crashes = _note_crash(request_id)
            if relay is None or relay.descriptor is None:
                _bump_resume("no_checkpoint")
                return await _terminal_sse_error(
                    request, response, relay,
                    "upstream engine died mid-stream and no resume "
                    "checkpoint was available")
            if crashes >= POISON_CRASH_LIMIT:
                poison_quarantines_total += 1
                _bump_resume("quarantined")
                logger.error(
                    "Quarantining poison request %s after %d engine "
                    "crashes; not resuming again", request_id, crashes)
                return await _terminal_sse_error(
                    request, response, relay,
                    f"request quarantined after {crashes} engine "
                    f"crashes")
            while True:
                candidates = usable_endpoints(live_pool(),
                                              exclude=exclude)
                if not candidates:
                    _bump_resume("exhausted")
                    return await _terminal_sse_error(
                        request, response, relay,
                        "upstream engine died mid-stream and no "
                        "healthy replacement accepted the resume")
                # Prefer backends that are not themselves mid-migrate:
                # a migrated stream must land on a replica that will
                # outlive it.
                migrating = get_migrating_urls()
                candidates = sorted(
                    candidates, key=lambda ep: ep.url in migrating)
                server_url = candidates[0].url
                if mgr is not None and not mgr.on_attempt(server_url):
                    exclude.add(server_url)
                    continue
                try:
                    await _pipe_resume(request, server_url, relay,
                                       response, request_id, mgr)
                except RetryableUpstreamError as e:
                    logger.warning(
                        "Resume of %s on %s refused (%s); trying "
                        "next candidate", request_id, server_url, e)
                    exclude.add(server_url)
                    continue
                except _BackendStreamError as e:
                    logger.warning(
                        "Resumed stream for %s died again on %s (%s)",
                        request_id, server_url, e)
                    exclude.add(server_url)
                    err = e
                    break  # outer loop: record the new crash
                _bump_resume("migrated" if migration else "resumed")
                if mgr is not None:
                    mgr.failovers_total += 1
                logger.info("%s stream %s on %s (%d chars "
                            "already delivered)",
                            "Migrated" if migration else "Resumed",
                            request_id, server_url,
                            relay.delivered_chars)
                return response
    except _ClientDisconnectedError:
        return response


def _semantic_cache_store_callback(endpoint_path: str, payload: dict):
    """Build a response-store hook when the semantic cache should learn
    from this request (non-streaming chat completions, gate enabled)."""
    if endpoint_path != "/v1/chat/completions" or payload.get("stream"):
        return None
    from production_stack_tpu.router.experimental.feature_gates import (
        SEMANTIC_CACHE_GATE,
        get_feature_gates,
    )
    if not get_feature_gates().enabled(SEMANTIC_CACHE_GATE):
        return None
    model, messages = payload.get("model"), payload.get("messages")
    if not model or not messages:
        return None

    def store(response_bytes: bytes) -> None:
        from production_stack_tpu.router.experimental.semantic_cache \
            import integration as sc
        try:
            sc.store_in_semantic_cache(
                model, messages, json.loads(response_bytes)
            )
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass

    return store


async def _proxy_stream(request: web.Request, server_url: str,
                        endpoint_path: str, body: bytes, request_id: str,
                        policy, store_callback=None,
                        span=None, mgr=None,
                        reject_statuses: tuple = (),
                        extra_headers: Optional[dict] = None,
                        slo_ctx: Optional[dict] = None
                        ) -> web.StreamResponse:
    """One proxy attempt. Raises ``RetryableUpstreamError`` when the
    backend failed before anything was streamed to the client; once the
    client response is prepared, failures are terminal.

    The caller has already admitted this attempt via ``mgr.on_attempt``;
    the ``finally`` below balances that admission with exactly one
    breaker verdict — success, failure, or (when the request ended with
    no verdict on the backend: client disconnect, cancellation, unknown
    error) a slot release — so a half-open probe can never leak."""
    monitor = get_request_stats_monitor()
    session = _client_session(request.app)
    fwd_headers = {
        k: v for k, v in request.headers.items()
        if k.lower() not in _HOP_HEADERS
    }
    fwd_headers["x-request-id"] = request_id
    if extra_headers:
        fwd_headers.update(extra_headers)

    start_time = time.time()
    monitor.on_request_start(server_url, request_id, start_time)
    completed = False
    prepared = False
    # True = backend's fault, False = backend succeeded, None = no
    # verdict (release the breaker admission without an outcome).
    blame: Optional[bool] = None
    response: Optional[web.StreamResponse] = None
    try:
        async with session.request(
            request.method, f"{server_url}{endpoint_path}",
            data=body, headers=fwd_headers,
            timeout=_request_timeout(mgr),
        ) as backend:
            if backend.status >= 500:
                raise RetryableUpstreamError(
                    f"upstream returned {backend.status}",
                    status=backend.status,
                )
            if backend.status == 429:
                # QoS shed (docs/qos.md): another engine may have
                # room, so fail over — but carry the engine's
                # Retry-After so all-saturated exhaustion can relay
                # the honest backoff. No breaker blame (see
                # RetryableUpstreamError).
                try:
                    retry_after = int(
                        backend.headers.get("Retry-After", "1"))
                except ValueError:
                    retry_after = 1
                raise RetryableUpstreamError(
                    "upstream saturated (429)", status=429,
                    retry_after=retry_after,
                )
            if backend.status in reject_statuses:
                # Caller-designated rejection statuses (disagg handoff
                # 409) surface as pre-stream errors instead of being
                # proxied: the caller decides retry vs fallback.
                raise RetryableUpstreamError(
                    f"upstream rejected with {backend.status}",
                    status=backend.status,
                )
            response = web.StreamResponse(
                status=backend.status,
                headers={
                    k: v for k, v in backend.headers.items()
                    if k.lower() not in _RESPONSE_DROP_HEADERS
                },
            )
            try:
                await response.prepare(request)
            except _NETWORK_ERRORS as e:
                raise _ClientDisconnectedError(
                    f"{type(e).__name__}: {e}") from e
            prepared = True
            first_chunk = True
            first_chunk_ts: Optional[float] = None
            n_chunks = 0
            cache_buffer = bytearray() if store_callback else None
            # SSE streams go through the relay: whole events only,
            # checkpoint frames captured for mid-stream failover
            # (docs/crash_recovery.md).
            relay = (_SseRelay() if backend.headers.get(
                "Content-Type", "").startswith("text/event-stream")
                else None)
            stream = backend.content.iter_any()
            while True:
                try:
                    chunk = await stream.__anext__()
                except StopAsyncIteration:
                    break
                except _NETWORK_ERRORS as e:
                    # Mid-stream death: bytes are already downstream,
                    # so plain retry is impossible — blame the backend
                    # and hand the relay up for a checkpoint resume.
                    raise _BackendStreamError(
                        f"{type(e).__name__}: {e}", response,
                        relay=relay, url=server_url) from e
                if relay is not None:
                    chunk = relay.feed(chunk)
                if not chunk:
                    continue
                chunk_ts = time.time()
                monitor.on_request_response(
                    server_url, request_id, chunk_ts,
                    is_first_token=first_chunk,
                )
                if first_chunk:
                    first_chunk_ts = chunk_ts
                first_chunk = False
                n_chunks += 1
                if span is not None:
                    span.on_chunk()
                if (cache_buffer is not None
                        and len(cache_buffer) < _CACHE_STORE_MAX_BYTES):
                    cache_buffer.extend(chunk)
                await response.write(chunk)
            if relay is not None and relay.buf:
                # A backend that ended without the final blank line:
                # flush the remainder so no bytes are lost.
                await response.write(bytes(relay.buf))
                relay.buf.clear()
            end_ts = time.time()
            monitor.on_request_complete(server_url, request_id, end_ts)
            completed = True
            await response.write_eof()
            blame = False
            if (cache_buffer is not None and backend.status == 200
                    and len(cache_buffer) < _CACHE_STORE_MAX_BYTES):
                store_callback(bytes(cache_buffer))
            _finish_span(span, "ok")
            _observe_slo(request.app, slo_ctx, server_url, request_id,
                         span, first_chunk_ts, n_chunks, end_ts)
            return response
    except RetryableUpstreamError as e:
        # A 429 is a healthy engine answering fast that it is full —
        # success for breaker purposes. Blaming it would open breakers
        # fleet-wide exactly when the fleet is saturated, converting
        # overload into an outage.
        blame = e.status != 429
        raise
    except _BackendStreamError as e:
        blame = True
        logger.warning("Backend stream from %s died mid-response for "
                       "%s: %s", server_url, request_id, e)
        _finish_span(span, "killed")
        raise
    except _ClientDisconnectedError as e:
        logger.info("Client gone before response start for %s via %s: %s",
                    request_id, server_url, e)
        _finish_span(span, "killed")
        raise
    except _NETWORK_ERRORS as e:
        if not prepared:
            # Connect error / timeout before the client saw anything.
            blame = True
            raise RetryableUpstreamError(
                f"{type(e).__name__}: {e}") from e
        # Client-side write failure (disconnect): not the backend's
        # fault — no breaker blame, no retry.
        logger.info("Client connection lost for %s via %s: %s",
                    request_id, server_url, e)
        _finish_span(span, "killed")
        raise _ClientDisconnectedError(
            f"{type(e).__name__}: {e}", response) from e
    except Exception as e:
        logger.warning("Proxy error for %s via %s: %s",
                       request_id, server_url, e)
        _finish_span(span, "error")
        if response is None:
            return _error(502, f"Upstream engine error: {e}",
                          err_type="upstream_error")
        raise
    finally:
        if mgr is not None:
            # Exactly one verdict per admission — runs on every exit,
            # including cancellation when the client goes away.
            if blame is True:
                mgr.record_failure(server_url)
            elif blame is False:
                mgr.record_success(server_url)
            else:
                mgr.release_attempt(server_url)
        if not completed:
            monitor.on_request_kill(server_url, request_id)
        policy.on_request_complete(server_url)
