"""SQLite-backed local batch processor.

Executes OpenAI-format batch jobs on this router without external
infrastructure: batch metadata persists in a local SQLite database
(surviving router restarts), and a background worker claims pending
batches, runs each JSONL input line as a request against a discovered
engine endpoint, and writes the OpenAI-format output/error files back
through the files Storage layer.

SQLite has no async driver in this environment, so all database access
is funneled through ``asyncio.to_thread`` onto a single shared
connection serialized by a lock — the event loop never blocks on disk,
and writer concurrency is a non-issue by construction.
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import threading
import time
import uuid
from typing import List, Optional

import aiohttp

from production_stack_tpu.router.services.batch.batch import (
    BatchInfo,
    BatchStatus,
)
from production_stack_tpu.router.services.batch.processor import (
    BatchProcessor,
)
from production_stack_tpu.router.services.files.storage import Storage
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS batches (
    id TEXT PRIMARY KEY,
    user_id TEXT NOT NULL,
    payload TEXT NOT NULL
);
"""


class LocalBatchProcessor(BatchProcessor):
    def __init__(self, storage: Storage,
                 db_path: str = "/tmp/pstpu_batch.db",
                 poll_interval_s: float = 2.0):
        super().__init__(storage)
        self.db_path = db_path
        self.poll_interval_s = poll_interval_s
        self._conn: Optional[sqlite3.Connection] = None
        self._db_lock = threading.Lock()
        self._worker: Optional[asyncio.Task] = None

    # ---- persistence ------------------------------------------------------

    def _db(self) -> sqlite3.Connection:
        if self._conn is None:
            self._conn = sqlite3.connect(
                self.db_path, check_same_thread=False
            )
            self._conn.execute(_SCHEMA)
            self._conn.commit()
        return self._conn

    def _store_sync(self, user_id: str, info: BatchInfo) -> None:
        with self._db_lock:
            db = self._db()
            db.execute(
                "INSERT OR REPLACE INTO batches (id, user_id, payload) "
                "VALUES (?, ?, ?)",
                (info.id, user_id, json.dumps(info.to_dict())),
            )
            db.commit()

    def _load_sync(self, user_id: str,
                   batch_id: Optional[str] = None) -> List[BatchInfo]:
        with self._db_lock:
            db = self._db()
            if batch_id is not None:
                rows = db.execute(
                    "SELECT payload FROM batches WHERE user_id=? AND id=?",
                    (user_id, batch_id),
                ).fetchall()
            else:
                rows = db.execute(
                    "SELECT payload FROM batches WHERE user_id=?",
                    (user_id,),
                ).fetchall()
        return [self._from_dict(json.loads(r[0])) for r in rows]

    @staticmethod
    def _from_dict(d: dict) -> BatchInfo:
        counts = d.get("request_counts", {})
        return BatchInfo(
            id=d["id"],
            input_file_id=d["input_file_id"],
            endpoint=d["endpoint"],
            completion_window=d.get("completion_window", "24h"),
            status=BatchStatus(d["status"]),
            created_at=d["created_at"],
            output_file_id=d.get("output_file_id"),
            error_file_id=d.get("error_file_id"),
            completed_at=d.get("completed_at"),
            failed_at=d.get("failed_at"),
            metadata=d.get("metadata"),
            total_requests=counts.get("total", 0),
            completed_requests=counts.get("completed", 0),
            failed_requests=counts.get("failed", 0),
        )

    # ---- BatchProcessor API ----------------------------------------------

    async def initialize(self) -> None:
        await asyncio.to_thread(self._db)
        if self._worker is None:
            self._worker = asyncio.create_task(self._work_loop())

    async def close(self) -> None:
        if self._worker is not None:
            self._worker.cancel()
            try:
                await self._worker
            except asyncio.CancelledError:
                pass
            self._worker = None

    async def create_batch(self, user_id: str, input_file_id: str,
                           endpoint: str, completion_window: str = "24h",
                           metadata: Optional[dict] = None) -> BatchInfo:
        info = BatchInfo(
            id=f"batch-{uuid.uuid4().hex[:24]}",
            input_file_id=input_file_id,
            endpoint=endpoint,
            completion_window=completion_window,
            metadata=dict(metadata or {}, user_id=user_id),
        )
        await asyncio.to_thread(self._store_sync, user_id, info)
        return info

    async def retrieve_batch(self, user_id: str, batch_id: str) -> BatchInfo:
        found = await asyncio.to_thread(self._load_sync, user_id, batch_id)
        if not found:
            raise FileNotFoundError(f"Batch {batch_id} not found")
        return found[0]

    async def list_batches(self, user_id: str) -> List[BatchInfo]:
        return await asyncio.to_thread(self._load_sync, user_id)

    async def cancel_batch(self, user_id: str, batch_id: str) -> BatchInfo:
        info = await self.retrieve_batch(user_id, batch_id)
        if info.status in (BatchStatus.VALIDATING, BatchStatus.IN_PROGRESS):
            info.status = BatchStatus.CANCELLED
            await asyncio.to_thread(self._store_sync, user_id, info)
        return info

    # ---- execution --------------------------------------------------------

    async def _work_loop(self) -> None:
        while True:
            try:
                await self._process_pending()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.error("Batch worker error: %s", e)
            await asyncio.sleep(self.poll_interval_s)

    async def _process_pending(self) -> None:
        for user_id, info in await self._pending_list():
            await self._run_batch(user_id, info)

    async def _pending_list(self) -> List[tuple[str, BatchInfo]]:
        def load():
            with self._db_lock:
                return self._db().execute(
                    "SELECT user_id, payload FROM batches"
                ).fetchall()
        rows = await asyncio.to_thread(load)
        return [
            (u, info) for u, p in rows
            if (info := self._from_dict(json.loads(p))).status
            == BatchStatus.VALIDATING
        ]

    async def _is_cancelled(self, user_id: str, batch_id: str) -> bool:
        try:
            current = await self.retrieve_batch(user_id, batch_id)
        except FileNotFoundError:
            return True
        return current.status == BatchStatus.CANCELLED

    def _pick_backend(self, model: str) -> Optional[str]:
        from production_stack_tpu.router.service_discovery import (
            get_service_discovery,
        )
        try:
            eps = get_service_discovery().get_endpoint_info()
        except ValueError:
            return None
        for ep in eps:
            if ep.serves_model(model):
                return ep.url
        return None

    async def _run_batch(self, user_id: str, info: BatchInfo) -> None:
        logger.info("Processing batch %s", info.id)
        info.status = BatchStatus.IN_PROGRESS
        await asyncio.to_thread(self._store_sync, user_id, info)
        try:
            raw = await self.storage.get_file_content(
                user_id, info.input_file_id
            )
        except FileNotFoundError:
            info.status = BatchStatus.FAILED
            await asyncio.to_thread(self._store_sync, user_id, info)
            return

        lines = [ln for ln in raw.decode().splitlines() if ln.strip()]
        info.total_requests = len(lines)
        outputs, errors = [], []
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=600)) as session:
            for line in lines:
                if await self._is_cancelled(user_id, info.id):
                    logger.info("Batch %s cancelled mid-run", info.id)
                    return
                try:
                    req = json.loads(line)
                    body = req.get("body", {})
                    backend = self._pick_backend(body.get("model", ""))
                    if backend is None:
                        raise RuntimeError("no backend serves this model")
                    async with session.post(
                        f"{backend}{info.endpoint}", json=body,
                        timeout=aiohttp.ClientTimeout(total=600),
                    ) as resp:
                        result = await resp.json()
                        status = resp.status
                    outputs.append(json.dumps({
                        "id": f"batch_req_{uuid.uuid4().hex[:16]}",
                        "custom_id": req.get("custom_id"),
                        "response": {
                            "status_code": status, "body": result,
                        },
                        "error": None,
                    }))
                    info.completed_requests += 1
                except Exception as e:
                    errors.append(json.dumps({
                        "custom_id": (req.get("custom_id")
                                      if isinstance(req, dict) else None),
                        "error": {"message": str(e)},
                    }))
                    info.failed_requests += 1

        if await self._is_cancelled(user_id, info.id):
            logger.info("Batch %s cancelled before finalize", info.id)
            return
        info.status = BatchStatus.FINALIZING
        await asyncio.to_thread(self._store_sync, user_id, info)
        out_file = await self.storage.save_file(
            user_id, f"{info.id}_output.jsonl",
            ("\n".join(outputs)).encode(), purpose="batch_output",
        )
        info.output_file_id = out_file.id
        if errors:
            err_file = await self.storage.save_file(
                user_id, f"{info.id}_errors.jsonl",
                ("\n".join(errors)).encode(), purpose="batch_output",
            )
            info.error_file_id = err_file.id
        info.status = BatchStatus.COMPLETED
        info.completed_at = int(time.time())
        await asyncio.to_thread(self._store_sync, user_id, info)
        logger.info("Batch %s completed: %d ok, %d failed",
                    info.id, info.completed_requests, info.failed_requests)
