"""Batch processor abstraction (parity: batch_service/processor.py)."""

import abc
from typing import List, Optional

from production_stack_tpu.router.services.batch.batch import BatchInfo
from production_stack_tpu.router.services.files.storage import Storage


class BatchProcessor(abc.ABC):
    def __init__(self, storage: Storage):
        self.storage = storage

    @abc.abstractmethod
    async def initialize(self) -> None: ...

    @abc.abstractmethod
    async def create_batch(self, user_id: str, input_file_id: str,
                           endpoint: str, completion_window: str = "24h",
                           metadata: Optional[dict] = None) -> BatchInfo: ...

    @abc.abstractmethod
    async def retrieve_batch(self, user_id: str,
                             batch_id: str) -> BatchInfo: ...

    @abc.abstractmethod
    async def list_batches(self, user_id: str) -> List[BatchInfo]: ...

    @abc.abstractmethod
    async def cancel_batch(self, user_id: str, batch_id: str) -> BatchInfo: ...


def initialize_batch_processor(kind: str, storage: Storage,
                               **kwargs) -> BatchProcessor:
    if kind == "local":
        from production_stack_tpu.router.services.batch.local_processor \
            import LocalBatchProcessor
        return LocalBatchProcessor(storage, **kwargs)
    raise ValueError(f"Unknown batch processor: {kind}")
