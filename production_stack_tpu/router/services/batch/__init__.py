from production_stack_tpu.router.services.batch.batch import (
    BatchInfo,
    BatchStatus,
)
from production_stack_tpu.router.services.batch.local_processor import (
    LocalBatchProcessor,
)
from production_stack_tpu.router.services.batch.processor import (
    BatchProcessor,
    initialize_batch_processor,
)

__all__ = [
    "BatchInfo", "BatchStatus", "BatchProcessor", "LocalBatchProcessor",
    "initialize_batch_processor",
]
