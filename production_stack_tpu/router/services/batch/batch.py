"""OpenAI Batch API wire objects (parity: batch_service/batch.py:6-91)."""

import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class BatchStatus(str, enum.Enum):
    VALIDATING = "validating"
    FAILED = "failed"
    IN_PROGRESS = "in_progress"
    FINALIZING = "finalizing"
    COMPLETED = "completed"
    EXPIRED = "expired"
    CANCELLING = "cancelling"
    CANCELLED = "cancelled"


@dataclass
class BatchInfo:
    id: str
    input_file_id: str
    endpoint: str
    completion_window: str = "24h"
    status: BatchStatus = BatchStatus.VALIDATING
    created_at: int = field(default_factory=lambda: int(time.time()))
    output_file_id: Optional[str] = None
    error_file_id: Optional[str] = None
    completed_at: Optional[int] = None
    failed_at: Optional[int] = None
    metadata: Optional[Dict[str, Any]] = None
    total_requests: int = 0
    completed_requests: int = 0
    failed_requests: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "object": "batch",
            "endpoint": self.endpoint,
            "input_file_id": self.input_file_id,
            "completion_window": self.completion_window,
            "status": self.status.value,
            "created_at": self.created_at,
            "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id,
            "completed_at": self.completed_at,
            "failed_at": self.failed_at,
            "metadata": self.metadata or {},
            "request_counts": {
                "total": self.total_requests,
                "completed": self.completed_requests,
                "failed": self.failed_requests,
            },
        }
