"""Router resilience layer: circuit breakers, active health checking,
and the retry/timeout budget the proxy hot path consults.

The reference production-stack keeps its OpenAI front door up while
engine pods churn; this module is where that property lives in this
stack. Three cooperating pieces, all endpoint-scoped:

- ``CircuitBreaker``: closed -> open -> half-open per endpoint URL.
  Opens when the failure rate over a sliding outcome window crosses a
  threshold, stays open for an exponentially growing (jittered) backoff,
  then admits a single half-open probe request whose outcome closes or
  re-opens it.
- ``HealthChecker``: a background asyncio task probing every discovered
  endpoint's ``GET /health`` on an interval. N consecutive failures mark
  the endpoint unhealthy; service discovery filters unhealthy endpoints
  out of rotation before routing ever sees them.
- ``ResilienceManager``: owns the breakers + checker + retry/timeout
  config, and the counters the metrics service exports.

All state is consulted from the router's single event loop (plus the
metrics render handler on that same loop); a lock still guards breaker
mutation so stats threads may read snapshots safely.

Disabled-by-default for embedders: ``get_resilience()`` returns ``None``
until ``initialize_resilience`` runs (the CLI app always initializes
it), and every caller treats ``None`` as "no filtering, no retries" —
the pre-resilience behavior.
"""

from __future__ import annotations

import asyncio
import enum
import math
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional

import aiohttp

from production_stack_tpu.utils import SingletonMeta
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


@dataclass
class ResilienceConfig:
    """Knobs, mirrored 1:1 by router CLI flags (see parser.py)."""

    # Retry-with-failover budget: how many *additional* endpoints a
    # request may be re-routed to after a pre-first-byte failure.
    max_retries: int = 2
    # Per-request backend timeouts (seconds). 0 disables that bound.
    # ``backend_timeout`` bounds each socket read (waiting for the
    # response to start, and every inter-chunk gap while streaming) —
    # NOT the total exchange, so a generation that keeps streaming can
    # run arbitrarily long while a backend that goes silent still gets
    # cut off.
    backend_connect_timeout: float = 30.0
    backend_timeout: float = 600.0
    # Active health checking. interval 0 disables the prober.
    health_check_interval: float = 10.0
    health_check_timeout: float = 2.0
    health_failure_threshold: int = 3
    health_success_threshold: int = 1
    # Circuit breaker.
    breaker_window: int = 20
    breaker_min_volume: int = 3
    breaker_failure_rate: float = 0.5
    breaker_open_base_s: float = 2.0
    breaker_open_max_s: float = 60.0
    breaker_jitter: float = 0.1
    breaker_half_open_max: int = 1

    def client_timeout(self) -> aiohttp.ClientTimeout:
        # sock_read (per-read stall bound) rather than total: a total
        # deadline would expire mid-stream on any legitimately long
        # generation (or while a slow client drains the response) and
        # blame a healthy backend for it.
        return aiohttp.ClientTimeout(
            total=None,
            sock_connect=self.backend_connect_timeout or None,
            sock_read=self.backend_timeout or None,
        )


class BreakerState(enum.IntEnum):
    """IntEnum so the value doubles as the exported gauge sample."""

    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class CircuitBreaker:
    """One endpoint's failure-rate breaker.

    Outcomes land in a bounded deque; once at least ``breaker_min_volume``
    outcomes are present and the failure fraction reaches
    ``breaker_failure_rate``, the breaker opens. While open,
    ``can_attempt`` stays False until the backoff elapses; the next
    attempt then transitions to half-open and rides as the probe.
    Consecutive opens double the backoff (capped, jittered) so a
    flapping backend is probed ever more gently.
    """

    def __init__(self, config: ResilienceConfig, clock=time.monotonic,
                 rng: Optional[random.Random] = None):
        self._config = config
        self._clock = clock
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._window: Deque[bool] = deque(maxlen=config.breaker_window)
        self._state = BreakerState.CLOSED
        self._opened_at = 0.0
        self._reopen_after = 0.0
        self._consecutive_opens = 0
        self._half_open_inflight = 0
        self.opens_total = 0
        # Cumulative failures charged to this endpoint — exported as
        # vllm:server_errors_total; the rollout judge reads a canary's
        # bake-window delta of it (docs/fleet.md).
        self.failures_total = 0

    @property
    def state(self) -> BreakerState:
        return self._state

    def can_attempt(self) -> bool:
        with self._lock:
            if self._state == BreakerState.CLOSED:
                return True
            if self._state == BreakerState.OPEN:
                return (self._clock() - self._opened_at
                        >= self._reopen_after)
            return (self._half_open_inflight
                    < self._config.breaker_half_open_max)

    def on_attempt(self) -> bool:
        """Atomically admit one dispatch to this endpoint. Returns False
        when the breaker is still open or every half-open probe slot is
        taken — the caller must skip the endpoint (``can_attempt`` is
        only an advisory pre-filter; concurrent requests may race
        between it and here). Every True return MUST be balanced by
        exactly one of ``record_success`` / ``record_failure`` /
        ``release_attempt``, else a probe slot leaks and the breaker
        wedges in HALF_OPEN forever."""
        with self._lock:
            if self._state == BreakerState.OPEN:
                if (self._clock() - self._opened_at
                        < self._reopen_after):
                    return False
                self._state = BreakerState.HALF_OPEN
                self._half_open_inflight = 0
                logger.info("Breaker half-open (probe admitted)")
            if self._state == BreakerState.HALF_OPEN:
                if (self._half_open_inflight
                        >= self._config.breaker_half_open_max):
                    return False
                self._half_open_inflight += 1
            return True

    def release_attempt(self) -> None:
        """Balance an admitted attempt that ended with neither success
        nor failure — client disconnect, handler cancellation, unknown
        proxy error. Frees the half-open probe slot so the next request
        can ride as the probe instead of the breaker staying HALF_OPEN
        with its slot leaked."""
        with self._lock:
            if self._state == BreakerState.HALF_OPEN:
                self._half_open_inflight = max(
                    0, self._half_open_inflight - 1)

    def record_success(self) -> None:
        with self._lock:
            if self._state == BreakerState.HALF_OPEN:
                self._state = BreakerState.CLOSED
                self._window.clear()
                self._consecutive_opens = 0
                self._half_open_inflight = 0
                logger.info("Breaker closed after successful probe")
            elif self._state == BreakerState.CLOSED:
                self._window.append(True)

    def record_failure(self) -> None:
        with self._lock:
            self.failures_total += 1
            if self._state == BreakerState.HALF_OPEN:
                self._half_open_inflight = max(
                    0, self._half_open_inflight - 1)
                self._open_locked()
            elif self._state == BreakerState.CLOSED:
                self._window.append(False)
                if (len(self._window) >= self._config.breaker_min_volume
                        and (self._window.count(False) / len(self._window)
                             >= self._config.breaker_failure_rate)):
                    self._open_locked()

    def _open_locked(self) -> None:
        cfg = self._config
        self._consecutive_opens += 1
        self.opens_total += 1
        backoff = min(
            cfg.breaker_open_base_s * 2 ** (self._consecutive_opens - 1),
            cfg.breaker_open_max_s,
        )
        if cfg.breaker_jitter:
            backoff *= 1.0 + cfg.breaker_jitter * (
                2.0 * self._rng.random() - 1.0)
        self._state = BreakerState.OPEN
        self._opened_at = self._clock()
        self._reopen_after = backoff
        self._window.clear()
        self._half_open_inflight = 0
        logger.warning("Breaker opened (open #%d, retry in %.2fs)",
                       self._consecutive_opens, backoff)

    def time_until_half_open(self) -> float:
        with self._lock:
            if self._state != BreakerState.OPEN:
                return 0.0
            return max(
                0.0,
                self._opened_at + self._reopen_after - self._clock(),
            )


@dataclass
class EndpointHealth:
    healthy: bool = True
    consecutive_failures: int = 0
    consecutive_successes: int = 0
    probes_total: int = 0
    failures_total: int = 0
    last_probe_ok: Optional[bool] = None


class HealthChecker:
    """Active ``GET /health`` prober over every discovered endpoint.

    Runs as an asyncio task on the router loop (``start``/``stop`` from
    the app lifecycle). Endpoints the checker has never probed count as
    healthy — a freshly discovered backend must not be blackholed while
    waiting for its first probe.
    """

    def __init__(self, config: ResilienceConfig):
        self._config = config
        self._status: Dict[str, EndpointHealth] = {}
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._started = False

    # -- lifecycle ----------------------------------------------------

    async def start(self) -> None:
        if self._task is not None:
            return
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self._config.health_check_timeout),
        )
        self._started = True
        self._task = asyncio.create_task(
            self._run(), name="endpoint-health-checker")
        logger.info("Health checker started (interval %.1fs)",
                    self._config.health_check_interval)

    async def stop(self) -> None:
        self._started = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    def is_running(self) -> bool:
        """False only when started and the task has died or stopped."""
        if not self._started:
            return True
        return self._task is not None and not self._task.done()

    async def _run(self) -> None:
        while True:
            try:
                await self.probe_all()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep the loop alive on any bug
                logger.error("Health probe sweep failed: %s", e)
            await asyncio.sleep(self._config.health_check_interval)

    # -- probing ------------------------------------------------------

    async def probe_all(self) -> None:
        """One sweep over the currently discovered endpoints."""
        from production_stack_tpu.router.service_discovery import (
            get_service_discovery,
        )
        try:
            endpoints = get_service_discovery().get_endpoint_info(
                include_unhealthy=True)
        except ValueError:
            return
        urls = [ep.url for ep in endpoints]
        own_session = self._session is None
        session = self._session or aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self._config.health_check_timeout),
        )
        try:
            await asyncio.gather(
                *(self._probe_one(session, url) for url in urls))
        finally:
            if own_session:
                await session.close()
        # Forget endpoints that left the pool so the map stays bounded.
        for url in list(self._status):
            if url not in urls:
                del self._status[url]

    async def _probe_one(self, session: aiohttp.ClientSession,
                         url: str) -> None:
        ok = False
        try:
            async with session.get(
                f"{url}/health",
                timeout=aiohttp.ClientTimeout(
                    total=self._config.health_check_timeout),
            ) as resp:
                ok = resp.status < 400
                if ok:
                    # A draining engine (docs/fleet.md) still answers
                    # 200 for its in-flight clients but advertises
                    # ``draining``: routing must stop sending it new
                    # work, so the probe counts as a failure.
                    try:
                        payload = await resp.json()
                    except Exception:
                        payload = None
                    if (isinstance(payload, dict)
                            and payload.get("draining")):
                        ok = False
        except asyncio.CancelledError:
            raise
        except Exception:
            ok = False
        self.record_probe(url, ok)

    def record_probe(self, url: str, ok: bool) -> None:
        cfg = self._config
        st = self._status.setdefault(url, EndpointHealth())
        st.probes_total += 1
        st.last_probe_ok = ok
        if ok:
            st.consecutive_failures = 0
            st.consecutive_successes += 1
            if (not st.healthy and st.consecutive_successes
                    >= cfg.health_success_threshold):
                st.healthy = True
                logger.info("Endpoint %s back to healthy", url)
        else:
            st.failures_total += 1
            st.consecutive_successes = 0
            st.consecutive_failures += 1
            if (st.healthy and st.consecutive_failures
                    >= cfg.health_failure_threshold):
                st.healthy = False
                logger.warning(
                    "Endpoint %s marked unhealthy after %d failed probes",
                    url, st.consecutive_failures)

    def is_healthy(self, url: str) -> bool:
        st = self._status.get(url)
        return True if st is None else st.healthy

    def snapshot(self) -> Dict[str, EndpointHealth]:
        return dict(self._status)


class ResilienceManager:
    """Facade the proxy, discovery, and metrics layers talk to."""

    def __init__(self, config: Optional[ResilienceConfig] = None,
                 clock=time.monotonic,
                 rng: Optional[random.Random] = None):
        self.config = config or ResilienceConfig()
        self._clock = clock
        self._rng = rng or random.Random(0x5E51)
        self._breakers: Dict[str, CircuitBreaker] = {}
        self.health: Optional[HealthChecker] = (
            HealthChecker(self.config)
            if self.config.health_check_interval > 0 else None
        )
        self.retries_total = 0
        self.failovers_total = 0
        self.shed_requests_total = 0

    def breaker(self, url: str) -> CircuitBreaker:
        br = self._breakers.get(url)
        if br is None:
            br = self._breakers[url] = CircuitBreaker(
                self.config, clock=self._clock, rng=self._rng)
        return br

    def endpoint_available(self, url: str) -> bool:
        if self.health is not None and not self.health.is_healthy(url):
            return False
        br = self._breakers.get(url)
        return br is None or br.can_attempt()

    def on_attempt(self, url: str) -> bool:
        """Atomic admission; a True return must be balanced by exactly
        one record_success / record_failure / release_attempt."""
        return self.breaker(url).on_attempt()

    def release_attempt(self, url: str) -> None:
        self.breaker(url).release_attempt()

    def record_success(self, url: str) -> None:
        self.breaker(url).record_success()

    def record_failure(self, url: str) -> None:
        self.breaker(url).record_failure()

    def retry_after_hint(self, urls: List[str]) -> int:
        """Seconds until the soonest open breaker admits a probe (or the
        next health sweep) — the ``Retry-After`` value for 503s."""
        waits = [
            self._breakers[u].time_until_half_open()
            for u in urls if u in self._breakers
        ]
        waits = [w for w in waits if w > 0]
        if not waits and self.health is not None:
            waits = [self.config.health_check_interval]
        return max(1, int(math.ceil(min(waits)))) if waits else 1

    def breaker_snapshot(self) -> Dict[str, CircuitBreaker]:
        return dict(self._breakers)

    async def start(self) -> None:
        if self.health is not None:
            await self.health.start()

    async def stop(self) -> None:
        if self.health is not None:
            await self.health.stop()


class _ResilienceHolder(metaclass=SingletonMeta):
    """SingletonMeta so the test harness resets it between tests."""

    def __init__(self):
        self.instance: Optional[ResilienceManager] = None


def initialize_resilience(
        config: Optional[ResilienceConfig] = None) -> ResilienceManager:
    holder = _ResilienceHolder()
    holder.instance = ResilienceManager(config)
    return holder.instance


def get_resilience() -> Optional[ResilienceManager]:
    """None until initialized: callers fall back to pre-resilience
    behavior (no filtering, no retries, session-default timeouts)."""
    return _ResilienceHolder().instance


def shutdown_resilience() -> None:
    _ResilienceHolder().instance = None
