"""OpenAI-compatible request router (data plane).

Capability parity with reference src/vllm_router/ (see SURVEY.md §2.1),
re-designed on aiohttp: one asyncio process, background threads only for
service discovery / metric scraping, streaming proxy with zero-copy chunks.
"""
