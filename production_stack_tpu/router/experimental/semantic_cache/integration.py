"""Router integration for the semantic cache
(parity: experimental/semantic_cache_integration.py, incl. the Prometheus
gauges and the hit short-circuit in the chat-completions handler).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from aiohttp import web
from prometheus_client import Gauge

from production_stack_tpu.router.experimental.semantic_cache.cache import (
    get_semantic_cache,
    initialize_semantic_cache,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

semantic_cache_size = Gauge(
    "vllm:semantic_cache_size", "Entries in the semantic cache")
semantic_cache_hits = Gauge(
    "vllm:semantic_cache_hits", "Semantic cache hit count")
semantic_cache_misses = Gauge(
    "vllm:semantic_cache_misses", "Semantic cache miss count")
semantic_cache_hit_ratio = Gauge(
    "vllm:semantic_cache_hit_ratio", "Semantic cache hit ratio")
semantic_cache_latency = Gauge(
    "vllm:semantic_cache_latency", "Semantic cache lookup latency (s)")


def enable_semantic_cache(**kwargs) -> None:
    initialize_semantic_cache(**kwargs)


def _refresh_gauges(cache) -> None:
    total = cache.hits + cache.misses
    semantic_cache_hits.set(cache.hits)
    semantic_cache_misses.set(cache.misses)
    semantic_cache_hit_ratio.set(cache.hits / total if total else 0.0)
    semantic_cache_size.set(
        sum(len(ix) for ix in cache._indexes.values())
    )


async def check_semantic_cache(
        request: web.Request) -> Optional[web.Response]:
    """Return a cached response, or None to continue to the engines."""
    cache = get_semantic_cache()
    if cache is None:
        return None
    try:
        payload = json.loads(await request.read())
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None
    if payload.get("stream"):
        return None  # only cache non-streaming requests
    model = payload.get("model")
    messages = payload.get("messages")
    if not model or not messages:
        return None
    start = time.time()
    cached = cache.lookup(model, messages)
    semantic_cache_latency.set(time.time() - start)
    _refresh_gauges(cache)
    if cached is None:
        return None
    return web.json_response(cached)


def store_in_semantic_cache(model: str, messages, response: dict) -> None:
    cache = get_semantic_cache()
    if cache is None:
        return
    cache.store(model, messages, response)
    _refresh_gauges(cache)
