"""Semantic response cache (parity: experimental/semantic_cache/).

Embeds the chat request, searches a vector index for a similar past
request, and serves the cached response on a hit. The reference uses
sentence-transformers + FAISS; this environment has no FAISS and no
network to fetch embedding weights, so the default embedder is a
hashing n-gram projection (deterministic, dependency-free) and the index
is exact cosine search over a numpy matrix. Both are pluggable:
``SemanticCache(embedder=...)`` accepts any callable str -> np.ndarray.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

DEFAULT_DIM = 384
DEFAULT_THRESHOLD = 0.95


def hashing_embedder(text: str, dim: int = DEFAULT_DIM) -> np.ndarray:
    """Deterministic bag-of-ngrams feature hashing with signed buckets."""
    vec = np.zeros(dim, dtype=np.float32)
    tokens = text.lower().split()
    grams = tokens + [
        " ".join(tokens[i:i + 2]) for i in range(len(tokens) - 1)
    ]
    for gram in grams:
        h = hashlib.blake2b(gram.encode(), digest_size=8).digest()
        idx = int.from_bytes(h[:4], "big") % dim
        sign = 1.0 if h[4] & 1 else -1.0
        vec[idx] += sign
    norm = np.linalg.norm(vec)
    return vec / norm if norm > 0 else vec


class VectorIndex:
    """Exact cosine-similarity search over a growable numpy matrix."""

    def __init__(self, dim: int):
        self.dim = dim
        self._matrix = np.zeros((0, dim), dtype=np.float32)
        self._payloads: List[Any] = []

    def __len__(self) -> int:
        return len(self._payloads)

    def add(self, vector: np.ndarray, payload: Any) -> None:
        self._matrix = np.vstack([self._matrix, vector[None, :]])
        self._payloads.append(payload)

    def search(self, vector: np.ndarray) -> Tuple[float, Optional[Any]]:
        if not self._payloads:
            return -1.0, None
        scores = self._matrix @ vector
        best = int(np.argmax(scores))
        return float(scores[best]), self._payloads[best]


class SemanticCache:
    def __init__(self,
                 embedder: Optional[Callable[[str], np.ndarray]] = None,
                 dim: int = DEFAULT_DIM,
                 threshold: float = DEFAULT_THRESHOLD,
                 persist_dir: Optional[str] = None):
        self.embedder = embedder or hashing_embedder
        self.threshold = threshold
        self.persist_dir = persist_dir
        self._lock = threading.Lock()
        # One index per model: answers must never cross models.
        self._indexes: Dict[str, VectorIndex] = {}
        self.dim = dim
        self.hits = 0
        self.misses = 0
        if persist_dir:
            self._load()

    @staticmethod
    def request_text(messages: List[dict]) -> str:
        return "\n".join(
            f"{m.get('role', '')}: {m.get('content', '')}" for m in messages
        )

    def lookup(self, model: str,
               messages: List[dict]) -> Optional[dict]:
        vec = self.embedder(self.request_text(messages))
        with self._lock:
            index = self._indexes.get(model)
            if index is None:
                self.misses += 1
                return None
            score, payload = index.search(vec)
            if score >= self.threshold:
                self.hits += 1
                logger.debug("Semantic cache hit (score=%.3f)", score)
                return payload
            self.misses += 1
            return None

    def store(self, model: str, messages: List[dict],
              response: dict) -> None:
        vec = self.embedder(self.request_text(messages))
        with self._lock:
            index = self._indexes.setdefault(
                model, VectorIndex(self.dim)
            )
            index.add(vec, response)
        if self.persist_dir:
            self._persist(model, messages, response)

    # ---- persistence (append-only JSONL per model) ------------------------

    def _model_path(self, model: str) -> str:
        safe = model.replace("/", "_")
        return os.path.join(self.persist_dir, f"{safe}.jsonl")

    def _persist(self, model: str, messages: List[dict],
                 response: dict) -> None:
        os.makedirs(self.persist_dir, exist_ok=True)
        with open(self._model_path(model), "a") as f:
            f.write(json.dumps(
                {"messages": messages, "response": response}
            ) + "\n")

    def _load(self) -> None:
        if not os.path.isdir(self.persist_dir):
            return
        for name in os.listdir(self.persist_dir):
            if not name.endswith(".jsonl"):
                continue
            model = name[:-len(".jsonl")]
            with open(os.path.join(self.persist_dir, name)) as f:
                for line in f:
                    try:
                        entry = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    vec = self.embedder(
                        self.request_text(entry["messages"])
                    )
                    self._indexes.setdefault(
                        model, VectorIndex(self.dim)
                    ).add(vec, entry["response"])


_instance: Optional[SemanticCache] = None


def initialize_semantic_cache(**kwargs) -> SemanticCache:
    global _instance
    _instance = SemanticCache(**kwargs)
    return _instance


def get_semantic_cache() -> Optional[SemanticCache]:
    return _instance
