"""PII detection types (parity: experimental/pii/types.py)."""

import enum
from dataclasses import dataclass, field
from typing import List, Set


class PIIType(str, enum.Enum):
    EMAIL = "email"
    PHONE = "phone"
    SSN = "ssn"
    CREDIT_CARD = "credit_card"
    IP_ADDRESS = "ip_address"
    API_KEY = "api_key"
    IBAN = "iban"


@dataclass
class PIIMatch:
    pii_type: PIIType
    start: int
    end: int
    snippet: str


@dataclass
class PIIAnalysisResult:
    has_pii: bool = False
    detected_types: Set[PIIType] = field(default_factory=set)
    matches: List[PIIMatch] = field(default_factory=list)
