"""Request-blocking PII middleware
(parity: experimental/pii/middleware.py:20-154 incl. its 5 Prometheus
metrics and the conservative block-on-error stance).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from aiohttp import web
from prometheus_client import Counter, Gauge

from production_stack_tpu.router.experimental.pii.analyzers import (
    PIIAnalyzer,
    create_analyzer,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

pii_requests_scanned = Counter(
    "vllm:pii_requests_scanned_total", "Requests scanned for PII")
pii_requests_blocked = Counter(
    "vllm:pii_requests_blocked_total", "Requests blocked due to PII")
pii_types_detected = Counter(
    "vllm:pii_types_detected_total", "PII types detected", ["pii_type"])
pii_scan_latency = Gauge(
    "vllm:pii_scan_latency_seconds", "Latency of last PII scan")
pii_analyzer_errors = Counter(
    "vllm:pii_analyzer_errors_total", "PII analyzer errors")

_analyzer: Optional[PIIAnalyzer] = None


def enable_pii_detection(kind: str = "regex") -> None:
    global _analyzer
    _analyzer = create_analyzer(kind)


def _extract_text(payload: dict) -> str:
    parts = []
    for message in payload.get("messages", []) or []:
        content = message.get("content")
        if isinstance(content, str):
            parts.append(content)
        elif isinstance(content, list):
            parts.extend(
                c.get("text", "") for c in content if isinstance(c, dict)
            )
    prompt = payload.get("prompt")
    if isinstance(prompt, str):
        parts.append(prompt)
    elif isinstance(prompt, list):
        parts.extend(p for p in prompt if isinstance(p, str))
    return "\n".join(parts)


async def check_request(request: web.Request) -> Optional[web.Response]:
    """Return a blocking response if the request contains PII, else None."""
    global _analyzer
    if _analyzer is None:
        _analyzer = create_analyzer("regex")
    try:
        body = await request.read()
        payload = json.loads(body) if body else {}
        text = _extract_text(payload)
        start = time.time()
        result = _analyzer.analyze(text)
        pii_scan_latency.set(time.time() - start)
        pii_requests_scanned.inc()
    except Exception as e:
        # Conservative: a scanner failure blocks the request.
        pii_analyzer_errors.inc()
        logger.error("PII analysis failed; blocking request: %s", e)
        return web.json_response(
            {"error": {"message": "PII analysis failed",
                       "type": "pii_analysis_error"}},
            status=500,
        )
    if result.has_pii:
        pii_requests_blocked.inc()
        for t in result.detected_types:
            pii_types_detected.labels(pii_type=t.value).inc()
        logger.warning("Blocked request containing PII: %s",
                       sorted(t.value for t in result.detected_types))
        return web.json_response(
            {"error": {
                "message": "Request blocked: contains personally "
                           "identifiable information",
                "type": "pii_detected",
                "detected_types": sorted(
                    t.value for t in result.detected_types),
            }},
            status=400,
        )
    return None
