"""PII analyzers (parity: experimental/pii/analyzers/{regex,presidio}.py).

The regex analyzer is self-contained; the presidio analyzer is
import-gated on the optional ``presidio_analyzer`` package.
"""

from __future__ import annotations

import abc
import re
from typing import Dict, Iterable, Optional

from production_stack_tpu.router.experimental.pii.types import (
    PIIAnalysisResult,
    PIIMatch,
    PIIType,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class PIIAnalyzer(abc.ABC):
    @abc.abstractmethod
    def analyze(self, text: str,
                types: Optional[Iterable[PIIType]] = None
                ) -> PIIAnalysisResult:
        ...


_PATTERNS: Dict[PIIType, re.Pattern] = {
    PIIType.EMAIL: re.compile(
        r"\b[A-Za-z0-9._%+-]+@[A-Za-z0-9.-]+\.[A-Za-z]{2,}\b"),
    PIIType.PHONE: re.compile(
        r"\b(?:\+?\d{1,3}[-. ]?)?\(?\d{3}\)?[-. ]?\d{3}[-. ]?\d{4}\b"),
    PIIType.SSN: re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    PIIType.CREDIT_CARD: re.compile(
        r"\b(?:\d[ -]*?){13,16}\b"),
    PIIType.IP_ADDRESS: re.compile(
        r"\b(?:(?:25[0-5]|2[0-4]\d|1?\d?\d)\.){3}"
        r"(?:25[0-5]|2[0-4]\d|1?\d?\d)\b"),
    PIIType.API_KEY: re.compile(
        r"\b(?:sk|pk|api|key|token)[-_][A-Za-z0-9_\-]{16,}\b",
        re.IGNORECASE),
    PIIType.IBAN: re.compile(
        r"\b[A-Z]{2}\d{2}[A-Z0-9]{11,30}\b"),
}


def _luhn_ok(digits: str) -> bool:
    total, parity = 0, len(digits) % 2
    for i, ch in enumerate(digits):
        d = int(ch)
        if i % 2 == parity:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return total % 10 == 0


class RegexAnalyzer(PIIAnalyzer):
    def analyze(self, text: str,
                types: Optional[Iterable[PIIType]] = None
                ) -> PIIAnalysisResult:
        result = PIIAnalysisResult()
        wanted = set(types) if types else set(_PATTERNS)
        for pii_type in wanted:
            pattern = _PATTERNS.get(pii_type)
            if pattern is None:
                continue
            for m in pattern.finditer(text):
                if pii_type == PIIType.CREDIT_CARD:
                    digits = re.sub(r"\D", "", m.group())
                    if not (13 <= len(digits) <= 16
                            and _luhn_ok(digits)):
                        continue
                result.has_pii = True
                result.detected_types.add(pii_type)
                result.matches.append(PIIMatch(
                    pii_type=pii_type, start=m.start(), end=m.end(),
                    snippet=m.group()[:32],
                ))
        return result


class PresidioAnalyzer(PIIAnalyzer):  # pragma: no cover - optional dep
    def __init__(self):
        try:
            from presidio_analyzer import AnalyzerEngine
        except ImportError as e:
            raise RuntimeError(
                "presidio analyzer requires the presidio_analyzer package"
            ) from e
        self._engine = AnalyzerEngine()

    def analyze(self, text, types=None) -> PIIAnalysisResult:
        result = PIIAnalysisResult()
        for finding in self._engine.analyze(text=text, language="en"):
            result.has_pii = True
            try:
                pii_type = PIIType(finding.entity_type.lower())
            except ValueError:
                continue
            result.detected_types.add(pii_type)
            result.matches.append(PIIMatch(
                pii_type=pii_type, start=finding.start, end=finding.end,
                snippet=text[finding.start:finding.end][:32],
            ))
        return result


def create_analyzer(kind: str = "regex") -> PIIAnalyzer:
    if kind == "regex":
        return RegexAnalyzer()
    if kind == "presidio":
        return PresidioAnalyzer()
    raise ValueError(f"Unknown PII analyzer: {kind}")
