from production_stack_tpu.router.experimental.feature_gates import (
    FeatureGates,
    FeatureStage,
    get_feature_gates,
    initialize_feature_gates,
)

__all__ = [
    "FeatureGates", "FeatureStage", "get_feature_gates",
    "initialize_feature_gates",
]
