"""K8s-style feature gates (parity: experimental/feature_gates.py:18-141).

``--feature-gates SemanticCache=true,PIIDetection=false`` or the
``PSTPU_FEATURE_GATES`` environment variable. Each gate has a maturity
stage; Alpha gates default off, Beta/GA default on unless disabled.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass
from typing import Dict, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

ENV_VAR = "PSTPU_FEATURE_GATES"

SEMANTIC_CACHE_GATE = "SemanticCache"
PII_DETECTION_GATE = "PIIDetection"
KV_AWARE_ROUTING_GATE = "KVAwareRouting"


class FeatureStage(str, enum.Enum):
    ALPHA = "Alpha"
    BETA = "Beta"
    GA = "GA"


@dataclass
class FeatureSpec:
    name: str
    stage: FeatureStage
    default: bool
    description: str = ""


_KNOWN_FEATURES: Dict[str, FeatureSpec] = {
    SEMANTIC_CACHE_GATE: FeatureSpec(
        SEMANTIC_CACHE_GATE, FeatureStage.ALPHA, False,
        "Embedding-similarity response cache for chat completions"),
    PII_DETECTION_GATE: FeatureSpec(
        PII_DETECTION_GATE, FeatureStage.ALPHA, False,
        "Request-blocking PII detection middleware"),
    KV_AWARE_ROUTING_GATE: FeatureSpec(
        KV_AWARE_ROUTING_GATE, FeatureStage.ALPHA, False,
        "Prefix-cache-aware routing hints"),
}


class FeatureGates:
    def __init__(self, spec: Optional[str] = None):
        self._enabled: Dict[str, bool] = {
            name: fs.default for name, fs in _KNOWN_FEATURES.items()
        }
        merged = ",".join(
            s for s in (os.environ.get(ENV_VAR, ""), spec or "") if s
        )
        for item in merged.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"Feature gate must be Name=true|false, got {item!r}"
                )
            name, _, value = item.partition("=")
            name = name.strip()
            if name not in _KNOWN_FEATURES:
                raise ValueError(f"Unknown feature gate: {name}")
            self._enabled[name] = value.strip().lower() == "true"
        for name, on in self._enabled.items():
            if on:
                logger.info("Feature gate enabled: %s (%s)", name,
                            _KNOWN_FEATURES[name].stage.value)

    def enabled(self, name: str) -> bool:
        return self._enabled.get(name, False)

    def as_dict(self) -> Dict[str, bool]:
        return dict(self._enabled)


_instance: Optional[FeatureGates] = None


def initialize_feature_gates(spec: Optional[str] = None) -> FeatureGates:
    global _instance
    _instance = FeatureGates(spec)
    return _instance


def get_feature_gates() -> FeatureGates:
    global _instance
    if _instance is None:
        _instance = FeatureGates()
    return _instance
