"""Structured request-span logging.

The reference has no tracing subsystem; its closest artifacts are
per-request timing logs (request.py:215-217) and the Grafana
router-queueing-delay panel. SURVEY.md §5 calls for structured spans at
parity — this module emits one JSON line per request covering the full
router-side lifecycle:

    {"span": "request", "request_id": ..., "model": ..., "path": ...,
     "backend": ..., "arrival_ts": ..., "queue_delay_ms": ...,
     "ttft_ms": ..., "latency_ms": ..., "chunks": ..., "status": ...}

Enable with ``--request-span-log PATH`` ("-" = the router log). Spans
are written by a plain file append per completed request — no buffering
state to lose on crash, and zero overhead when disabled (the hot path
checks one ``is None``).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


@dataclass
class RequestSpan:
    request_id: str
    model: str
    path: str
    # QoS attribution (docs/qos.md): the request's priority class and
    # tenant identity, so SLO attainment per class is derivable from
    # span logs alone. Always set by the router (class defaults to
    # the deployment default when the x-priority header is absent).
    priority_class: Optional[str] = None
    tenant: Optional[str] = None
    arrival_ts: float = field(default_factory=time.time)
    backend: Optional[str] = None
    routed_ts: Optional[float] = None
    first_chunk_ts: Optional[float] = None
    end_ts: Optional[float] = None
    chunks: int = 0
    status: str = "ok"  # ok | killed | rejected | error
    retries: int = 0
    tried_backends: list = field(default_factory=list)
    # Disaggregated two-hop dispatch (docs/disaggregation.md): the
    # prefill hop's backend and the descriptor-received -> decode-hop-
    # routed gap. Explicit hop fields — the prefill->decode transition
    # is NOT a failover and must not touch retries/tried_backends.
    prefill_backend: Optional[str] = None
    prefill_done_ts: Optional[float] = None
    handoff_ms: Optional[float] = None

    def on_routed(self, backend: str) -> None:
        if self.backend is not None and backend != self.backend:
            # Failover: the previous backend failed pre-first-byte.
            self.tried_backends.append(self.backend)
            self.retries += 1
        self.backend = backend
        self.routed_ts = time.time()

    def on_prefill_routed(self, backend: str) -> None:
        """The disagg prefill hop returned its descriptor from
        ``backend``. Recorded as a hop, never as a failover."""
        self.prefill_backend = backend
        self.prefill_done_ts = time.time()

    def on_decode_routed(self, backend: str) -> None:
        """The disagg decode hop routed to ``backend``: ordinary
        routing (failover semantics apply among decode candidates)
        plus the descriptor-received -> decode-routed handoff gap.
        If no decode hop is ever routed (straight to the monolithic
        fallback), handoff_ms stays unset — no handoff happened."""
        self.on_routed(backend)
        if self.prefill_done_ts is not None:
            self.handoff_ms = round(
                (self.routed_ts - self.prefill_done_ts) * 1e3, 2)

    def on_chunk(self) -> None:
        if self.first_chunk_ts is None:
            self.first_chunk_ts = time.time()
        self.chunks += 1

    def finish(self, status: str = "ok") -> None:
        self.status = status
        self.end_ts = time.time()

    def to_json(self) -> str:
        def ms(a, b):
            return (None if a is None or b is None
                    else round((b - a) * 1e3, 2))
        return json.dumps({
            "span": "request",
            "request_id": self.request_id,
            "model": self.model,
            "path": self.path,
            "priority_class": self.priority_class,
            "tenant": self.tenant,
            "backend": self.backend,
            "arrival_ts": round(self.arrival_ts, 6),
            "queue_delay_ms": ms(self.arrival_ts, self.routed_ts),
            "ttft_ms": ms(self.arrival_ts, self.first_chunk_ts),
            "latency_ms": ms(self.arrival_ts, self.end_ts),
            "chunks": self.chunks,
            "status": self.status,
            "retries": self.retries,
            "tried_backends": list(self.tried_backends),
            "prefill_backend": self.prefill_backend,
            "handoff_ms": self.handoff_ms,
        })


class SpanLogger:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._file = (None if path == "-"
                      else open(path, "a", buffering=1))

    def emit(self, span: RequestSpan) -> None:
        line = span.to_json()
        if self._file is None:
            logger.info("%s", line)
        else:
            with self._lock:
                self._file.write(line + "\n")

    def close(self) -> None:
        if self._file is not None:
            self._file.close()


_span_logger: Optional[SpanLogger] = None


def initialize_span_logger(path: Optional[str]) -> Optional[SpanLogger]:
    global _span_logger
    if _span_logger is not None:
        _span_logger.close()
    _span_logger = SpanLogger(path) if path else None
    if _span_logger:
        logger.info("Request-span logging -> %s", path)
    return _span_logger


def get_span_logger() -> Optional[SpanLogger]:
    return _span_logger


def start_span(request_id: str, model: str, path: str,
               priority_class: Optional[str] = None,
               tenant: Optional[str] = None) -> Optional[RequestSpan]:
    """None when span logging is disabled — the hot path stays free."""
    if _span_logger is None:
        return None
    return RequestSpan(request_id=request_id, model=model, path=path,
                       priority_class=priority_class, tenant=tenant)
