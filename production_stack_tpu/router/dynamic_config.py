"""Dynamic config hot-reload (parity: src/vllm_router/dynamic_config.py).

A daemon thread polls a JSON file (written by the control-plane agent or a
mounted ConfigMap); on content change it reconfigures service discovery and
routing logic live, without restarting the router. The current config is
surfaced in ``/health`` responses.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import List, Optional

from production_stack_tpu.utils import (
    SingletonMeta,
    parse_comma_separated_urls,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

DEFAULT_POLL_INTERVAL_S = 10.0


@dataclass
class DynamicRouterConfig:
    """The hot-reloadable subset of router configuration."""

    service_discovery: str = "static"
    routing_logic: str = "roundrobin"
    static_backends: List[str] = field(default_factory=list)
    static_models: List[str] = field(default_factory=list)
    # Per-backend engine roles ("prefill"/"decode"/"both"), aligned
    # with static_backends — the fleet manager registers disagg pools
    # through this file, so roles must survive the hot-reload path.
    static_roles: List[str] = field(default_factory=list)
    # Build revision per backend, aligned with static_backends — the
    # fleet rollout controller labels members so per-server gauges and
    # stacktop can tell the canary revision from the stable one.
    static_revisions: List[str] = field(default_factory=list)
    # url -> dispatch traffic share for baking canaries (docs/fleet.md).
    canary_weights: dict = field(default_factory=dict)
    # Backends in a migrate-mode drain: their mid-stream deaths are
    # planned migrations, not crashes (resume outcome "migrated").
    migrating: List[str] = field(default_factory=list)
    # Per-pool rollout snapshot for /cluster/status and stacktop.
    rollout_status: dict = field(default_factory=dict)
    session_key: Optional[str] = None
    k8s_namespace: str = "default"
    k8s_port: int = 8000
    k8s_label_selector: str = ""

    @classmethod
    def from_json(cls, text: str) -> "DynamicRouterConfig":
        raw = json.loads(text)
        backends = raw.get("static_backends", "")
        models = raw.get("static_models", "")
        roles = raw.get("static_roles", "")
        revisions = raw.get("static_revisions", "")
        if isinstance(backends, list):
            backends = ",".join(backends)
        # Same validation/normalization as the --static-backends CLI path.
        backends = parse_comma_separated_urls(backends)
        if isinstance(models, str):
            models = [m.strip() for m in models.split(",") if m.strip()]
        if isinstance(roles, str):
            roles = [r.strip() for r in roles.split(",") if r.strip()]
        if isinstance(revisions, str):
            revisions = [r.strip() for r in revisions.split(",")
                         if r.strip()]
        return cls(
            service_discovery=raw.get("service_discovery", "static"),
            routing_logic=raw.get("routing_logic", "roundrobin"),
            static_backends=backends,
            static_models=models,
            static_roles=roles,
            static_revisions=[str(r) for r in revisions],
            canary_weights={
                str(url): float(w)
                for url, w in (raw.get("canary_weights") or {}).items()},
            migrating=[str(u) for u in raw.get("migrating", [])],
            rollout_status=raw.get("rollout_status") or {},
            session_key=raw.get("session_key"),
            k8s_namespace=raw.get("k8s_namespace", "default"),
            k8s_port=int(raw.get("k8s_port", 8000)),
            k8s_label_selector=raw.get("k8s_label_selector", ""),
        )

    def to_dict(self) -> dict:
        return {
            "service_discovery": self.service_discovery,
            "routing_logic": self.routing_logic,
            "static_backends": self.static_backends,
            "static_models": self.static_models,
            "static_roles": self.static_roles,
            "static_revisions": self.static_revisions,
            "canary_weights": self.canary_weights,
            "migrating": self.migrating,
            "rollout_status": self.rollout_status,
            "session_key": self.session_key,
        }


def apply_dynamic_config(config: DynamicRouterConfig) -> None:
    from production_stack_tpu.router.routing.logic import (
        reconfigure_routing_logic,
        set_canary_weights,
        set_migrating_urls,
    )
    from production_stack_tpu.router.service_discovery import (
        reconfigure_service_discovery,
    )

    if config.service_discovery == "static":
        reconfigure_service_discovery(
            "static", urls=config.static_backends,
            models=config.static_models or None,
            roles=config.static_roles or None,
            revisions=config.static_revisions or None,
        )
    else:
        reconfigure_service_discovery(
            "k8s", namespace=config.k8s_namespace, port=config.k8s_port,
            label_selector=config.k8s_label_selector,
        )
    reconfigure_routing_logic(
        config.routing_logic, session_key=config.session_key
    )
    set_canary_weights(config.canary_weights)
    set_migrating_urls(config.migrating)


class DynamicConfigWatcher(metaclass=SingletonMeta):
    """Polls the dynamic-config JSON file and applies changes."""

    def __init__(self, config_path: Optional[str] = None,
                 poll_interval_s: float = DEFAULT_POLL_INTERVAL_S):
        if getattr(self, "_initialized", False):
            return
        if config_path is None:
            raise ValueError("DynamicConfigWatcher needs config_path")
        self.config_path = config_path
        self.poll_interval_s = poll_interval_s
        self._digest: Optional[str] = None
        self._current: Optional[DynamicRouterConfig] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dynamic-config-watcher"
        )
        self._thread.start()
        self._initialized = True

    def _run(self) -> None:
        # First tick immediately so a pre-existing file applies at startup.
        while True:
            self.check_and_apply()
            if self._stop.wait(self.poll_interval_s):
                return

    def check_and_apply(self) -> bool:
        """Returns True if a new config was applied."""
        try:
            with open(self.config_path) as f:
                text = f.read()
        except FileNotFoundError:
            return False
        except OSError as e:
            logger.warning("Cannot read dynamic config: %s", e)
            return False
        digest = hashlib.sha256(text.encode()).hexdigest()
        if digest == self._digest:
            return False
        try:
            config = DynamicRouterConfig.from_json(text)
            apply_dynamic_config(config)
        except Exception as e:
            logger.error("Invalid dynamic config %s: %s",
                         self.config_path, e)
            self._digest = digest  # don't retry a bad file every tick
            return False
        self._digest = digest
        self._current = config
        logger.info("Applied dynamic config from %s", self.config_path)
        return True

    def get_current_config(self) -> Optional[DynamicRouterConfig]:
        return self._current

    def get_health(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()


def initialize_dynamic_config_watcher(
        config_path: str,
        poll_interval_s: float = DEFAULT_POLL_INTERVAL_S
) -> DynamicConfigWatcher:
    return DynamicConfigWatcher(config_path, poll_interval_s)


def get_dynamic_config_watcher() -> Optional[DynamicConfigWatcher]:
    if DynamicConfigWatcher in SingletonMeta._instances:
        return SingletonMeta._instances[DynamicConfigWatcher]
    return None
