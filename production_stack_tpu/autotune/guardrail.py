"""Drift-sentinel guardrail for self-tuning controllers.

A controller that keeps "optimizing" while the engine is actually
regressing is worse than a static knob: it chases noise and amplifies
the regression. The guardrail watches the same signals an operator
would page on — the perf-drift sentinel's per-phase flags
(``vllm:perf_drift``-family, obs/drift.py) and the 5-minute SLO burn
rate — and when either degrades, it FREEZES every controller whose
applied decisions fall inside the recent blame window. Frozen state
is latched (``vllm:autotune_frozen{controller}`` stays 1) until an
operator resets it via ``POST /autotune/reset``; a frozen controller
keeps observing and span-logging in shadow, but never applies again.

Signals are injected as callables so the same guardrail serves the
engine loop (observatory step-time medians), the fleet controller
(autoscaler one-scrape burn rate) and the tests (fake everything,
fake clock). See docs/autotuning.md.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


class DriftGuardrail:
    """Freeze controllers whose recent decisions correlate with a
    perf-drift flip or a rising SLO burn.

    ``drift_flags`` returns ``{phase: 0.0|1.0}`` (a flag going
    0 -> 1 between scans is a trip); ``burn_rate`` returns the
    current 5m burn (a rise to/above ``burn_threshold`` between
    scans is a trip). Either may be None/empty — absent signals
    never trip."""

    def __init__(self, freeze_window_s: float = 30.0,
                 burn_threshold: float = 1.0,
                 drift_flags: Optional[
                     Callable[[], Dict[str, float]]] = None,
                 burn_rate: Optional[Callable[[], float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.freeze_window_s = float(freeze_window_s)
        self.burn_threshold = float(burn_threshold)
        self.drift_flags = drift_flags
        self.burn_rate = burn_rate
        self.clock = clock
        self._last_flags: Dict[str, float] = {}
        self._last_burn: Optional[float] = None
        # controller -> wall time of its most recent APPLIED decision
        # (shadow decisions carry no blame: they changed nothing).
        self._recent: Dict[str, float] = {}
        # controller -> freeze time; membership IS the latch.
        self._frozen: Dict[str, float] = {}

    def note_applied(self, controller: str,
                     now: Optional[float] = None) -> None:
        self._recent[controller] = (self.clock() if now is None
                                    else now)

    def scan(self, now: Optional[float] = None) -> List[str]:
        """Evaluate the signals once; returns newly frozen names."""
        now = self.clock() if now is None else now
        tripped = self._tripped()
        if not tripped:
            return []
        newly: List[str] = []
        for name, ts in self._recent.items():
            if (now - ts <= self.freeze_window_s
                    and name not in self._frozen):
                self._frozen[name] = now
                newly.append(name)
        return newly

    def _tripped(self) -> bool:
        tripped = False
        flags: Dict[str, float] = {}
        if self.drift_flags is not None:
            try:
                flags = dict(self.drift_flags() or {})
            except Exception:
                flags = {}
            for phase, val in flags.items():
                if val and not self._last_flags.get(phase, 0.0):
                    tripped = True
            self._last_flags = flags
        if self.burn_rate is not None:
            try:
                burn = float(self.burn_rate())
            except Exception:
                burn = None
            if burn is not None:
                if (self._last_burn is not None
                        and burn > self._last_burn
                        and burn >= self.burn_threshold):
                    tripped = True
                self._last_burn = burn
        return tripped

    def is_frozen(self, controller: str) -> bool:
        return controller in self._frozen

    def frozen(self) -> Dict[str, float]:
        """{controller: freeze wall time} for every latched freeze."""
        return dict(self._frozen)

    def reset(self, controller: Optional[str] = None) -> List[str]:
        """Operator reset: unlatch one controller (or all). The blame
        window restarts too, so the next scan cannot re-freeze on the
        decisions that caused the trip."""
        if controller is None:
            cleared = sorted(self._frozen)
            self._frozen.clear()
            self._recent.clear()
            return cleared
        if controller in self._frozen:
            del self._frozen[controller]
            self._recent.pop(controller, None)
            return [controller]
        return []
