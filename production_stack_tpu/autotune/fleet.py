"""Fleet-side self-tuning: the prefill-vs-decode pool split.

Disaggregated fleets fix the prefill/decode replica split in the
fleet spec, but the profitable split follows the workload: a burst of
long prompts starves the prefill pool while decode replicas idle, and
vice versa. :class:`PoolSplitController` rides the autoscaler's
existing one-scrape signal path — the per-phase request-time means
the router already exports (``vllm:engine_request_prefill_time_mean_
seconds`` / ``..._decode_...``, docs/observability.md) — and biases
one replica of headroom between a prefill-role pool and a decode-role
pool when the phase-time ratio drifts from its own baseline.

It runs AFTER the per-pool :class:`PoolAutoscaler` in
``FleetManager.autoscale_once`` (SLO target tracking keeps priority;
the split only spends headroom inside each pool's min/max band), and
it carries the same guardrail semantics as the engine-side
controllers: a rising 5m SLO burn within the freeze window of a move
freezes the controller, latched until reset. Off unless the fleet
spec sets ``autotune_pool_split`` (docs/fleet.md).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from production_stack_tpu.autotune.guardrail import DriftGuardrail
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class PoolSplitController:
    """Bias the replica split between one prefill-role and one
    decode-role pool by the phase-time ratio's drift from its own
    baseline (first complete observation)."""

    name = "pool_split"

    def __init__(self, ratio_band: float = 0.5,
                 cooldown_s: float = 60.0,
                 freeze_window_s: float = 30.0,
                 burn_threshold: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.ratio_band = float(ratio_band)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._baseline: Optional[float] = None
        self._last_move = -float("inf")
        self._burn = -1.0
        self.guardrail = DriftGuardrail(
            freeze_window_s=freeze_window_s,
            burn_threshold=burn_threshold,
            burn_rate=lambda: self._burn, clock=clock)
        self.moves_total = 0

    @property
    def frozen(self) -> bool:
        return self.guardrail.is_frozen(self.name)

    def reset(self) -> None:
        self.guardrail.reset(self.name)

    def rebalance(self, pools, signals_by_pool: Dict[str, object],
                  desired: Dict[str, int]) -> Dict[str, int]:
        """One tick: returns the (possibly adjusted) desired counts.

        ``pools`` are PoolSpec objects; ``signals_by_pool`` maps pool
        name -> PoolSignals from the same scrape the autoscalers just
        consumed; ``desired`` is the post-autoscale target map (not
        mutated — a copy is returned)."""
        out = dict(desired)
        prefill = next((p for p in pools if p.role == "prefill"), None)
        decode = next((p for p in pools if p.role == "decode"), None)
        if prefill is None or decode is None:
            return out
        now = self.clock()
        # Guardrail first: burn is fleet-wide, mirrored in every
        # pool's signals.
        for sig in signals_by_pool.values():
            burn = getattr(sig, "slo_burn_rate", -1.0)
            if burn >= 0:
                self._burn = max(self._burn, burn)
        self.guardrail.scan(now)
        if self.frozen:
            return out
        pmean = self._phase_mean(signals_by_pool, "prefill_time_mean_s")
        dmean = self._phase_mean(signals_by_pool, "decode_time_mean_s")
        if pmean <= 0 or dmean <= 0:
            return out
        ratio = pmean / dmean
        if self._baseline is None:
            self._baseline = ratio
            return out
        if now - self._last_move < self.cooldown_s:
            return out
        drift = ratio / self._baseline
        src = dst = None
        if drift > 1.0 + self.ratio_band:
            # Prefill phase got relatively slower: shift headroom in.
            src, dst = decode, prefill
        elif drift < 1.0 / (1.0 + self.ratio_band):
            src, dst = prefill, decode
        if src is None:
            return out
        if (out[src.name] - 1 < src.min_replicas
                or out[dst.name] + 1 > dst.max_replicas):
            return out
        out[src.name] -= 1
        out[dst.name] += 1
        self._last_move = now
        self.moves_total += 1
        self.guardrail.note_applied(self.name, now)
        logger.info(
            "autotune pool split: %s -> %s (phase ratio %.2f, "
            "baseline %.2f)", src.name, dst.name, ratio,
            self._baseline)
        return out

    @staticmethod
    def _phase_mean(signals_by_pool: Dict[str, object],
                    attr: str) -> float:
        worst = -1.0
        for sig in signals_by_pool.values():
            worst = max(worst, getattr(sig, attr, -1.0))
        return worst
