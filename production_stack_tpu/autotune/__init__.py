"""Self-tuning serving: host-side controllers that close the
telemetry -> knob loop (docs/autotuning.md).

Engine-side controllers tick from the engine loop and read the
metrics registry / observatory directly; the fleet-side pool-split
controller rides the autoscaler's one-scrape signal path. Everything
is off by default (``--autotune off|shadow|on``)."""

from production_stack_tpu.autotune.controller import (
    MODES, Autotuner, Controller)
from production_stack_tpu.autotune.controllers import (
    CheckpointIntervalController, KVEconController,
    PrefillBudgetController, QoSShedController, SpecKController,
    build_engine_controllers, observatory_drift_flags)
from production_stack_tpu.autotune.fleet import PoolSplitController
from production_stack_tpu.autotune.guardrail import DriftGuardrail

__all__ = [
    "MODES",
    "Autotuner",
    "Controller",
    "DriftGuardrail",
    "SpecKController",
    "PrefillBudgetController",
    "KVEconController",
    "CheckpointIntervalController",
    "QoSShedController",
    "PoolSplitController",
    "build_engine_controllers",
    "observatory_drift_flags",
]
