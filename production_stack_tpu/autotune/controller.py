"""Self-tuning controller framework (docs/autotuning.md).

Host-side closed-loop tuning: every knob a controller touches rides a
non-shape input or an already-compiled bucket lattice, so a decision
can never trigger an XLA recompile — the compile-ledger assertion in
``bench.py --worker drift`` holds the framework to that.

One ``Autotuner`` owns a set of ``Controller`` objects and ticks them
on a bounded cadence from the engine loop (or any host loop). Each
tick runs the controller's observe -> propose -> apply pipeline:

- ``observe()`` reads the controller's telemetry signal (windowed —
  controllers keep their own last-snapshot state); None = no signal
  yet, skip this tick;
- ``propose(signal)`` turns the signal into a target knob value
  (None = hold); the framework clamps it to the controller's
  [lo, hi] band and drops it inside the relative dead-band;
- ``apply(target)`` writes the knob — only in ``on`` mode and only
  while the drift guardrail has not frozen the controller.

Every surviving decision — applied or shadow — is emitted as an
``autotune_decision`` span event on a synthetic engine span (the
watchdog-trip pattern), which is the whole A/B story: run ``shadow``
next to ``on`` and diff the span logs.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from production_stack_tpu.autotune.guardrail import DriftGuardrail
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

MODES = ("off", "shadow", "on")


class Controller:
    """One closed-loop knob: a name, a clamp band, and the
    observe/propose/apply triplet. Subclasses hold references to the
    live objects whose attributes they tune (scheduler, configs, the
    KV summary tracker) — all host-side dataclass fields read fresh
    each step, never compiled shapes."""

    name = "controller"

    def __init__(self, lo: float, hi: float):
        if lo > hi:
            raise ValueError(
                f"controller {self.name}: lo {lo} > hi {hi}")
        self.lo = float(lo)
        self.hi = float(hi)

    def enabled(self) -> bool:
        """False when the tuned feature is off (no spec decoding, no
        checkpointing, ...) — the autotuner then drops the
        controller entirely."""
        return True

    def observe(self) -> Optional[float]:
        raise NotImplementedError

    def current(self) -> float:
        raise NotImplementedError

    def propose(self, signal: float) -> Optional[float]:
        raise NotImplementedError

    def apply(self, target: float) -> None:
        raise NotImplementedError

    def clamp(self, value: float) -> float:
        return min(self.hi, max(self.lo, value))


class Autotuner:
    """Ticks controllers on a bounded cadence and enforces the shared
    policy: mode gating, dead-band, clamps, guardrail freezes, span
    emission, and the decision/knob counters behind the
    ``vllm:autotune_*`` metrics."""

    def __init__(self, config, controllers: List[Controller],
                 tracer=None,
                 clock: Callable[[], float] = time.monotonic,
                 drift_flags: Optional[
                     Callable[[], Dict[str, float]]] = None,
                 burn_rate: Optional[Callable[[], float]] = None):
        self.config = config
        self.mode = config.mode
        selected = _parse_selection(config.controllers)
        self.controllers = [
            c for c in controllers
            if c.enabled() and (selected is None or c.name in selected)
        ]
        self.tracer = tracer
        self.clock = clock
        self.guardrail = DriftGuardrail(
            freeze_window_s=config.freeze_window_s,
            burn_threshold=config.burn_threshold,
            drift_flags=drift_flags, burn_rate=burn_rate, clock=clock)
        self._next_tick: Optional[float] = None
        self._lock = threading.Lock()
        self.decisions_total: Dict[str, int] = {
            c.name: 0 for c in self.controllers}
        self.applied_total: Dict[str, int] = {
            c.name: 0 for c in self.controllers}

    # -- cadence ------------------------------------------------------------

    def maybe_tick(self) -> bool:
        """Called from the host loop every iteration; runs one tick
        when the cadence interval has elapsed. Cheap no-op in
        ``off`` mode and between ticks."""
        if self.mode == "off" or not self.controllers:
            return False
        now = self.clock()
        if self._next_tick is not None and now < self._next_tick:
            return False
        self._next_tick = now + self.config.interval_s
        self.tick(now)
        return True

    def tick(self, now: Optional[float] = None) -> None:
        """One observe -> propose -> apply pass over every
        controller. Exceptions in a controller are contained — a
        broken tuner must never take down the engine loop."""
        now = self.clock() if now is None else now
        with self._lock:
            newly = self.guardrail.scan(now)
            for name in newly:
                logger.warning(
                    "autotune: controller %s FROZEN (perf drift / "
                    "burn rise within %.0fs of its decisions); "
                    "latched until POST /autotune/reset",
                    name, self.guardrail.freeze_window_s)
            for c in self.controllers:
                try:
                    self._tick_one(c, now)
                except Exception:
                    logger.exception(
                        "autotune: controller %s tick failed", c.name)

    def _tick_one(self, c: Controller, now: float) -> None:
        signal = c.observe()
        if signal is None:
            return
        target = c.propose(signal)
        if target is None:
            return
        target = c.clamp(target)
        current = c.current()
        if self._within_dead_band(current, target):
            return
        frozen = self.guardrail.is_frozen(c.name)
        applied = False
        if self.mode == "on" and not frozen:
            c.apply(target)
            applied = True
            self.applied_total[c.name] += 1
            self.guardrail.note_applied(c.name, now)
        self.decisions_total[c.name] += 1
        self._emit_span(c, signal, current, target, applied, frozen)

    def _within_dead_band(self, current: float,
                          target: float) -> bool:
        band = self.config.dead_band * max(abs(current), 1e-9)
        return abs(target - current) <= band

    def _emit_span(self, c: Controller, signal: float,
                   current: float, target: float, applied: bool,
                   frozen: bool) -> None:
        if self.tracer is None:
            return
        # Synthetic span (the watchdog-trip pattern): decisions show
        # up in traceview next to the requests they affected.
        sid = f"autotune-{uuid.uuid4().hex[:12]}"
        self.tracer.start(sid, prompt_tokens=0)
        self.tracer.event(
            sid, "autotune_decision", controller=c.name,
            mode=self.mode, signal=round(float(signal), 6),
            current=round(float(current), 6),
            target=round(float(target), 6),
            applied=applied, frozen=frozen)
        self.tracer.finish(sid, reason="autotune")

    # -- observability surface ----------------------------------------------

    def active_count(self) -> int:
        """Controllers currently allowed to act: 0 in off/shadow
        mode (nothing is being applied), unfrozen count in on."""
        if self.mode != "on":
            return 0
        return sum(1 for c in self.controllers
                   if not self.guardrail.is_frozen(c.name))

    def frozen_flags(self) -> Dict[str, bool]:
        return {c.name: self.guardrail.is_frozen(c.name)
                for c in self.controllers}

    def knob_values(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for c in self.controllers:
            try:
                out[c.name] = float(c.current())
            except Exception:
                out[c.name] = 0.0
        return out

    def status(self) -> dict:
        """The GET /autotune/status payload."""
        knobs = self.knob_values()
        return {
            "mode": self.mode,
            "interval_s": self.config.interval_s,
            "active_controllers": self.active_count(),
            "controllers": [
                {
                    "name": c.name,
                    "knob": knobs.get(c.name, 0.0),
                    "lo": c.lo,
                    "hi": c.hi,
                    "frozen": self.guardrail.is_frozen(c.name),
                    "decisions": self.decisions_total[c.name],
                    "applied": self.applied_total[c.name],
                }
                for c in self.controllers
            ],
        }

    def reset(self, controller: Optional[str] = None) -> List[str]:
        with self._lock:
            return self.guardrail.reset(controller)


def _parse_selection(spec: str) -> Optional[set]:
    """``--autotune-controllers`` value -> name set (None = all)."""
    spec = (spec or "all").strip()
    if spec in ("", "all"):
        return None
    return {name.strip() for name in spec.split(",") if name.strip()}
