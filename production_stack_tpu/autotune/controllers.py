"""The engine-side controller catalog (docs/autotuning.md).

Five closed loops over knobs the stack already reads live each step —
per-sequence speculative k, the unified-step prefill token budget,
kvecon admission/watermarks, the checkpoint interval, and the QoS
shed gate. Every knob is host-side state (dataclass fields, scheduler
attributes, per-sequence caps): no controller decision can change a
compiled program shape, so tuning is recompile-free by construction.

``build_engine_controllers(server)`` wires the catalog to a live
EngineServer; tests construct controllers directly against fakes.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from production_stack_tpu.autotune.controller import Controller


class HistogramWindow:
    """Windowed quantiles over an engine/metrics.py Histogram: diffs
    the cumulative bucket counts between calls and returns the bucket
    upper edge at the requested rank — cheap, host-side, and exactly
    the resolution the dead-band needs."""

    def __init__(self, hist):
        self.hist = hist
        self._counts = list(hist.counts)
        self._n = hist.n

    def quantile(self, q: float) -> Tuple[Optional[float], int]:
        """(approximate q-quantile over the window, window count)."""
        counts = list(self.hist.counts)
        n = self.hist.n
        delta = [c - p for c, p in zip(counts, self._counts)]
        dn = n - self._n
        self._counts, self._n = counts, n
        if dn <= 0:
            return None, 0
        rank = q * dn
        cum = 0
        for i, c in enumerate(delta):
            cum += c
            if cum >= rank and c > 0:
                if i < len(self.hist.buckets):
                    return self.hist.buckets[i], dn
                break
        # +inf tail: report past the last finite edge.
        return self.hist.buckets[-1] * 2.0, dn


class SpecKController(Controller):
    """(1) Per-sequence speculative k from observed per-seq
    acceptance. Shrinks a row's draft cap when its windowed
    acceptance is low (wasted verify slots), grows it back toward the
    ``--speculative-k`` ceiling when acceptance is high. The cap
    rides ``seq.spec_k_cap`` — a bound the proposer applies to the
    existing non-shape draft inputs, so the verify program never
    recompiles. Knob scalar = mean cap over running rows."""

    name = "spec_k"
    LOW_ACCEPT = 0.4
    HIGH_ACCEPT = 0.7
    MIN_WINDOW_DRAFTED = 4

    def __init__(self, engine, cfg):
        super().__init__(lo=cfg.min_spec_k,
                         hi=max(cfg.min_spec_k,
                                engine.config.scheduler.speculative_k))
        self.engine = engine
        self._seen: Dict[str, Tuple[int, int]] = {}
        self._window: Dict[str, Tuple[int, int]] = {}

    def enabled(self) -> bool:
        return self.engine.config.scheduler.speculative_k > 0

    def observe(self) -> Optional[float]:
        running = list(self.engine.scheduler.running)
        total_d = total_a = 0
        self._window = {}
        seen_now: Dict[str, Tuple[int, int]] = {}
        for seq in running:
            d = seq.spec_drafted_total
            a = seq.spec_accepted_total
            pd, pa = self._seen.get(seq.seq_id, (0, 0))
            seen_now[seq.seq_id] = (d, a)
            wd, wa = d - pd, a - pa
            if wd > 0:
                self._window[seq.seq_id] = (wd, wa)
                total_d += wd
                total_a += wa
        self._seen = seen_now  # finished rows fall out of the window
        if total_d < self.MIN_WINDOW_DRAFTED:
            return None
        return total_a / total_d

    def current(self) -> float:
        caps = [seq.spec_k_cap
                for seq in self.engine.scheduler.running
                if seq.spec_k_cap is not None]
        if not caps:
            return self.hi
        return sum(caps) / len(caps)

    def propose(self, signal: float) -> Optional[float]:
        cur = self.current()
        if signal < self.LOW_ACCEPT:
            return cur - 1.0
        if signal > self.HIGH_ACCEPT:
            return cur + 1.0
        return None

    def apply(self, target: float) -> None:
        # Per-sequence: each row moves by ITS OWN windowed acceptance;
        # rows without enough window data drift toward the mean
        # target so new arrivals converge too.
        for seq in self.engine.scheduler.running:
            cap = (seq.spec_k_cap if seq.spec_k_cap is not None
                   else int(self.hi))
            wd, wa = self._window.get(seq.seq_id, (0, 0))
            if wd >= 2:
                acc = wa / wd
                if acc < self.LOW_ACCEPT:
                    cap -= 1
                elif acc > self.HIGH_ACCEPT:
                    cap += 1
            elif target > cap:
                cap += 1
            elif target < cap:
                cap -= 1
            seq.spec_k_cap = int(self.clamp(cap))


class PrefillBudgetController(Controller):
    """(2) Unified-step prefill token budget from decode ITL
    headroom. While the windowed ITL p99 has slack against the target
    (``--autotune-target-itl-ms``), grow mixed-step prefill admission
    one chunk at a time toward the static full-bandwidth budget;
    shrink when p99 exceeds the target. The budget is a host-side
    scheduler attribute that only narrows chunk selection inside the
    already-compiled ragged shape."""

    name = "prefill_budget"
    MIN_WINDOW_TOKENS = 8

    def __init__(self, engine, cfg):
        sched = engine.config.scheduler
        self.chunk = sched.prefill_chunk_size
        super().__init__(
            lo=self.chunk,
            hi=self.chunk * sched.prefill_batch_size)
        self.engine = engine
        self.target_itl_s = cfg.target_itl_ms / 1000.0
        self._win = HistogramWindow(engine.metrics.itl)

    def enabled(self) -> bool:
        return (self.engine.config.scheduler.unified_step
                and self.target_itl_s > 0)

    def observe(self) -> Optional[float]:
        p99, n = self._win.quantile(0.99)
        if p99 is None or n < self.MIN_WINDOW_TOKENS:
            return None
        return p99

    def current(self) -> float:
        return float(self.engine.scheduler.mixed_prefill_budget)

    def propose(self, p99: float) -> Optional[float]:
        cur = self.current()
        if p99 > self.target_itl_s:
            return cur - self.chunk
        if p99 < 0.5 * self.target_itl_s:
            return cur + self.chunk
        return None

    def apply(self, target: float) -> None:
        self.engine.scheduler.mixed_prefill_budget = int(
            self.clamp(target))


class KVEconController(Controller):
    """(3) kvecon admission floor and offload-pool watermarks from
    measured hit rate vs free-page headroom. Under page pressure with
    a weak windowed hit rate, tighten the summary's admission floor
    (fewer speculative hot-chain advertisements) and pull the host
    pool watermarks down so eviction runs earlier; with ample
    headroom and a paying hit rate, relax both back toward the
    configured statics. Knob scalar = ``admit_hits``."""

    name = "kvecon"
    LOW_HEADROOM = 0.15
    HIGH_HEADROOM = 0.5
    PAYING_HIT_RATE = 0.2
    WATERMARK_STEP = 0.05
    WATERMARK_FLOOR = 0.5

    def __init__(self, engine, kv_summary, cfg):
        super().__init__(lo=1.0, hi=8.0)
        self.engine = engine
        self.kv_summary = kv_summary
        self._prev_hits = 0
        self._prev_queries = 0
        self._hit_rate = 0.0

    def observe(self) -> Optional[float]:
        cm = self.engine.cache_manager
        total = max(1, cm.config.num_pages - 1)
        headroom = cm.num_free_pages / total
        hits = cm.prefix_hit_tokens
        queries = cm.prefix_query_tokens
        dq = queries - self._prev_queries
        dh = hits - self._prev_hits
        self._prev_hits, self._prev_queries = hits, queries
        if dq > 0:
            self._hit_rate = dh / dq
        return headroom

    def current(self) -> float:
        return float(self.kv_summary.admit_hits)

    def propose(self, headroom: float) -> Optional[float]:
        cur = self.current()
        if headroom < self.LOW_HEADROOM:
            return cur + 1.0
        if (headroom > self.HIGH_HEADROOM
                and self._hit_rate >= self.PAYING_HIT_RATE):
            return cur - 1.0
        return None

    def apply(self, target: float) -> None:
        tightening = target > self.current()
        self.kv_summary.admit_hits = int(self.clamp(target))
        offload = self.engine.offload
        pool = getattr(offload, "host", None) if offload else None
        if pool is None:
            return
        kve = self.engine.config.kvecon
        step = (-self.WATERMARK_STEP if tightening
                else self.WATERMARK_STEP)
        high = min(kve.watermark_high,
                   max(self.WATERMARK_FLOOR,
                       pool.watermark_high + step))
        low = min(kve.watermark_low,
                  max(self.WATERMARK_FLOOR - self.WATERMARK_STEP,
                      pool.watermark_low + step))
        pool.watermark_high = max(high, low)
        pool.watermark_low = min(high, low)


class CheckpointIntervalController(Controller):
    """(4) Checkpoint interval from observed crash/resume rates. A
    resume arriving means a stream actually crashed somewhere and had
    to replay from its last checkpoint — halve the interval so the
    next crash loses less. Quiet windows let the interval relax back
    up (doubling) toward the configured ceiling, shedding the
    ship-per-N-tokens overhead."""

    name = "checkpoint_interval"
    QUIET_TICKS_TO_RELAX = 5

    def __init__(self, engine, cfg):
        super().__init__(lo=cfg.min_checkpoint_interval_tokens,
                         hi=cfg.max_checkpoint_interval_tokens)
        self.engine = engine
        self._prev_resumes: Optional[int] = None
        self._quiet_ticks = 0

    def enabled(self) -> bool:
        return self.engine.config.checkpoint_interval_tokens > 0

    def observe(self) -> Optional[float]:
        resumes = self.engine.stream_resumes
        prev, self._prev_resumes = self._prev_resumes, resumes
        if prev is None:
            return None
        return float(resumes - prev)

    def current(self) -> float:
        return float(self.engine.config.checkpoint_interval_tokens)

    def propose(self, resume_delta: float) -> Optional[float]:
        cur = self.current()
        if resume_delta > 0:
            self._quiet_ticks = 0
            return cur / 2.0
        self._quiet_ticks += 1
        if self._quiet_ticks >= self.QUIET_TICKS_TO_RELAX:
            self._quiet_ticks = 0
            return cur * 2.0
        return None

    def apply(self, target: float) -> None:
        self.engine.config.checkpoint_interval_tokens = int(
            self.clamp(target))


class QoSShedController(Controller):
    """(5) QoS shed threshold and degrade-ladder clamp from measured
    queue drain rate. A queue that keeps growing while already deep
    means admission outruns drain: pull the shed gate earlier (shed
    sooner, keep interactive latency) and clamp the degrade ladder —
    non-interactive rows lose their speculative slots engine-wide
    (the same ``spec_off`` semantics the router's per-request header
    uses). A draining queue relaxes both back to the configured
    statics."""

    name = "qos_shed"
    DEEP_FRACTION = 0.25
    SHALLOW_FRACTION = 0.1
    STEP = 0.05

    def __init__(self, engine, cfg):
        super().__init__(lo=cfg.min_shed_threshold,
                         hi=engine.config.qos.shed_threshold)
        self.engine = engine
        self._prev_waiting: Optional[int] = None
        self._waiting = 0

    def observe(self) -> Optional[float]:
        waiting = self.engine.scheduler.num_waiting
        prev, self._prev_waiting = self._prev_waiting, waiting
        self._waiting = waiting
        if prev is None:
            return None
        return float(waiting - prev)

    def current(self) -> float:
        return float(self.engine.config.qos.shed_threshold)

    def propose(self, growth: float) -> Optional[float]:
        cur = self.current()
        max_queue = max(1, self.engine.config.scheduler.max_queue_len)
        depth = self._waiting / max_queue
        if growth > 0 and depth > self.DEEP_FRACTION:
            return cur - self.STEP
        if growth <= 0 and depth < self.SHALLOW_FRACTION:
            return cur + self.STEP
        return None

    def apply(self, target: float) -> None:
        value = self.clamp(target)
        self.engine.config.qos.shed_threshold = value
        # Degrade ladder clamp: while the gate sits below the
        # configured static, the engine is in degrade — spend no
        # speculative slack on non-interactive rows.
        self.engine.scheduler.spec_degrade_clamp = (
            value < self.hi - 1e-9)


def build_engine_controllers(server, cfg) -> list:
    """The full catalog wired to a live EngineServer; the Autotuner
    drops entries whose ``enabled()`` says the feature is off."""
    engine = server.engine
    return [
        SpecKController(engine, cfg),
        PrefillBudgetController(engine, cfg),
        KVEconController(engine, server.kv_summary, cfg),
        CheckpointIntervalController(engine, cfg),
        QoSShedController(engine, cfg),
    ]


def observatory_drift_flags(runner, band: float = 0.25):
    """Engine-local drift signal for the guardrail: the first
    non-zero step-time median per kind becomes the baseline; a median
    later exceeding baseline * (1 + band) flags that kind — the same
    median-vs-band shape as the router's perf-drift sentinel
    (obs/drift.py), minus the baseline file."""
    baseline: Dict[str, float] = {}

    def flags() -> Dict[str, float]:
        obs = getattr(runner, "observatory", None)
        if obs is None:
            return {}
        out: Dict[str, float] = {}
        for kind, median in obs.step_time_medians().items():
            if median <= 0:
                continue
            base = baseline.setdefault(kind, median)
            out[kind] = 1.0 if median > base * (1.0 + band) else 0.0
        return out

    return flags
