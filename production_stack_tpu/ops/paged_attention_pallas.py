"""Pallas TPU kernel: paged decode attention.

The decode hot loop reads a sequence's KV pages from HBM and attends a
single query token against them. The XLA reference implementation
(ops/attention.py) gathers the *whole* padded context per step; this
kernel walks the page list instead.

Design (v2 — round 3): the first cut put the page walk in the *grid*
(one tiny BlockSpec DMA per page), which bottlenecked on per-grid-step
overhead: batch x kv_heads x max_pages steps each moving a 2 KB block
made the kernel ~10x slower than the XLA gather on-chip. This version
keeps the whole page walk *inside* one kernel instance:

- grid is just (batch, kv_head) — 64 steps for a B=8, 8-head model,
- the KV cache stays in HBM (``memory_space=HBM``); the kernel issues
  manual double-buffered async DMAs (pltpu.make_async_copy) for a
  *chunk* of pages at a time, overlapping copy-in with compute,
- pages are stored token-minor ([head_dim, page_size]) so one page's
  slice is (sublane, lane)-tile-aligned for DMA — head_dim is rarely
  a lane multiple (64 on 1B-class llamas) and a token-major page
  would need its minor dim padded to 128, which Mosaic rejects for
  HBM slicing — and K arrives pre-transposed for the ``q @ k^T`` MXU
  contraction,
- the page loop is a STATIC unroll over the page-table width with
  ``pl.when`` guards on the row's real chunk count — skipped chunks
  issue no DMAs and run no compute, so work still scales with the
  context actually cached,
- flash-style online softmax accumulated in VMEM scratch,
- matmuls are 2D ``[G, D] x [D, C*P]`` / ``[G, C*P] x [D, C*P]^T``
  contractions (the MXU forms Mosaic supports), with the query-head
  group padded to >=8 sublanes.

The DMA/page-walk machinery is the SHARED layer in
ops/paged_kv_common.py — one definition serves this kernel, the
chunked-prefill kernel and the unified ragged step; only the query
block layout (single token, group padded to a sublane tile) and the
score mask (pure ``pos < kv_len``) live here.

Pages past the sequence length DMA the trash page 0 (the allocator
never hands it out) and are masked; the page-table width is padded to
a multiple of the chunk so page indices never run off the row.

Contract matches ops.attention.paged_attention at T=1; parity is
tested in tests/test_pallas_attention.py (interpret mode) and compiled
lowering in tests/test_pallas_lowering.py.

Replaces: vLLM's paged_attention CUDA kernels (external to the
reference repo; provisioned via its Helm chart
helm/templates/deployment-vllm-multi.yaml), re-thought for TPU's
DMA+VMEM model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from production_stack_tpu.ops.paged_kv_common import (
    NEG_INF,
    cache_alias_map,
    dma_semaphore_shapes,
    hbm_block_spec,
    kv_scratch_shapes,
    make_page_dma,
    pad_page_table,
    passthrough_out_shapes,
    rewrap_cache_outputs,
    run_page_walk,
    unwrap_cache,
    validate_layer_arg,
)

# Minimum sublane count for the query-group axis: fp32 tiles are
# (8, 128), so G < 8 would force degenerate layouts.
_MIN_GROUP = 8

# Pages copied per DMA burst: 4 x 128-token pages = a 512-token KV
# tile per compute step (4 lane tiles per scores matmul).
_PAGES_PER_CHUNK = 4


def _decode_kernel(page_table_ref, kv_lens_ref, layer_ref, q_ref,
                   k_hbm, v_hbm, ks_hbm, vs_hbm,
                   o_ref,
                   k_scratch, v_scratch, ks_scratch, vs_scratch,
                   m_ref, l_ref, acc_ref,
                   sem, ssem, *, page_size: int, pages_per_chunk: int,
                   group_pad: int, head_dim: int, max_pages: int,
                   has_layer: bool, quantized: bool):
    # ks_hbm/vs_hbm carry the per-slot f32 dequant scales of an int8
    # cache (ops/quant_kv.py), pre-reshaped by the wrapper to
    # [.., pages, 1, page_size] so each page's scale row DMAs as the
    # same 2-D (sublane, lane) tile shape as the data pages; they (and
    # their scratch/semaphore) are None for a full-precision cache.
    del group_pad  # sized into the scratch blocks by the wrapper
    b = pl.program_id(0)
    h = pl.program_id(1)
    c = pages_per_chunk
    chunk_tokens = c * page_size
    max_chunks = max_pages // c  # static unroll bound

    kv_len = kv_lens_ref[b]
    num_chunks = (kv_len + chunk_tokens - 1) // chunk_tokens

    issue, wait = make_page_dma(
        b=b, h=h, page_table_ref=page_table_ref, layer_ref=layer_ref,
        k_hbm=k_hbm, v_hbm=v_hbm, ks_hbm=ks_hbm, vs_hbm=vs_hbm,
        k_scratch=k_scratch, v_scratch=v_scratch,
        ks_scratch=ks_scratch, vs_scratch=vs_scratch,
        sem=sem, ssem=ssem, pages_per_chunk=c, page_size=page_size,
        has_layer=has_layer, quantized=quantized,
    )

    # Padded batch rows have kv_len == 0 -> num_chunks == 0: nothing
    # may be issued for them — an unwaited DMA leaks its semaphore
    # signal into the next grid step's waits.
    @pl.when(num_chunks > 0)
    def _warmup():
        issue(0, 0)

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)  # [G_pad, D]

    run_page_walk(
        q=q, kv_len=kv_len, num_chunks=num_chunks,
        max_chunks=max_chunks, chunk_tokens=chunk_tokens,
        head_dim=head_dim, issue=issue, wait=wait,
        k_scratch=k_scratch, v_scratch=v_scratch,
        ks_scratch=ks_scratch, vs_scratch=vs_scratch,
        m_ref=m_ref, l_ref=l_ref, acc_ref=acc_ref,
        mask_fn=lambda token_pos: token_pos < kv_len,
        quantized=quantized,
    )

    o_ref[0, 0] = (acc_ref[...]
                   / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                           v_cache_layer: jnp.ndarray,
                           page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           layer: "jnp.ndarray | int | None" = None,
                           interpret: bool = False) -> jnp.ndarray:
    """Single-token paged attention.

    Args:
      q:           [B, num_q_heads, head_dim]
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size],
                   or the full stacked [L, ...] cache with ``layer``
                   given (scalar; reaches the kernel via SMEM prefetch
                   so no per-layer slice is ever materialized)
      page_table:  [B, max_pages] int32 physical page ids
      kv_lens:     [B] int32 valid cached tokens per sequence
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, num_q_heads, head_dim] for the 4D per-layer cache
    form. For the stacked 5D form returns
    ``(out, k_cache, v_cache)`` — the caches are passed THROUGH the
    kernel via input/output aliasing and the caller must thread them
    (models/llama.py layer loop); this keeps the cache buffer chain
    linear so XLA's copy-insertion never duplicates it.
    """
    has_layer = validate_layer_arg(k_cache_layer, layer)
    (quantized, k_data, v_data,
     k_scale, v_scale, scale_shape) = unwrap_cache(
        k_cache_layer, v_cache_layer)
    layer_arr = jnp.asarray(
        [0 if layer is None else layer], jnp.int32)
    b, num_q_heads, head_dim = q.shape
    num_kv_heads, _, _, page_size = k_data.shape[-4:]
    group = num_q_heads // num_kv_heads
    group_pad = max(group, _MIN_GROUP)
    c = _PAGES_PER_CHUNK

    page_table, max_pages = pad_page_table(page_table, c)

    # [B, KV, G, D] with the group axis padded up to a full sublane
    # tile; padded rows attend to real keys and are sliced off below.
    qg = q.reshape(b, num_kv_heads, group, head_dim)
    if group_pad != group:
        qg = jnp.pad(
            qg, ((0, 0), (0, 0), (0, group_pad - group), (0, 0))
        )

    base_kernel = functools.partial(
        _decode_kernel, page_size=page_size, pages_per_chunk=c,
        group_pad=group_pad, head_dim=head_dim, max_pages=max_pages,
        has_layer=has_layer, quantized=quantized,
    )
    n_cache_in = 4 if quantized else 2
    # Pass-through cache outputs (stacked form) exist only so the
    # caller can thread the cache THROUGH the custom call via
    # input/output aliasing: without it the cache buffer is both a
    # custom-call operand and the target of the next layer's scatter,
    # and XLA's copy-insertion breaks the apparent interference with a
    # full-cache copy per layer (measured ~158 ms/decode-step on v5e
    # for the 1B bench config). The kernel never touches them, so this
    # adapter strips them (and splices None for the quant-only refs)
    # before calling the canonical kernel signature.
    n_pass = n_cache_in if has_layer else 0

    def kernel(pt, kl, la, q_ref, *refs):
        cache_in = refs[:n_cache_in]
        o_ref = refs[n_cache_in]
        scratch = refs[n_cache_in + 1 + n_pass:]
        if quantized:
            k, v, ks, vs = cache_in
            (k_s, v_s, ks_s, vs_s, m, l, acc, sem, ssem) = scratch
        else:
            k, v = cache_in
            ks = vs = ks_s = vs_s = ssem = None
            (k_s, v_s, m, l, acc, sem) = scratch
        base_kernel(pt, kl, la, q_ref, k, v, ks, vs, o_ref,
                    k_s, v_s, ks_s, vs_s, m, l, acc, sem, ssem)

    hbm = hbm_block_spec()
    scratch_shapes = kv_scratch_shapes(
        head_dim, c, page_size, k_data.dtype, v_data.dtype, quantized)
    scratch_shapes += [
        pltpu.VMEM((group_pad, 1), jnp.float32),  # m
        pltpu.VMEM((group_pad, 1), jnp.float32),  # l
        pltpu.VMEM((group_pad, head_dim), jnp.float32),  # acc
    ]
    scratch_shapes += dma_semaphore_shapes(c, quantized)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # page_table, kv_lens, layer
        grid=(b, num_kv_heads),
        in_specs=[
            # q block: one sequence's query group for one kv head.
            pl.BlockSpec(
                (1, 1, group_pad, head_dim),
                lambda bi, hi, pt, kl, la: (bi, hi, 0, 0),
            ),
            # Full KV cache (and int8 scales) stays in HBM; the kernel
            # DMAs pages itself.
        ] + [hbm] * n_cache_in,
        out_specs=[
            pl.BlockSpec(
                (1, 1, group_pad, head_dim),
                lambda bi, hi, pt, kl, la: (bi, hi, 0, 0),
            ),
        ] + [hbm] * n_pass,
        scratch_shapes=scratch_shapes,
    )

    out_shape = [jax.ShapeDtypeStruct(
        (b, num_kv_heads, group_pad, head_dim), q.dtype)]
    operands = [page_table, kv_lens, layer_arr, qg, k_data, v_data]
    if quantized:
        operands += [k_scale, v_scale]
    if has_layer:
        out_shape += passthrough_out_shapes(
            k_data, v_data, k_scale, v_scale, quantized)
    aliases = cache_alias_map(3, n_cache_in, has_layer)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    out = res[0][:, :, :group].reshape(b, num_q_heads, head_dim)
    if has_layer:
        kc, vc = rewrap_cache_outputs(res, scale_shape, quantized)
        return out, kc, vc
    return out
