"""Pallas TPU kernel: paged decode attention.

The decode hot loop reads a sequence's KV pages from HBM and attends a
single query token against them. The XLA reference implementation
(ops/attention.py) gathers the *whole* padded context per step; this
kernel walks the page list instead.

Design (v2 — round 3): the first cut put the page walk in the *grid*
(one tiny BlockSpec DMA per page), which bottlenecked on per-grid-step
overhead: batch x kv_heads x max_pages steps each moving a 2 KB block
made the kernel ~10x slower than the XLA gather on-chip. This version
keeps the whole page walk *inside* one kernel instance:

- grid is just (batch, kv_head) — 64 steps for a B=8, 8-head model,
- the KV cache stays in HBM (``memory_space=HBM``); the kernel issues
  manual double-buffered async DMAs (pltpu.make_async_copy) for a
  *chunk* of pages at a time, overlapping copy-in with compute,
- pages are stored token-minor ([head_dim, page_size]) so one page's
  slice is (sublane, lane)-tile-aligned for DMA — head_dim is rarely
  a lane multiple (64 on 1B-class llamas) and a token-major page
  would need its minor dim padded to 128, which Mosaic rejects for
  HBM slicing — and K arrives pre-transposed for the ``q @ k^T`` MXU
  contraction,
- the page loop is a STATIC unroll over the page-table width with
  ``pl.when`` guards on the row's real chunk count — skipped chunks
  issue no DMAs and run no compute, so work still scales with the
  context actually cached. (A dynamic ``fori_loop`` bound would be
  tighter code, but dynamic trip counts + DMA semaphores push Mosaic
  down a rarely-exercised compilation path — observed hanging the
  AOT compiler on v5e — while the static unroll is the standard
  public-Pallas shape.)
- flash-style online softmax accumulated in VMEM scratch,
- matmuls are 2D ``[G, D] x [D, C*P]`` / ``[G, C*P] x [D, C*P]^T``
  contractions (the MXU forms Mosaic supports), with the query-head
  group padded to >=8 sublanes.

Pages past the sequence length DMA the trash page 0 (the allocator
never hands it out) and are masked; the page-table width is padded to
a multiple of the chunk so page indices never run off the row.

Contract matches ops.attention.paged_attention at T=1; parity is
tested in tests/test_pallas_attention.py (interpret mode) and compiled
lowering in tests/test_pallas_lowering.py.

Replaces: vLLM's paged_attention CUDA kernels (external to the
reference repo; provisioned via its Helm chart
helm/templates/deployment-vllm-multi.yaml), re-thought for TPU's
DMA+VMEM model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from production_stack_tpu.ops.quant_kv import QuantKV

try:  # jax >= 0.5 spelling
    _HBM = pltpu.MemorySpace.HBM
except AttributeError:  # jax 0.4.x: ANY keeps the operand un-blocked in HBM
    _HBM = pltpu.TPUMemorySpace.ANY

NEG_INF = -1e30

# Minimum sublane count for the query-group axis: fp32 tiles are
# (8, 128), so G < 8 would force degenerate layouts.
_MIN_GROUP = 8

# Pages copied per DMA burst: 4 x 128-token pages = a 512-token KV
# tile per compute step (4 lane tiles per scores matmul).
_PAGES_PER_CHUNK = 4


def _decode_kernel(page_table_ref, kv_lens_ref, layer_ref, q_ref,
                   k_hbm, v_hbm, ks_hbm, vs_hbm,
                   o_ref,
                   k_scratch, v_scratch, ks_scratch, vs_scratch,
                   m_ref, l_ref, acc_ref,
                   sem, ssem, *, page_size: int, pages_per_chunk: int,
                   group_pad: int, head_dim: int, max_pages: int,
                   has_layer: bool, quantized: bool):
    # ks_hbm/vs_hbm carry the per-slot f32 dequant scales of an int8
    # cache (ops/quant_kv.py), pre-reshaped by the wrapper to
    # [.., pages, 1, page_size] so each page's scale row DMAs as the
    # same 2-D (sublane, lane) tile shape as the data pages; they (and
    # their scratch/semaphore) are None for a full-precision cache.
    b = pl.program_id(0)
    h = pl.program_id(1)
    c = pages_per_chunk
    chunk_tokens = c * page_size
    max_chunks = max_pages // c  # static unroll bound

    kv_len = kv_lens_ref[b]
    num_chunks = (kv_len + chunk_tokens - 1) // chunk_tokens

    def dma(slot, chunk_idx, j):
        """DMA page j of chunk chunk_idx into buffer ``slot``.

        Scratch is laid out [slot, d, c*P]: each page lands in its own
        128-aligned lane window, so after ``c`` copies the buffer IS
        the [D, chunk_tokens] K/V tile — no in-VMEM reshuffle.
        """
        pid = page_table_ref[b, chunk_idx * c + j]
        if has_layer:
            # Stacked [L, kv, pages, d, p] cache: the layer index
            # arrives as a prefetched scalar, so ONE compiled kernel
            # serves every layer and the caller never slices (an HLO
            # slice feeding a pallas custom-call materializes the
            # whole 10s-of-MB layer as a copy).
            k_src = k_hbm.at[layer_ref[0], h, pid]
            v_src = v_hbm.at[layer_ref[0], h, pid]
        else:
            k_src = k_hbm.at[h, pid]
            v_src = v_hbm.at[h, pid]
        copies = [
            pltpu.make_async_copy(
                k_src,
                k_scratch.at[slot, :, pl.ds(j * page_size, page_size)],
                sem.at[0, slot, j],
            ),
            pltpu.make_async_copy(
                v_src,
                v_scratch.at[slot, :, pl.ds(j * page_size, page_size)],
                sem.at[1, slot, j],
            ),
        ]
        if quantized:
            if has_layer:
                ks_src = ks_hbm.at[layer_ref[0], h, pid]
                vs_src = vs_hbm.at[layer_ref[0], h, pid]
            else:
                ks_src = ks_hbm.at[h, pid]
                vs_src = vs_hbm.at[h, pid]
            copies += [
                pltpu.make_async_copy(
                    ks_src,
                    ks_scratch.at[
                        slot, :, pl.ds(j * page_size, page_size)],
                    ssem.at[0, slot, j],
                ),
                pltpu.make_async_copy(
                    vs_src,
                    vs_scratch.at[
                        slot, :, pl.ds(j * page_size, page_size)],
                    ssem.at[1, slot, j],
                ),
            ]
        return copies

    def issue(slot, chunk_idx):
        for j in range(c):
            for cp in dma(slot, chunk_idx, j):
                cp.start()

    # Padded batch rows have kv_len == 0 -> num_chunks == 0: nothing
    # may be issued for them — an unwaited DMA leaks its semaphore
    # signal into the next grid step's waits.
    @pl.when(num_chunks > 0)
    def _warmup():
        issue(0, 0)

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)  # [G_pad, D]
    scale = 1.0 / (head_dim ** 0.5)

    for chunk_idx in range(max_chunks):
        @pl.when(chunk_idx < num_chunks)
        def _chunk(chunk_idx=chunk_idx):
            slot = chunk_idx % 2

            @pl.when(chunk_idx + 1 < num_chunks)
            def _prefetch():
                issue(1 - slot, chunk_idx + 1)

            for j in range(c):
                for cp in dma(slot, chunk_idx, j):
                    cp.wait()

            k = k_scratch[slot].astype(jnp.float32)  # [D, C*P]
            v = v_scratch[slot].astype(jnp.float32)  # [D, C*P]
            scores = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G_pad, C*P]
            if quantized:
                # Fold the k dequant scales into the logits: exact,
                # since each scale is constant along the contracted
                # head_dim axis. [1, C*P] broadcasts over the group.
                scores = scores * ks_scratch[slot]

            token_pos = (chunk_idx * chunk_tokens
                         + jax.lax.broadcasted_iota(
                             jnp.int32, scores.shape, 1))
            scores = jnp.where(token_pos < kv_len, scores, NEG_INF)

            m_prev = m_ref[...]
            m_new = jnp.maximum(
                m_prev, jnp.max(scores, axis=-1, keepdims=True)
            )
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(scores - m_new)
            l_ref[...] = l_ref[...] * alpha + jnp.sum(
                probs, axis=-1, keepdims=True
            )
            if quantized:
                # v dequant folds into the probabilities before the
                # pv contraction (per-token scales, constant along d).
                probs = probs * vs_scratch[slot]
            # pv: [G_pad, D] — contract the token axis of both sides.
            pv = jax.lax.dot_general(
                probs, v,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = m_new

    o_ref[0, 0] = (acc_ref[...]
                   / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                           v_cache_layer: jnp.ndarray,
                           page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           layer: "jnp.ndarray | int | None" = None,
                           interpret: bool = False) -> jnp.ndarray:
    """Single-token paged attention.

    Args:
      q:           [B, num_q_heads, head_dim]
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size],
                   or the full stacked [L, ...] cache with ``layer``
                   given (scalar; reaches the kernel via SMEM prefetch
                   so no per-layer slice is ever materialized)
      page_table:  [B, max_pages] int32 physical page ids
      kv_lens:     [B] int32 valid cached tokens per sequence
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, num_q_heads, head_dim] for the 4D per-layer cache
    form. For the stacked 5D form returns
    ``(out, k_cache, v_cache)`` — the caches are passed THROUGH the
    kernel via input/output aliasing and the caller must thread them
    (models/llama.py layer loop); this keeps the cache buffer chain
    linear so XLA's copy-insertion never duplicates it.
    """
    has_layer = k_cache_layer.ndim == 5
    if has_layer != (layer is not None):
        raise ValueError(
            "layer index and cache rank must agree: pass a stacked "
            "[L, ...] cache WITH layer, or a per-layer [kv, ...] "
            f"cache WITHOUT (got ndim={k_cache_layer.ndim}, "
            f"layer={layer!r})")
    quantized = isinstance(k_cache_layer, QuantKV)
    if quantized:
        k_data, v_data = k_cache_layer.data, v_cache_layer.data
        scale_shape = k_cache_layer.scale.shape
        # [.., pages, ps] -> [.., pages, 1, ps]: scale DMAs then move
        # 2-D (1, page_size) tiles, the same (sublane, lane) slicing
        # discipline as the data pages. Pure bitcast — last axis is
        # contiguous either way.
        sshape = scale_shape[:-1] + (1, scale_shape[-1])
        k_scale = k_cache_layer.scale.reshape(sshape)
        v_scale = v_cache_layer.scale.reshape(sshape)
    else:
        k_data, v_data = k_cache_layer, v_cache_layer
    layer_arr = jnp.asarray(
        [0 if layer is None else layer], jnp.int32)
    b, num_q_heads, head_dim = q.shape
    num_kv_heads, _, _, page_size = k_data.shape[-4:]
    group = num_q_heads // num_kv_heads
    group_pad = max(group, _MIN_GROUP)
    c = _PAGES_PER_CHUNK

    # Pad the page-table width to a chunk multiple so the DMA loop's
    # page indices stay in range: the static unroll bound is
    # max_pages // c, so every index lands inside the padded table
    # (padded entries point at the trash page and are masked).
    max_pages = page_table.shape[1]
    if max_pages % c:
        page_table = jnp.pad(
            page_table, ((0, 0), (0, c - max_pages % c))
        )
        max_pages = page_table.shape[1]

    # [B, KV, G, D] with the group axis padded up to a full sublane
    # tile; padded rows attend to real keys and are sliced off below.
    qg = q.reshape(b, num_kv_heads, group, head_dim)
    if group_pad != group:
        qg = jnp.pad(
            qg, ((0, 0), (0, 0), (0, group_pad - group), (0, 0))
        )

    base_kernel = functools.partial(
        _decode_kernel, page_size=page_size, pages_per_chunk=c,
        group_pad=group_pad, head_dim=head_dim, max_pages=max_pages,
        has_layer=has_layer, quantized=quantized,
    )
    n_cache_in = 4 if quantized else 2
    # Pass-through cache outputs (stacked form) exist only so the
    # caller can thread the cache THROUGH the custom call via
    # input/output aliasing: without it the cache buffer is both a
    # custom-call operand and the target of the next layer's scatter,
    # and XLA's copy-insertion breaks the apparent interference with a
    # full-cache copy per layer (measured ~158 ms/decode-step on v5e
    # for the 1B bench config). The kernel never touches them, so this
    # adapter strips them (and splices None for the quant-only refs)
    # before calling the canonical kernel signature.
    n_pass = n_cache_in if has_layer else 0

    def kernel(pt, kl, la, q_ref, *refs):
        cache_in = refs[:n_cache_in]
        o_ref = refs[n_cache_in]
        scratch = refs[n_cache_in + 1 + n_pass:]
        if quantized:
            k, v, ks, vs = cache_in
            (k_s, v_s, ks_s, vs_s, m, l, acc, sem, ssem) = scratch
        else:
            k, v = cache_in
            ks = vs = ks_s = vs_s = ssem = None
            (k_s, v_s, m, l, acc, sem) = scratch
        base_kernel(pt, kl, la, q_ref, k, v, ks, vs, o_ref,
                    k_s, v_s, ks_s, vs_s, m, l, acc, sem, ssem)

    hbm = pl.BlockSpec(memory_space=_HBM)
    scratch_shapes = [
        pltpu.VMEM((2, head_dim, c * page_size), k_data.dtype),
        pltpu.VMEM((2, head_dim, c * page_size), v_data.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, 1, c * page_size), jnp.float32),  # k scale
            pltpu.VMEM((2, 1, c * page_size), jnp.float32),  # v scale
        ]
    scratch_shapes += [
        pltpu.VMEM((group_pad, 1), jnp.float32),  # m
        pltpu.VMEM((group_pad, 1), jnp.float32),  # l
        pltpu.VMEM((group_pad, head_dim), jnp.float32),  # acc
        pltpu.SemaphoreType.DMA((2, 2, c)),
    ]
    if quantized:
        scratch_shapes += [pltpu.SemaphoreType.DMA((2, 2, c))]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # page_table, kv_lens, layer
        grid=(b, num_kv_heads),
        in_specs=[
            # q block: one sequence's query group for one kv head.
            pl.BlockSpec(
                (1, 1, group_pad, head_dim),
                lambda bi, hi, pt, kl, la: (bi, hi, 0, 0),
            ),
            # Full KV cache (and int8 scales) stays in HBM; the kernel
            # DMAs pages itself.
        ] + [hbm] * n_cache_in,
        out_specs=[
            pl.BlockSpec(
                (1, 1, group_pad, head_dim),
                lambda bi, hi, pt, kl, la: (bi, hi, 0, 0),
            ),
        ] + [hbm] * n_pass,
        scratch_shapes=scratch_shapes,
    )

    out_shape = [jax.ShapeDtypeStruct(
        (b, num_kv_heads, group_pad, head_dim), q.dtype)]
    operands = [page_table, kv_lens, layer_arr, qg, k_data, v_data]
    if quantized:
        operands += [k_scale, v_scale]
    if has_layer:
        out_shape += [
            jax.ShapeDtypeStruct(k_data.shape, k_data.dtype),
            jax.ShapeDtypeStruct(v_data.shape, v_data.dtype),
        ]
        if quantized:
            out_shape += [
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ]
    # Inputs count scalar-prefetch operands: (page_table, kv_lens,
    # layer, q, k, v[, ks, vs]) -> cache operands starting at 4 alias
    # outputs starting at 1. Only the stacked (engine) form aliases:
    # 4D callers keep using their caches afterwards, and aliasing a
    # still-live value would force the copy it exists to avoid.
    aliases = ({4 + i: 1 + i for i in range(n_cache_in)}
               if has_layer else {})
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    out = res[0][:, :, :group].reshape(b, num_q_heads, head_dim)
    if has_layer:
        if quantized:
            return (out,
                    QuantKV(res[1], res[3].reshape(scale_shape)),
                    QuantKV(res[2], res[4].reshape(scale_shape)))
        return out, res[1], res[2]
    return out
