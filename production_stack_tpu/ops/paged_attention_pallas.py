"""Pallas TPU kernel: paged decode attention.

The decode hot loop reads a sequence's KV pages from HBM and attends a
single query token against them. The XLA reference implementation
(ops/attention.py) gathers the *whole* padded context per step; this
kernel instead walks the page list with flash-style online softmax:

- grid (batch, kv_head, pages): page blocks are DMA'd HBM->VMEM one at
  a time, selected by the scalar-prefetched page table (the Pallas
  BlockSpec index_map does the "paging" — no materialized gather),
- all matmuls are plain 2D ``[G, D] x [P, D]`` contractions (the MXU
  form Mosaic supports; batched dot_generals with unequal batch dims
  do not compile), with the query-head group padded to >=8 sublanes,
- running (max, denom, acc) in VMEM scratch across the page walk,
- pages past the sequence length are masked (they DMA the trash page
  0, which the allocator never hands out, so the reads are harmless).

Contract matches ops.attention.paged_attention at T=1; the parity test
(tests/test_pallas_attention.py) checks the two against each other.

Replaces: vLLM's paged_attention CUDA kernels (external to the
reference repo), re-thought for TPU's DMA+VMEM model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Minimum sublane count for the query-group axis: fp32 tiles are
# (8, 128), so G < 8 would force degenerate layouts.
_MIN_GROUP = 8


def _decode_kernel(page_table_ref, kv_lens_ref, q_ref, k_ref, v_ref,
                   o_ref, m_ref, l_ref, acc_ref, *, page_size: int):
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_page_steps = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)  # [G, D]
    k = k_ref[0, 0].astype(jnp.float32)  # [P, D]
    v = v_ref[0, 0].astype(jnp.float32)  # [P, D]
    head_dim = q.shape[-1]

    scale = 1.0 / (head_dim ** 0.5)
    # scores: [G, P] — a single 2D MXU contraction over head_dim.
    scores = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    kv_len = kv_lens_ref[b]
    token_pos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1
    )
    scores = jnp.where(token_pos < kv_len, scores, NEG_INF)

    # Online softmax update.
    m_prev = m_ref[...]                                   # [G, 1]
    m_new = jnp.maximum(
        m_prev, jnp.max(scores, axis=-1, keepdims=True)
    )
    alpha = jnp.exp(m_prev - m_new)                       # [G, 1]
    probs = jnp.exp(scores - m_new)                       # [G, P]
    l_ref[...] = l_ref[...] * alpha + jnp.sum(
        probs, axis=-1, keepdims=True
    )
    # pv: [G, D] — second 2D MXU contraction over the page axis.
    pv = jax.lax.dot_general(
        probs, v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = m_new

    @pl.when(p == num_page_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                           v_cache_layer: jnp.ndarray,
                           page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           interpret: bool = False) -> jnp.ndarray:
    """Single-token paged attention.

    Args:
      q:           [B, num_q_heads, head_dim]
      k/v_cache_layer: [num_kv_heads, num_pages, page_size, head_dim]
      page_table:  [B, max_pages] int32 physical page ids
      kv_lens:     [B] int32 valid cached tokens per sequence
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, num_q_heads, head_dim].
    """
    b, num_q_heads, head_dim = q.shape
    num_kv_heads, _, page_size, _ = k_cache_layer.shape
    max_pages = page_table.shape[1]
    group = num_q_heads // num_kv_heads
    group_pad = max(group, _MIN_GROUP)

    # [B, KV, G, D] with the group axis padded up to a full sublane
    # tile; padded rows attend to real keys and are sliced off below.
    qg = q.reshape(b, num_kv_heads, group, head_dim)
    if group_pad != group:
        qg = jnp.pad(
            qg, ((0, 0), (0, 0), (0, group_pad - group), (0, 0))
        )

    kernel = functools.partial(_decode_kernel, page_size=page_size)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, kv_lens
        grid=(b, num_kv_heads, max_pages),
        in_specs=[
            # q block: one sequence's query group for one kv head.
            pl.BlockSpec(
                (1, 1, group_pad, head_dim),
                lambda bi, hi, pi, pt, kl: (bi, hi, 0, 0),
            ),
            # k/v block: ONE physical page of ONE kv head, chosen via
            # the scalar-prefetched page table. The head-major cache
            # layout keeps the sliced dims major so the (page, head_dim)
            # minor dims stay full tiles.
            pl.BlockSpec(
                (1, 1, page_size, head_dim),
                lambda bi, hi, pi, pt, kl: (hi, pt[bi, pi], 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, page_size, head_dim),
                lambda bi, hi, pi, pt, kl: (hi, pt[bi, pi], 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group_pad, head_dim),
            lambda bi, hi, pi, pt, kl: (bi, hi, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((group_pad, 1), jnp.float32),  # m
            pltpu.VMEM((group_pad, 1), jnp.float32),  # l
            pltpu.VMEM((group_pad, head_dim), jnp.float32),  # acc
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (b, num_kv_heads, group_pad, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table, kv_lens, qg, k_cache_layer, v_cache_layer)
    return out[:, :, :group].reshape(b, num_q_heads, head_dim)
