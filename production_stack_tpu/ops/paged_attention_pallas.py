"""Pallas TPU kernel: paged decode attention.

The decode hot loop reads a sequence's KV pages from HBM and attends a
single query token against them. The XLA reference implementation
(ops/attention.py) gathers the *whole* padded context per step; this
kernel instead walks the page list with flash-style online softmax:

- grid (batch, pages): page blocks are DMA'd HBM->VMEM one at a time,
  selected by the scalar-prefetched page table (the Pallas BlockSpec
  index_map does the "paging" — no materialized gather),
- running (max, denom, acc) in VMEM scratch across the page walk,
- pages past the sequence length are masked (they DMA the trash page
  0, which the allocator never hands out, so the reads are harmless).

Contract matches ops.attention.paged_attention at T=1; the parity test
(tests/test_pallas_attention.py) checks the two against each other.

Replaces: vLLM's paged_attention CUDA kernels (external to the
reference repo), re-thought for TPU's DMA+VMEM model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(page_table_ref, kv_lens_ref, q_ref, k_ref, v_ref,
                   o_ref, m_ref, l_ref, acc_ref, *, page_size: int,
                   num_kv_heads: int, group: int):
    p = pl.program_id(1)
    num_page_steps = pl.num_programs(1)
    b = pl.program_id(0)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # q: [H, D] viewed as [KV, G, D]
    q = q_ref[0].astype(jnp.float32)
    head_dim = q.shape[-1]
    qg = q.reshape(num_kv_heads, group, head_dim)
    k = k_ref[0].astype(jnp.float32)  # [page, KV, D]
    v = v_ref[0].astype(jnp.float32)

    scale = 1.0 / (head_dim ** 0.5)
    # scores: [KV, G, page]
    scores = jax.lax.dot_general(
        qg, k,
        dimension_numbers=(((2,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    ) * scale

    kv_len = kv_lens_ref[b]
    token_pos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, page_size), 2
    )
    scores = jnp.where(token_pos < kv_len, scores, NEG_INF)

    # Online softmax update.
    m_prev = m_ref[:]  # [KV, G]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    probs = jnp.exp(scores - m_new[..., None])  # [KV, G, page]
    l_ref[:] = l_ref[:] * alpha + jnp.sum(probs, axis=-1)
    # pv: [KV, G, D]
    pv = jax.lax.dot_general(
        probs, v,
        dimension_numbers=(((2,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] = acc_ref[:] * alpha[..., None] + pv
    m_ref[:] = m_new

    @pl.when(p == num_page_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:], 1e-30)[..., None]
        out = (acc_ref[:] / denom).reshape(
            num_kv_heads * group, head_dim
        )
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                           v_cache_layer: jnp.ndarray,
                           page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           interpret: bool = False) -> jnp.ndarray:
    """Single-token paged attention.

    Args:
      q:           [B, num_q_heads, head_dim]
      k/v_cache_layer: [num_pages, page_size, num_kv_heads, head_dim]
      page_table:  [B, max_pages] int32 physical page ids
      kv_lens:     [B] int32 valid cached tokens per sequence
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, num_q_heads, head_dim].
    """
    b, num_q_heads, head_dim = q.shape
    _, page_size, num_kv_heads, _ = k_cache_layer.shape
    max_pages = page_table.shape[1]
    group = num_q_heads // num_kv_heads

    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        num_kv_heads=num_kv_heads,
        group=group,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, kv_lens
        grid=(b, max_pages),
        in_specs=[
            # q block: one sequence's heads.
            pl.BlockSpec(
                (1, num_q_heads, head_dim),
                lambda bi, pi, pt, kl: (bi, 0, 0),
            ),
            # k/v block: ONE physical page, chosen via the page table.
            pl.BlockSpec(
                (1, page_size, num_kv_heads, head_dim),
                lambda bi, pi, pt, kl: (pt[bi, pi], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page_size, num_kv_heads, head_dim),
                lambda bi, pi, pt, kl: (pt[bi, pi], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, num_q_heads, head_dim),
            lambda bi, pi, pt, kl: (bi, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv_heads, group), jnp.float32),  # m
            pltpu.VMEM((num_kv_heads, group), jnp.float32),  # l
            pltpu.VMEM((num_kv_heads, group, head_dim),
                       jnp.float32),  # acc
        ],
    )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (b, num_q_heads, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table, kv_lens, q, k_cache_layer, v_cache_layer)
