"""Pallas TPU kernel: paged decode attention.

The decode hot loop reads a sequence's KV pages from HBM and attends a
single query token against them. The XLA reference implementation
(ops/attention.py) gathers the *whole* padded context per step; this
kernel walks the page list instead.

Design (v2 — round 3): the first cut put the page walk in the *grid*
(one tiny BlockSpec DMA per page), which bottlenecked on per-grid-step
overhead: batch x kv_heads x max_pages steps each moving a 2 KB block
made the kernel ~10x slower than the XLA gather on-chip. This version
keeps the whole page walk *inside* one kernel instance:

- grid is just (batch, kv_head) — 64 steps for a B=8, 8-head model,
- the KV cache stays in HBM (``memory_space=HBM``); the kernel issues
  manual double-buffered async DMAs (pltpu.make_async_copy) for a
  *chunk* of pages at a time, overlapping copy-in with compute,
- pages are stored token-minor ([head_dim, page_size]) so one page's
  slice is (sublane, lane)-tile-aligned for DMA — head_dim is rarely
  a lane multiple (64 on 1B-class llamas) and a token-major page
  would need its minor dim padded to 128, which Mosaic rejects for
  HBM slicing — and K arrives pre-transposed for the ``q @ k^T`` MXU
  contraction,
- the page loop is a dynamic ``fori_loop`` bounded by the sequence's
  real ``kv_len`` — work scales with the context actually cached, not
  with the page-table width,
- flash-style online softmax carried across chunks,
- matmuls are 2D ``[G, D] x [D, C*P]`` / ``[G, C*P] x [D, C*P]^T``
  contractions (the MXU forms Mosaic supports), with the query-head
  group padded to >=8 sublanes.

Pages past the sequence length DMA the trash page 0 (the allocator
never hands it out) and are masked; the page-table width is padded to
a multiple of the chunk so page indices never run off the row.

Contract matches ops.attention.paged_attention at T=1; parity is
tested in tests/test_pallas_attention.py (interpret mode) and compiled
lowering in tests/test_pallas_lowering.py.

Replaces: vLLM's paged_attention CUDA kernels (external to the
reference repo; provisioned via its Helm chart
helm/templates/deployment-vllm-multi.yaml), re-thought for TPU's
DMA+VMEM model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Minimum sublane count for the query-group axis: fp32 tiles are
# (8, 128), so G < 8 would force degenerate layouts.
_MIN_GROUP = 8

# Pages copied per DMA burst: 4 x 128-token pages = a 512-token KV
# tile per compute step (4 lane tiles per scores matmul).
_PAGES_PER_CHUNK = 4


def _decode_kernel(page_table_ref, kv_lens_ref, q_ref, k_hbm, v_hbm,
                   o_ref, k_scratch, v_scratch, sem, *,
                   page_size: int, pages_per_chunk: int,
                   group_pad: int, head_dim: int, max_pages: int):
    b = pl.program_id(0)
    h = pl.program_id(1)
    c = pages_per_chunk
    chunk_tokens = c * page_size

    kv_len = kv_lens_ref[b]
    num_chunks = (kv_len + chunk_tokens - 1) // chunk_tokens

    def dma(slot, chunk_idx, j):
        """DMA page j of chunk chunk_idx into buffer ``slot``.

        Scratch is laid out [slot, d, c*P]: each page lands in its own
        128-aligned lane window, so after ``c`` copies the buffer IS
        the [D, chunk_tokens] K/V tile — no in-VMEM reshuffle.
        """
        page_idx = jnp.minimum(chunk_idx * c + j, max_pages - 1)
        pid = page_table_ref[b, page_idx]
        return (
            pltpu.make_async_copy(
                k_hbm.at[h, pid],
                k_scratch.at[slot, :, pl.ds(j * page_size, page_size)],
                sem.at[0, slot, j],
            ),
            pltpu.make_async_copy(
                v_hbm.at[h, pid],
                v_scratch.at[slot, :, pl.ds(j * page_size, page_size)],
                sem.at[1, slot, j],
            ),
        )

    def issue(slot, chunk_idx):
        for j in range(c):
            dk, dv = dma(slot, chunk_idx, j)
            dk.start()
            dv.start()

    # Padded batch rows have kv_len == 0 -> num_chunks == 0: the loop
    # never runs, so nothing may be issued either — an unwaited DMA
    # leaks its semaphore signal into the next grid step's waits.
    @pl.when(num_chunks > 0)
    def _warmup():
        issue(0, 0)

    q = q_ref[0, 0].astype(jnp.float32)  # [G_pad, D]
    scale = 1.0 / (head_dim ** 0.5)

    def chunk_step(chunk_idx, carry):
        m_prev, l_prev, acc = carry
        slot = jax.lax.rem(chunk_idx, 2)

        @pl.when(chunk_idx + 1 < num_chunks)
        def _prefetch():
            issue(1 - slot, chunk_idx + 1)

        for j in range(c):
            dk, dv = dma(slot, chunk_idx, j)
            dk.wait()
            dv.wait()

        k = k_scratch[slot].astype(jnp.float32)  # [D, C*P]
        v = v_scratch[slot].astype(jnp.float32)  # [D, C*P]
        scores = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G_pad, C*P]

        token_pos = chunk_idx * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        scores = jnp.where(token_pos < kv_len, scores, NEG_INF)

        m_new = jnp.maximum(
            m_prev, jnp.max(scores, axis=-1, keepdims=True)
        )
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new)
        l_new = l_prev * alpha + jnp.sum(probs, axis=-1, keepdims=True)
        # pv: [G_pad, D] — contract the token axis of both operands.
        pv = jax.lax.dot_general(
            probs, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((group_pad, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((group_pad, 1), jnp.float32)
    acc0 = jnp.zeros((group_pad, head_dim), jnp.float32)
    _, l, acc = jax.lax.fori_loop(
        0, num_chunks, chunk_step, (m0, l0, acc0)
    )
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                           v_cache_layer: jnp.ndarray,
                           page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           interpret: bool = False) -> jnp.ndarray:
    """Single-token paged attention.

    Args:
      q:           [B, num_q_heads, head_dim]
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size]
      page_table:  [B, max_pages] int32 physical page ids
      kv_lens:     [B] int32 valid cached tokens per sequence
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, num_q_heads, head_dim].
    """
    b, num_q_heads, head_dim = q.shape
    num_kv_heads, _, _, page_size = k_cache_layer.shape
    group = num_q_heads // num_kv_heads
    group_pad = max(group, _MIN_GROUP)
    c = _PAGES_PER_CHUNK

    # Pad the page-table width to a chunk multiple so the DMA loop's
    # page indices stay in range (padded entries are clamped + masked).
    max_pages = page_table.shape[1]
    if max_pages % c:
        page_table = jnp.pad(
            page_table, ((0, 0), (0, c - max_pages % c))
        )
        max_pages = page_table.shape[1]

    # [B, KV, G, D] with the group axis padded up to a full sublane
    # tile; padded rows attend to real keys and are sliced off below.
    qg = q.reshape(b, num_kv_heads, group, head_dim)
    if group_pad != group:
        qg = jnp.pad(
            qg, ((0, 0), (0, 0), (0, group_pad - group), (0, 0))
        )

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, pages_per_chunk=c,
        group_pad=group_pad, head_dim=head_dim, max_pages=max_pages,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, kv_lens
        grid=(b, num_kv_heads),
        in_specs=[
            # q block: one sequence's query group for one kv head.
            pl.BlockSpec(
                (1, 1, group_pad, head_dim),
                lambda bi, hi, pt, kl: (bi, hi, 0, 0),
            ),
            # Full KV cache stays in HBM; the kernel DMAs pages itself.
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group_pad, head_dim),
            lambda bi, hi, pt, kl: (bi, hi, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, head_dim, c * page_size),
                       k_cache_layer.dtype),
            pltpu.VMEM((2, head_dim, c * page_size),
                       v_cache_layer.dtype),
            pltpu.SemaphoreType.DMA((2, 2, c)),
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (b, num_kv_heads, group_pad, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table, kv_lens, qg, k_cache_layer, v_cache_layer)
    return out[:, :, :group].reshape(b, num_q_heads, head_dim)
