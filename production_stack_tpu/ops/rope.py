"""Rotary position embeddings (RoPE), the Llama flavor.

Implemented as a pure function of positions so it works identically for
packed prefill chunks and scattered decode batches (no precomputed cache
table needed; XLA fuses the sin/cos into the surrounding matmuls).
"""

import jax.numpy as jnp


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               theta: float = 10000.0) -> jnp.ndarray:
    """Rotate q or k.

    Args:
      x: [..., seq, heads, head_dim]
      positions: [..., seq] absolute token positions
      theta: rope base frequency
    """
    head_dim = x.shape[-1]
    half = head_dim // 2
    freq_exponents = jnp.arange(half, dtype=jnp.float32) / half
    timescale = theta ** freq_exponents  # [half]
    angles = positions[..., None].astype(jnp.float32) / timescale  # [...,seq,half]
    angles = angles[..., None, :]  # broadcast over heads: [..., seq, 1, half]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)
