"""Pallas TPU kernel: fused ragged attention for the unified step.

The unified ragged step (docs/unified_step.md) runs genuinely mixed
batches — decode rows, speculative-verify rows, and prefill-chunk rows
— through ONE fixed-shape [R, W] program. Before this kernel that
program *composed* the T>1 prefill attention path (or the XLA gather)
per layer; this module is the fused form: one grid, one page walk, and
the per-row raggedness rebuilt in-kernel from the step's three-int row
descriptors, scalar-prefetched through SMEM:

- ``kv_lens[r]``   — valid cached tokens after this step's KV write
  (0 marks a pad row),
- ``last_index[r]`` — the row's last live query slot (a decode row's
  is its draft count; a prefill chunk's is chunk_len - 1),
- ``draft_lens[r]`` — how many of the trailing live slots are
  speculative drafts (the sampler's scoring span is
  ``[last_index - draft_lens, last_index]``).

The engine's layout invariant (model_runner.run_unified) makes the
row's first query position recoverable as ``q_start = kv_len - 1 -
last_index`` for every row kind, so the mask is three terms over a
[rows, C*P] absolute-position tile:

    slot <= last_index          (live query slots only — pad slots
                                 past a chunk's real length score
                                 nothing instead of garbage)
    token_pos <= q_start + slot (causal; a decode row degenerates to
                                 the 1-query case, and a verify row's
                                 draft span masks itself: draft KV is
                                 written at positions < kv_len and
                                 each draft query's window ends at its
                                 own position, so no extra span term
                                 is needed — draft_lens still rides
                                 the prefetch tuple so the descriptor
                                 contract reaches SMEM whole and a
                                 future span-local mask (e.g. tree
                                 drafts) is an in-kernel change, not
                                 an operand change)
    token_pos < kv_len          (nothing past the cached context)

Pad rows (``kv_lens == 0`` → zero page chunks) issue no DMAs and run
no compute via ``pl.when`` — an unwaited DMA would leak its semaphore
signal into the next grid step's waits.

Everything else is the shared paged-KV machine (ops/paged_kv_common):
grid (row, kv_head), double-buffered HBM→VMEM page-burst DMA with the
int8 dequant scales streamed through the same pipeline (one kernel
serves bf16 AND QuantKV caches), flash-style online softmax in VMEM
scratch, and query/output blocks padded to true (8, 128) tile
multiples (the small-head fix — see prefill_attention_pallas).

Contract matches ops.attention.paged_attention over the live slots;
parity (pure-decode / pure-prefill / mixed / verify spans / pad rows /
int8) is pinned in tests/test_pallas_attention.py and TPU
cross-lowering in tests/test_pallas_lowering.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from production_stack_tpu.ops.paged_kv_common import (
    LANE_TILE,
    NEG_INF,
    SUBLANE_TILE,
    cache_alias_map,
    dma_semaphore_shapes,
    hbm_block_spec,
    kv_scratch_shapes,
    make_page_dma,
    pad_page_table,
    pad_query_rows,
    passthrough_out_shapes,
    rewrap_cache_outputs,
    run_page_walk,
    tile_pad,
    unwrap_cache,
    validate_layer_arg,
    zero_pad_sublanes,
)

# Pages per DMA burst — same trade as the prefill kernel: ragged
# scores are [G*W_pad, tile], so a fatter KV tile costs VMEM
# quadratically while the MXU is already saturated.
_PAGES_PER_CHUNK = 2


def _ragged_kernel(page_table_ref, kv_lens_ref, last_index_ref,
                   draft_lens_ref, layer_ref, q_ref,
                   k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref,
                   m_ref, l_ref, acc_ref,
                   k_scratch, v_scratch, ks_scratch, vs_scratch,
                   sem, ssem, *,
                   page_size: int, pages_per_chunk: int, width: int,
                   head_dim: int, head_dim_pad: int, rows_pad: int,
                   max_pages: int, has_layer: bool, quantized: bool):
    b = pl.program_id(0)
    h = pl.program_id(1)
    c = pages_per_chunk
    chunk_tokens = c * page_size
    max_chunks = max_pages // c  # static unroll bound

    kv_len = kv_lens_ref[b]
    last_index = last_index_ref[b]
    # The causal rebuild needs only (kv_len, last_index); the draft
    # span is self-masking (module docstring). Prefetched regardless:
    # the descriptor tuple reaches SMEM whole.
    del draft_lens_ref
    q_start = kv_len - 1 - last_index
    num_chunks = (kv_len + chunk_tokens - 1) // chunk_tokens

    issue, wait = make_page_dma(
        b=b, h=h, page_table_ref=page_table_ref, layer_ref=layer_ref,
        k_hbm=k_hbm, v_hbm=v_hbm, ks_hbm=ks_hbm, vs_hbm=vs_hbm,
        k_scratch=k_scratch, v_scratch=v_scratch,
        ks_scratch=ks_scratch, vs_scratch=vs_scratch,
        sem=sem, ssem=ssem, pages_per_chunk=c, page_size=page_size,
        has_layer=has_layer, quantized=quantized,
        dma_sublanes=(head_dim if head_dim_pad != head_dim else None),
    )

    # Pad rows (kv_len == 0 -> num_chunks == 0) issue no DMAs and run
    # no compute: the walk below skips every chunk, and an unwaited
    # warmup DMA would leak its semaphore signal into the next grid
    # step's waits.
    @pl.when(num_chunks > 0)
    def _warmup():
        issue(0, 0)

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    zero_pad_sublanes(k_scratch, v_scratch, head_dim, head_dim_pad)

    q = q_ref[0, 0].astype(jnp.float32)  # [rows_pad, D_pad]

    # Row r of the flattened queries is (g, slot) = (r // W, r % W);
    # its absolute position is q_start + slot for every row kind (the
    # engine's layout invariant — module docstring).
    slot = jax.lax.broadcasted_iota(
        jnp.int32, (rows_pad, chunk_tokens), 0
    ) % width
    q_pos = q_start + slot
    live = slot <= last_index

    run_page_walk(
        q=q, kv_len=kv_len, num_chunks=num_chunks,
        max_chunks=max_chunks, chunk_tokens=chunk_tokens,
        head_dim=head_dim, issue=issue, wait=wait,
        k_scratch=k_scratch, v_scratch=v_scratch,
        ks_scratch=ks_scratch, vs_scratch=vs_scratch,
        m_ref=m_ref, l_ref=l_ref, acc_ref=acc_ref,
        mask_fn=lambda token_pos: (live & (token_pos <= q_pos)
                                   & (token_pos < kv_len)),
        quantized=quantized,
    )

    # Dead slots (past last_index) saw only fully-masked tiles, so
    # their accumulator holds exp(0)-weighted garbage — write zeros
    # instead (the documented contract; pad rows already land here
    # with acc == 0). One column of the slot iota is the per-row mask.
    live_col = live[:, :1]
    denom = jnp.maximum(l_ref[...], 1e-30)
    o_ref[0, 0] = jnp.where(
        live_col, acc_ref[...] / denom, 0.0).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_ragged_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                           v_cache_layer: jnp.ndarray,
                           page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           last_index: jnp.ndarray,
                           draft_lens: "jnp.ndarray | None" = None,
                           layer: "jnp.ndarray | int | None" = None,
                           interpret: bool = False) -> jnp.ndarray:
    """Fused ragged attention over the unified step's [R, W] block.

    Args:
      q:           [R, W, num_q_heads, head_dim] ragged query block
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size],
                   or the full stacked [L, ...] cache with ``layer``
                   given (scalar; reaches the kernel via SMEM prefetch
                   so no per-layer slice is ever materialized)
      page_table:  [R, max_pages] int32 physical page ids
      kv_lens:     [R] int32 valid cached tokens incl. this step's
                   write; 0 marks a pad row (no DMAs, no compute)
      last_index:  [R] int32 last live query slot of the row
      draft_lens:  [R] int32 speculative-draft count (None -> zeros;
                   attention is invariant to it — the draft span is
                   causally self-masking — but callers holding the
                   full descriptor tuple pass it through unchanged)
      interpret:   run in interpreter mode (CPU testing)

    Returns [R, W, num_q_heads, head_dim] for the 4D per-layer cache
    form; ``(out, k_cache, v_cache)`` for the stacked 5D form (caches
    pass through the kernel aliased — see paged_decode_attention).
    Slots past a row's ``last_index`` are fully masked (zero output),
    unlike the XLA path's garbage-attention pad slots — both are
    discarded by the sampler's span gather.
    """
    has_layer = validate_layer_arg(k_cache_layer, layer)
    (quantized, k_data, v_data,
     k_scale, v_scale, scale_shape) = unwrap_cache(
        k_cache_layer, v_cache_layer)
    layer_arr = jnp.asarray(
        [0 if layer is None else layer], jnp.int32)
    if draft_lens is None:
        draft_lens = jnp.zeros_like(kv_lens)
    r, w, num_q_heads, head_dim = q.shape
    num_kv_heads, _, _, page_size = k_data.shape[-4:]
    group = num_q_heads // num_kv_heads
    c = _PAGES_PER_CHUNK

    page_table, max_pages = pad_page_table(page_table, c)

    # [R, W, KV, G, D] -> [R, KV, G*W, D] rows of one kv head's
    # queries, then tile-padded (small-head fix: Mosaic's machine-code
    # pass wants true (8, 128) multiples in the q/o blocks).
    rows = group * w
    rows_pad = max(tile_pad(rows, SUBLANE_TILE), SUBLANE_TILE)
    d_pad = tile_pad(head_dim, LANE_TILE)
    qg = (q.reshape(r, w, num_kv_heads, group, head_dim)
          .transpose(0, 2, 3, 1, 4)
          .reshape(r, num_kv_heads, rows, head_dim))
    qg = pad_query_rows(qg, rows_pad, d_pad)

    base_kernel = functools.partial(
        _ragged_kernel, page_size=page_size, pages_per_chunk=c,
        width=w, head_dim=head_dim, head_dim_pad=d_pad,
        rows_pad=rows_pad, max_pages=max_pages,
        has_layer=has_layer, quantized=quantized,
    )
    n_cache_in = 4 if quantized else 2
    n_pass = n_cache_in if has_layer else 0

    def kernel(pt, kl, li, dl, la, q_ref, *refs):
        cache_in = refs[:n_cache_in]
        o_ref = refs[n_cache_in]
        scratch = refs[n_cache_in + 1 + n_pass:]
        if quantized:
            k, v, ks, vs = cache_in
            (m, l, acc, k_s, v_s, ks_s, vs_s, sem, ssem) = scratch
        else:
            k, v = cache_in
            ks = vs = ks_s = vs_s = ssem = None
            (m, l, acc, k_s, v_s, sem) = scratch
        base_kernel(pt, kl, li, dl, la, q_ref, k, v, ks, vs, o_ref,
                    m, l, acc, k_s, v_s, ks_s, vs_s, sem, ssem)

    hbm = hbm_block_spec()
    scratch_shapes = [
        pltpu.VMEM((rows_pad, 1), jnp.float32),  # m
        pltpu.VMEM((rows_pad, 1), jnp.float32),  # l
        pltpu.VMEM((rows_pad, d_pad), jnp.float32),  # acc
    ]
    scratch_shapes += kv_scratch_shapes(
        d_pad, c, page_size, k_data.dtype, v_data.dtype, quantized)
    scratch_shapes += dma_semaphore_shapes(c, quantized)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        # page_table, kv_lens, last_index, draft_lens, layer
        num_scalar_prefetch=5,
        grid=(r, num_kv_heads),
        in_specs=[
            pl.BlockSpec(
                (1, 1, rows_pad, d_pad),
                lambda bi, hi, pt, kl, li, dl, la: (bi, hi, 0, 0),
            ),
        ] + [hbm] * n_cache_in,
        out_specs=[
            pl.BlockSpec(
                (1, 1, rows_pad, d_pad),
                lambda bi, hi, pt, kl, li, dl, la: (bi, hi, 0, 0),
            ),
        ] + [hbm] * n_pass,
        scratch_shapes=scratch_shapes,
    )

    out_shape = [jax.ShapeDtypeStruct(
        (r, num_kv_heads, rows_pad, d_pad), q.dtype)]
    operands = [page_table, kv_lens, last_index, draft_lens,
                layer_arr, qg, k_data, v_data]
    if quantized:
        operands += [k_scale, v_scale]
    if has_layer:
        out_shape += passthrough_out_shapes(
            k_data, v_data, k_scale, v_scale, quantized)
    aliases = cache_alias_map(5, n_cache_in, has_layer)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    out = (res[0][:, :, :rows, :head_dim]
           .reshape(r, num_kv_heads, group, w, head_dim)
           .transpose(0, 3, 1, 2, 4)
           .reshape(r, w, num_q_heads, head_dim))
    if has_layer:
        kc, vc = rewrap_cache_outputs(res, scale_shape, quantized)
        return out, kc, vc
    return out
