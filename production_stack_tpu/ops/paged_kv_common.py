"""Shared grid/index-map + DMA layer for the paged-KV Pallas kernels.

The prefill (ops/prefill_attention_pallas.py) and decode
(ops/paged_attention_pallas.py) kernels are the same machine with a
different query block: grid (batch, kv_head), the whole page walk
inside one kernel instance as a static unroll, KV pages double-buffer
DMA'd from HBM in bursts of C token-minor pages, int8 dequant scales
streamed alongside as (1, page_size) tiles, flash-style online
softmax in VMEM scratch. Historically each kernel carried its own
copy of that machinery; this module is the single definition both
import (the unified ragged step rides the same layer — see
docs/unified_step.md). Kernel-specific remains only the query layout
and the score mask.

Everything here is either called at trace time from inside a
pallas_call kernel body (the closures built by ``make_page_dma`` /
``run_page_walk``) or at wrapper level before the call (operand
unwrap/pad helpers); nothing allocates device memory itself.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from production_stack_tpu.ops.quant_kv import QuantKV

try:  # jax >= 0.5 spelling
    _HBM = pltpu.MemorySpace.HBM
except AttributeError:  # jax 0.4.x: ANY keeps the operand un-blocked in HBM
    _HBM = pltpu.TPUMemorySpace.ANY

HBM = _HBM
NEG_INF = -1e30

# Mosaic's VMEM tile for 32-bit (and the floor for narrower) types:
# a block's last two dims must each be a multiple of these or equal to
# the whole array dim — and the *backend* (machine-code) pass is
# stricter than the Python lowering rules about the "or equal" escape
# hatch for the query/output blocks (BENCH_r02: head_dim=64 block
# shapes lowered fine cross-platform and then failed on the chip).
# The query-side kernels therefore pad to true tile multiples.
SUBLANE_TILE = 8
LANE_TILE = 128


def tile_pad(n: int, tile: int) -> int:
    """Round ``n`` up to a multiple of ``tile``."""
    return -(-n // tile) * tile


def hbm_block_spec():
    """A BlockSpec that keeps the operand un-blocked in HBM (the
    kernel DMAs pages itself)."""
    return pl.BlockSpec(memory_space=_HBM)


# ---- wrapper-level operand helpers -------------------------------------


def validate_layer_arg(k_cache_layer, layer) -> bool:
    """Check the stacked-cache/layer-index contract shared by every
    paged kernel wrapper; returns ``has_layer``."""
    has_layer = k_cache_layer.ndim == 5
    if has_layer != (layer is not None):
        raise ValueError(
            "layer index and cache rank must agree: pass a stacked "
            "[L, ...] cache WITH layer, or a per-layer [kv, ...] "
            f"cache WITHOUT (got ndim={k_cache_layer.ndim}, "
            f"layer={layer!r})")
    return has_layer


def unwrap_cache(k_cache_layer, v_cache_layer):
    """Split a possibly-quantized cache operand pair into DMA-able
    arrays.

    Returns (quantized, k_data, v_data, k_scale, v_scale,
    scale_shape). For an int8 QuantKV cache the [.., pages, ps]
    scales are reshaped to [.., pages, 1, ps] so each page's scale
    row DMAs as the same 2-D (sublane, lane) tile shape as the data
    pages (pure bitcast — the last axis is contiguous either way);
    ``scale_shape`` is the original shape for re-wrapping outputs.
    For a full-precision cache the scale slots are None.
    """
    if isinstance(k_cache_layer, QuantKV):
        scale_shape = k_cache_layer.scale.shape
        sshape = scale_shape[:-1] + (1, scale_shape[-1])
        return (True, k_cache_layer.data, v_cache_layer.data,
                k_cache_layer.scale.reshape(sshape),
                v_cache_layer.scale.reshape(sshape), scale_shape)
    return False, k_cache_layer, v_cache_layer, None, None, None


def pad_page_table(page_table: jnp.ndarray, pages_per_chunk: int):
    """Pad the page-table width to a chunk multiple so the DMA loop's
    static unroll (max_pages // c chunks) never indexes off the row;
    padded entries point at the trash page and are masked. Returns
    (page_table, max_pages)."""
    max_pages = page_table.shape[1]
    if max_pages % pages_per_chunk:
        page_table = jnp.pad(
            page_table,
            ((0, 0), (0, pages_per_chunk - max_pages % pages_per_chunk)),
        )
        max_pages = page_table.shape[1]
    return page_table, max_pages


def pad_query_rows(qg: jnp.ndarray, rows_pad: int, d_pad: int):
    """Zero-pad a [B, KV, rows, D] flattened query block to the Mosaic
    tile-aligned [B, KV, rows_pad, d_pad] the kernels take. Zero pad
    lanes contribute nothing to the q·k contraction (0 × anything
    accumulates 0 once the matching k-scratch sublanes are zeroed —
    ``zero_pad_sublanes``), and pad rows are sliced back off the
    output by the wrapper."""
    b, kv, rows, d = qg.shape
    if rows_pad == rows and d_pad == d:
        return qg
    return jnp.pad(qg, ((0, 0), (0, 0),
                        (0, rows_pad - rows), (0, d_pad - d)))


def zero_pad_sublanes(k_scratch, v_scratch, head_dim: int,
                      head_dim_pad: int) -> None:
    """Zero the KV scratch sublanes past ``head_dim`` once per kernel
    instance (both DMA slots, both sides). The page DMAs only ever
    fill ``[:head_dim]``, and uninitialized VMEM can hold NaNs —
    0 (pad q lane) × NaN (pad k sublane) would poison the scores
    accumulator. ``head_dim`` is a sublane multiple (the page tile's
    own layout requires it), so the slice is tile-legal."""
    if head_dim_pad == head_dim:
        return
    pad = head_dim_pad - head_dim
    width = k_scratch.shape[-1]
    for side in (k_scratch, v_scratch):
        for slot in range(2):
            side[slot, pl.ds(head_dim, pad), :] = jnp.zeros(
                (pad, width), side.dtype)


def kv_scratch_shapes(head_dim: int, pages_per_chunk: int,
                      page_size: int, k_dtype, v_dtype,
                      quantized: bool):
    """Double-buffered KV (+ int8 scale) VMEM scratch: [slot, d, C*P]
    per side — each page lands in its own 128-aligned lane window, so
    after C copies the buffer IS the [D, chunk_tokens] tile."""
    shapes = [
        pltpu.VMEM((2, head_dim, pages_per_chunk * page_size), k_dtype),
        pltpu.VMEM((2, head_dim, pages_per_chunk * page_size), v_dtype),
    ]
    if quantized:
        shapes += [
            pltpu.VMEM((2, 1, pages_per_chunk * page_size), jnp.float32),
            pltpu.VMEM((2, 1, pages_per_chunk * page_size), jnp.float32),
        ]
    return shapes


def dma_semaphore_shapes(pages_per_chunk: int, quantized: bool):
    """[kv side, slot, page-in-chunk] DMA semaphores, one extra set
    for the scale streams of a quantized cache."""
    shapes = [pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk))]
    if quantized:
        shapes += [pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk))]
    return shapes


def cache_alias_map(num_scalar_prefetch: int, n_cache_in: int,
                    has_layer: bool):
    """Input/output alias map threading the stacked cache THROUGH the
    custom call: cache operands follow the scalar-prefetch operands
    and the query, outputs follow the attention output. Only the
    stacked (engine) form aliases — 4D callers keep using their
    caches afterwards, and aliasing a still-live value would force
    the copy aliasing exists to avoid."""
    if not has_layer:
        return {}
    base = num_scalar_prefetch + 1  # prefetch scalars + q
    return {base + i: 1 + i for i in range(n_cache_in)}


def passthrough_out_shapes(k_data, v_data, k_scale, v_scale,
                           quantized: bool):
    """ShapeDtypeStructs for the aliased cache pass-through outputs
    (stacked form only; the kernel never touches them)."""
    shapes = [
        jax.ShapeDtypeStruct(k_data.shape, k_data.dtype),
        jax.ShapeDtypeStruct(v_data.shape, v_data.dtype),
    ]
    if quantized:
        shapes += [
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
    return shapes


def rewrap_cache_outputs(res, scale_shape, quantized: bool):
    """Re-wrap the stacked form's pass-through cache outputs (res[1:])
    for the caller's thread-the-cache contract."""
    if quantized:
        return (QuantKV(res[1], res[3].reshape(scale_shape)),
                QuantKV(res[2], res[4].reshape(scale_shape)))
    return res[1], res[2]


# ---- in-kernel page-walk machinery -------------------------------------


def make_page_dma(*, b, h, page_table_ref, layer_ref,
                  k_hbm, v_hbm, ks_hbm, vs_hbm,
                  k_scratch, v_scratch, ks_scratch, vs_scratch,
                  sem, ssem, pages_per_chunk: int, page_size: int,
                  has_layer: bool, quantized: bool,
                  dma_sublanes: "int | None" = None):
    """Build the (issue, wait) pair for the double-buffered page-burst
    DMA shared by every paged kernel.

    ``issue(slot, chunk_idx)`` starts the async copies of chunk
    ``chunk_idx``'s C pages (K, V and — for an int8 cache — their
    dequant scale rows) into buffer ``slot``; ``wait(slot,
    chunk_idx)`` blocks on the same set. With a stacked [L, ...]
    cache the layer index arrives as a prefetched scalar, so ONE
    compiled kernel serves every layer and the caller never slices
    (an HLO slice feeding a pallas custom-call materializes the
    whole 10s-of-MB layer as a copy).

    ``dma_sublanes`` bounds the destination's sublane window when the
    KV scratch is padded past the page tile's head_dim (small-head
    fix: the scratch is lane/sublane tile-padded while the HBM pages
    keep their real [head_dim, page_size] shape).
    """
    c = pages_per_chunk

    def dst(scratch, slot, j):
        win = pl.ds(j * page_size, page_size)
        if dma_sublanes is None:
            return scratch.at[slot, :, win]
        return scratch.at[slot, pl.ds(0, dma_sublanes), win]

    def dma(slot, chunk_idx, j):
        pid = page_table_ref[b, chunk_idx * c + j]
        if has_layer:
            k_src = k_hbm.at[layer_ref[0], h, pid]
            v_src = v_hbm.at[layer_ref[0], h, pid]
        else:
            k_src = k_hbm.at[h, pid]
            v_src = v_hbm.at[h, pid]
        copies = [
            pltpu.make_async_copy(
                k_src, dst(k_scratch, slot, j), sem.at[0, slot, j],
            ),
            pltpu.make_async_copy(
                v_src, dst(v_scratch, slot, j), sem.at[1, slot, j],
            ),
        ]
        if quantized:
            if has_layer:
                ks_src = ks_hbm.at[layer_ref[0], h, pid]
                vs_src = vs_hbm.at[layer_ref[0], h, pid]
            else:
                ks_src = ks_hbm.at[h, pid]
                vs_src = vs_hbm.at[h, pid]
            copies += [
                pltpu.make_async_copy(
                    ks_src,
                    ks_scratch.at[
                        slot, :, pl.ds(j * page_size, page_size)],
                    ssem.at[0, slot, j],
                ),
                pltpu.make_async_copy(
                    vs_src,
                    vs_scratch.at[
                        slot, :, pl.ds(j * page_size, page_size)],
                    ssem.at[1, slot, j],
                ),
            ]
        return copies

    def issue(slot, chunk_idx):
        for j in range(c):
            for cp in dma(slot, chunk_idx, j):
                cp.start()

    def wait(slot, chunk_idx):
        for j in range(c):
            for cp in dma(slot, chunk_idx, j):
                cp.wait()

    return issue, wait


def run_page_walk(*, q, kv_len, num_chunks, max_chunks: int,
                  chunk_tokens: int, head_dim: int,
                  issue, wait,
                  k_scratch, v_scratch, ks_scratch, vs_scratch,
                  m_ref, l_ref, acc_ref, mask_fn, quantized: bool):
    """The shared flash-attention page walk: a STATIC unroll over the
    page-table width with ``pl.when`` guards on the row's real chunk
    count — skipped chunks issue no DMAs and run no compute, so work
    scales with the context actually cached. (A dynamic ``fori_loop``
    bound would be tighter code, but dynamic trip counts + DMA
    semaphores push Mosaic down a rarely-exercised path — observed
    hanging the AOT compiler on v5e — while the static unroll is the
    standard public-Pallas shape.)

    ``q`` is the [rows, D] f32 query block; ``mask_fn(token_pos)``
    returns the validity mask for a [rows, C*P] absolute-token-
    position tile (decode: ``pos < kv_len``; prefill/ragged adds the
    causal ``pos <= q_pos`` term). Caller issues the warmup DMA for
    chunk 0 (guarded on ``num_chunks > 0`` — padded rows must issue
    nothing: an unwaited DMA leaks its semaphore signal into the
    next grid step's waits) and normalizes acc/l at the end.
    """
    del kv_len  # masking is mask_fn's job; kept for signature clarity
    scale = 1.0 / (head_dim ** 0.5)

    for chunk_idx in range(max_chunks):
        @pl.when(chunk_idx < num_chunks)
        def _chunk(chunk_idx=chunk_idx):
            slot = chunk_idx % 2

            @pl.when(chunk_idx + 1 < num_chunks)
            def _prefetch():
                issue(1 - slot, chunk_idx + 1)

            wait(slot, chunk_idx)

            k = k_scratch[slot].astype(jnp.float32)  # [D, C*P]
            v = v_scratch[slot].astype(jnp.float32)  # [D, C*P]
            scores = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [rows, C*P]
            if quantized:
                # Fold the k dequant scales into the logits: exact,
                # since each scale is constant along the contracted
                # head_dim axis. [1, C*P] broadcasts over the rows.
                scores = scores * ks_scratch[slot]

            token_pos = (chunk_idx * chunk_tokens
                         + jax.lax.broadcasted_iota(
                             jnp.int32, scores.shape, 1))
            scores = jnp.where(mask_fn(token_pos), scores, NEG_INF)

            m_prev = m_ref[...]
            m_new = jnp.maximum(
                m_prev, jnp.max(scores, axis=-1, keepdims=True)
            )
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(scores - m_new)
            l_ref[...] = l_ref[...] * alpha + jnp.sum(
                probs, axis=-1, keepdims=True
            )
            if quantized:
                # v dequant folds into the probabilities before the
                # pv contraction (per-token scales, constant along d).
                probs = probs * vs_scratch[slot]
            pv = jax.lax.dot_general(
                probs, v,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rows, D]
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = m_new
