"""Token sampling, fully vectorized in-graph (no host round-trip of
logits): temperature, top-k, top-p and greedy, per-slot parameters so one
decode batch mixes sampling configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_penalties(logits: jnp.ndarray, counts: jnp.ndarray,
                    prompt_mask: jnp.ndarray,
                    presence: jnp.ndarray, frequency: jnp.ndarray,
                    repetition: jnp.ndarray) -> jnp.ndarray:
    """Sampling penalties, vectorized per row.

    OpenAI semantics for presence/frequency (over tokens *generated*
    so far) and vLLM/HF semantics for repetition (over prompt +
    generated: positive logits divided by r, negative multiplied).

    Args:
      logits:      [B, vocab] f32
      counts:      [B, vocab] int32 occurrences in the OUTPUT so far
      prompt_mask: [B, vocab] bool, True where the token appears in
                   the prompt
      presence/frequency: [B] f32 (0 disables)
      repetition:  [B] f32 (1 disables)

    Returns penalized [B, vocab] logits.
    """
    countsf = counts.astype(logits.dtype)
    seen_out = countsf > 0
    # Repetition applies FIRST, on the raw logits (vLLM/HF order);
    # presence/frequency subtract from the result.
    seen_any = seen_out | prompt_mask
    rep = repetition[:, None]
    repeated = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen_any, repeated, logits)
    logits = logits - presence[:, None] * seen_out.astype(logits.dtype)
    return logits - frequency[:, None] * countsf


def token_logprobs(logits: jnp.ndarray, sampled: jnp.ndarray,
                   k: int):
    """Logprob of each sampled token + the top-k alternatives.

    Computed from the UNMODIFIED model distribution (before
    temperature/penalties), the OpenAI ``logprobs`` contract.

    Args:
      logits:  [B, vocab] f32 raw logits
      sampled: [B] int32 sampled token ids
      k:       static top-k width (>= 1)

    Returns (sampled_logprob [B], top_ids [B, k], top_logprobs [B, k]).
    """
    lp = jax.nn.log_softmax(logits, axis=-1)
    sampled_lp = jnp.take_along_axis(
        lp, sampled[:, None].astype(jnp.int32), axis=1)[:, 0]
    top_lp, top_ids = jax.lax.top_k(lp, k)
    return sampled_lp, top_ids.astype(jnp.int32), top_lp


def _mask_top_k_top_p(scaled: jnp.ndarray, top_p: jnp.ndarray,
                      top_k: jnp.ndarray) -> jnp.ndarray:
    """NEG_INF-mask every logit outside its row's top-k/top-p set.

    Shared by ``sample_tokens`` and ``spec_verify`` so the sampling
    and speculative-verification distributions cannot drift.

    Args:
      scaled: [B, vocab] temperature-scaled logits
      top_p:  [B] (1.0 => disabled)
      top_k:  [B] int32 (0 => disabled)
    """
    b, vocab = scaled.shape
    # Rank of each logit within its row (0 = largest).
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)

    # top-k: keep ranks < k (k==0 disables).
    ranks = jnp.arange(vocab)[None, :]
    k = jnp.where(top_k > 0, top_k, vocab)
    topk_mask = ranks < k[:, None]

    # top-p: keep the smallest prefix with cumulative prob >=
    # top_p, always including the most likely token.
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(sorted_probs, axis=-1)
    topp_mask = (cumprobs - sorted_probs) < top_p[:, None]

    keep_sorted = topk_mask & topp_mask
    masked_sorted = jnp.where(keep_sorted, sorted_logits, NEG_INF)
    # Scatter the mask back to vocab order.
    return jnp.zeros_like(scaled).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(masked_sorted)


def sample_tokens(logits: jnp.ndarray, temperature: jnp.ndarray,
                  top_p: jnp.ndarray, top_k: jnp.ndarray,
                  key: jax.Array,
                  seeds: "jnp.ndarray | None" = None,
                  emitted: "jnp.ndarray | None" = None,
                  seed_mask: "jnp.ndarray | None" = None) -> jnp.ndarray:
    """Sample one token per row.

    Args:
      logits:      [B, vocab] float32
      temperature: [B] (0 => greedy)
      top_p:       [B] (1.0 => disabled)
      top_k:       [B] int32 (0 => disabled)
      key:         PRNG key (the engine's stream; used for unseeded rows)
      seeds:       optional [B] int32 per-row request seeds, carrying
                   the FULL 32-bit user seed (two's-complement
                   reinterpretation — no folding, so distinct user
                   seeds never collide). A seeded row's randomness
                   derives ONLY from (seed, emitted-token index), so
                   identical seeded requests reproduce identical
                   samples regardless of batch composition or engine
                   history.
      emitted:     [B] int32 tokens generated so far per row (required
                   with ``seeds``)
      seed_mask:   [B] bool — True where the row is seeded. Required
                   with ``seeds``: the seed value itself cannot gate
                   seededness without surrendering a bit of seed space.

    Returns [B] int32 token ids.
    """
    b, vocab = logits.shape
    greedy_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]

    def categorical(masked):
        if seeds is None:
            return jax.random.categorical(key, masked, axis=-1)
        # Per-row keys (legacy uint32[2] key form, what the engine's
        # PRNGKey stream uses): unseeded rows fold the row index into
        # the engine key; seeded rows rebuild their key from
        # (seed, emitted index) only.
        if seed_mask is None:
            # Seeds carry full 32-bit values: the sign bit is seed
            # payload, NOT an unseeded marker, so there is no valid
            # way to gate without the mask (a >= 0 fallback would
            # silently drop seeding for half the seed space).
            raise ValueError(
                "sample_tokens: seeds requires seed_mask")
        row_keys = jax.vmap(
            lambda i: jax.random.fold_in(key, i))(jnp.arange(b))
        seeded_keys = jax.vmap(
            lambda s, e: jax.random.fold_in(
                jax.random.PRNGKey(s.astype(jnp.uint32)), e)
        )(seeds, emitted)
        keys = jnp.where(seed_mask[:, None], seeded_keys, row_keys)
        return jax.vmap(jax.random.categorical)(keys, masked)

    def masked_sample():
        return categorical(_mask_top_k_top_p(scaled, top_p, top_k))

    def plain_sample():
        # No top-k/top-p anywhere in the batch: skip the vocab sort.
        return categorical(scaled)

    def sample_path():
        needs_mask = jnp.any((top_k > 0) | (top_p < 1.0))
        return jax.lax.cond(
            needs_mask, masked_sample, plain_sample
        ).astype(jnp.int32)

    # Runtime fast path: an all-greedy batch (the common serving case
    # at temperature 0) never executes the sort/softmax at all.
    any_stochastic = jnp.any(temperature > 0)
    sampled = jax.lax.cond(
        any_stochastic, sample_path, lambda: greedy_tokens
    )
    return jnp.where(temperature > 0, sampled, greedy_tokens).astype(
        jnp.int32
    )


def spec_verify(logits: jnp.ndarray, drafts: jnp.ndarray,
                draft_lens: jnp.ndarray, temperature: jnp.ndarray,
                top_p: jnp.ndarray, top_k: jnp.ndarray,
                key: jax.Array) -> jnp.ndarray:
    """Vectorized speculative-decoding acceptance rule.

    One verify forward pass scored S = K+1 positions per row: the
    row's last committed token followed by its K draft tokens (padded
    with invalid slots). ``logits[:, j]`` is the target model's
    distribution for the token at offset j past the committed length.

    Acceptance (Leviathan et al. rejection sampling with a
    deterministic point-mass proposal — the n-gram draft):
      * greedy rows (temperature 0): draft j is accepted iff it equals
        the raw-logits argmax at offset j — the emitted stream is
        byte-identical to non-speculative greedy decode.
      * stochastic rows: draft j is accepted with probability
        p_j(d_j) under the row's FULL sampling distribution
        (temperature + top-k/top-p via the same mask as
        ``sample_tokens``); on rejection the replacement is drawn from
        the residual distribution (the draft token masked out), which
        leaves the output distribution exactly the target model's.
    Acceptance stops at the first rejection; the row always emits one
    token beyond its accepted prefix (the resample, or the bonus token
    when every draft was accepted), so progress is >= 1 token/step.

    Args:
      logits:      [B, S, vocab] raw logits
      drafts:      [B, S-1] int32 draft tokens, -1 padded
      draft_lens:  [B] int32 in [0, S-1]; 0 = plain decode row
      temperature: [B] (0 => greedy)
      top_p:       [B] (1.0 => disabled)
      top_k:       [B] int32 (0 => disabled)
      key:         PRNG key for acceptance draws + residual samples

    Returns [B, S] int32: row i's emitted tokens in its first
    ``accepted_i + 1`` slots, -1 beyond.
    """
    b, s, vocab = logits.shape
    pos = jnp.arange(s)[None, :]
    in_draft = pos[:, :-1] < draft_lens[:, None]  # [B, S-1]
    dsafe = jnp.clip(drafts, 0)
    stochastic = temperature > 0  # [B]

    # Residual removal mask: at offset j the (rejected) draft token is
    # excluded from the replacement draw. Greedy rows share it — a
    # rejected draft is by definition not the argmax, so removal never
    # changes the greedy winner; the padded final column (bonus
    # position) removes nothing.
    remove = (jax.nn.one_hot(dsafe, vocab, dtype=bool)
              & in_draft[..., None])
    remove = jnp.pad(remove, ((0, 0), (0, 1), (0, 0)))  # [B, S, V]

    greedy_targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    greedy_final = jnp.argmax(
        jnp.where(remove, NEG_INF, logits), axis=-1).astype(jnp.int32)
    accept_greedy = (drafts == greedy_targets[:, :-1]) & in_draft

    def greedy_only():
        # All-greedy batch (the common serving case): two argmaxes,
        # no softmax/sort/randomness — mirrors sample_tokens' fast
        # path.
        return accept_greedy, greedy_final

    def with_stochastic():
        safe_temp = jnp.where(stochastic, temperature, 1.0)
        scaled = (logits / safe_temp[:, None, None]).reshape(
            b * s, vocab)
        masked = _mask_top_k_top_p(
            scaled, jnp.repeat(top_p, s), jnp.repeat(top_k, s)
        ).reshape(b, s, vocab)
        probs = jax.nn.softmax(masked, axis=-1)
        p_draft = jnp.take_along_axis(
            probs[:, :-1], dsafe[..., None], axis=-1)[..., 0]
        key_u, key_r = jax.random.split(key)
        u = jax.random.uniform(key_u, (b, s - 1))
        accept_st = u < p_draft
        accept = jnp.where(stochastic[:, None], accept_st,
                           accept_greedy[:, :] | False)
        # Residual (and bonus) draw at every offset; only the offset
        # at the first rejection / past the accepted prefix is used.
        resampled = jax.random.categorical(
            key_r,
            jnp.where(remove, NEG_INF, masked).reshape(b * s, vocab),
            axis=-1).reshape(b, s).astype(jnp.int32)
        final = jnp.where(stochastic[:, None], resampled,
                          greedy_final)
        return accept & in_draft, final

    accept, final = jax.lax.cond(jnp.any(stochastic),
                                 with_stochastic, greedy_only)
    # Accepted prefix length: drafts accept left-to-right until the
    # first rejection.
    a = jnp.cumprod(accept.astype(jnp.int32), axis=-1).sum(axis=-1)
    drafts_padded = jnp.pad(drafts, ((0, 0), (0, 1)))
    return jnp.where(
        pos < a[:, None], drafts_padded,
        jnp.where(pos == a[:, None], final, -1)).astype(jnp.int32)
