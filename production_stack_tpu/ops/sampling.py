"""Token sampling, fully vectorized in-graph (no host round-trip of
logits): temperature, top-k, top-p and greedy, per-slot parameters so one
decode batch mixes sampling configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_penalties(logits: jnp.ndarray, counts: jnp.ndarray,
                    prompt_mask: jnp.ndarray,
                    presence: jnp.ndarray, frequency: jnp.ndarray,
                    repetition: jnp.ndarray) -> jnp.ndarray:
    """Sampling penalties, vectorized per row.

    OpenAI semantics for presence/frequency (over tokens *generated*
    so far) and vLLM/HF semantics for repetition (over prompt +
    generated: positive logits divided by r, negative multiplied).

    Args:
      logits:      [B, vocab] f32
      counts:      [B, vocab] int32 occurrences in the OUTPUT so far
      prompt_mask: [B, vocab] bool, True where the token appears in
                   the prompt
      presence/frequency: [B] f32 (0 disables)
      repetition:  [B] f32 (1 disables)

    Returns penalized [B, vocab] logits.
    """
    countsf = counts.astype(logits.dtype)
    seen_out = countsf > 0
    # Repetition applies FIRST, on the raw logits (vLLM/HF order);
    # presence/frequency subtract from the result.
    seen_any = seen_out | prompt_mask
    rep = repetition[:, None]
    repeated = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen_any, repeated, logits)
    logits = logits - presence[:, None] * seen_out.astype(logits.dtype)
    return logits - frequency[:, None] * countsf


def token_logprobs(logits: jnp.ndarray, sampled: jnp.ndarray,
                   k: int):
    """Logprob of each sampled token + the top-k alternatives.

    Computed from the UNMODIFIED model distribution (before
    temperature/penalties), the OpenAI ``logprobs`` contract.

    Args:
      logits:  [B, vocab] f32 raw logits
      sampled: [B] int32 sampled token ids
      k:       static top-k width (>= 1)

    Returns (sampled_logprob [B], top_ids [B, k], top_logprobs [B, k]).
    """
    lp = jax.nn.log_softmax(logits, axis=-1)
    sampled_lp = jnp.take_along_axis(
        lp, sampled[:, None].astype(jnp.int32), axis=1)[:, 0]
    top_lp, top_ids = jax.lax.top_k(lp, k)
    return sampled_lp, top_ids.astype(jnp.int32), top_lp


def sample_tokens(logits: jnp.ndarray, temperature: jnp.ndarray,
                  top_p: jnp.ndarray, top_k: jnp.ndarray,
                  key: jax.Array,
                  seeds: "jnp.ndarray | None" = None,
                  emitted: "jnp.ndarray | None" = None,
                  seed_mask: "jnp.ndarray | None" = None) -> jnp.ndarray:
    """Sample one token per row.

    Args:
      logits:      [B, vocab] float32
      temperature: [B] (0 => greedy)
      top_p:       [B] (1.0 => disabled)
      top_k:       [B] int32 (0 => disabled)
      key:         PRNG key (the engine's stream; used for unseeded rows)
      seeds:       optional [B] int32 per-row request seeds, carrying
                   the FULL 32-bit user seed (two's-complement
                   reinterpretation — no folding, so distinct user
                   seeds never collide). A seeded row's randomness
                   derives ONLY from (seed, emitted-token index), so
                   identical seeded requests reproduce identical
                   samples regardless of batch composition or engine
                   history.
      emitted:     [B] int32 tokens generated so far per row (required
                   with ``seeds``)
      seed_mask:   [B] bool — True where the row is seeded. Required
                   with ``seeds``: the seed value itself cannot gate
                   seededness without surrendering a bit of seed space.

    Returns [B] int32 token ids.
    """
    b, vocab = logits.shape
    greedy_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]

    def categorical(masked):
        if seeds is None:
            return jax.random.categorical(key, masked, axis=-1)
        # Per-row keys (legacy uint32[2] key form, what the engine's
        # PRNGKey stream uses): unseeded rows fold the row index into
        # the engine key; seeded rows rebuild their key from
        # (seed, emitted index) only.
        if seed_mask is None:
            # Seeds carry full 32-bit values: the sign bit is seed
            # payload, NOT an unseeded marker, so there is no valid
            # way to gate without the mask (a >= 0 fallback would
            # silently drop seeding for half the seed space).
            raise ValueError(
                "sample_tokens: seeds requires seed_mask")
        row_keys = jax.vmap(
            lambda i: jax.random.fold_in(key, i))(jnp.arange(b))
        seeded_keys = jax.vmap(
            lambda s, e: jax.random.fold_in(
                jax.random.PRNGKey(s.astype(jnp.uint32)), e)
        )(seeds, emitted)
        keys = jnp.where(seed_mask[:, None], seeded_keys, row_keys)
        return jax.vmap(jax.random.categorical)(keys, masked)

    def masked_sample():
        # Rank of each logit within its row (0 = largest).
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)

        # top-k: keep ranks < k (k==0 disables).
        ranks = jnp.arange(vocab)[None, :]
        k = jnp.where(top_k > 0, top_k, vocab)
        topk_mask = ranks < k[:, None]

        # top-p: keep the smallest prefix with cumulative prob >=
        # top_p, always including the most likely token.
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(sorted_probs, axis=-1)
        topp_mask = (cumprobs - sorted_probs) < top_p[:, None]

        keep_sorted = topk_mask & topp_mask
        masked_sorted = jnp.where(keep_sorted, sorted_logits, NEG_INF)
        # Scatter the mask back to vocab order.
        masked = jnp.zeros_like(scaled).at[
            jnp.arange(b)[:, None], sort_idx
        ].set(masked_sorted)
        return categorical(masked)

    def plain_sample():
        # No top-k/top-p anywhere in the batch: skip the vocab sort.
        return categorical(scaled)

    def sample_path():
        needs_mask = jnp.any((top_k > 0) | (top_p < 1.0))
        return jax.lax.cond(
            needs_mask, masked_sample, plain_sample
        ).astype(jnp.int32)

    # Runtime fast path: an all-greedy batch (the common serving case
    # at temperature 0) never executes the sort/softmax at all.
    any_stochastic = jnp.any(temperature > 0)
    sampled = jax.lax.cond(
        any_stochastic, sample_path, lambda: greedy_tokens
    )
    return jnp.where(temperature > 0, sampled, greedy_tokens).astype(
        jnp.int32
    )
