"""Token sampling, fully vectorized in-graph (no host round-trip of
logits): temperature, top-k, top-p and greedy, per-slot parameters so one
decode batch mixes sampling configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(logits: jnp.ndarray, temperature: jnp.ndarray,
                  top_p: jnp.ndarray, top_k: jnp.ndarray,
                  key: jax.Array) -> jnp.ndarray:
    """Sample one token per row.

    Args:
      logits:      [B, vocab] float32
      temperature: [B] (0 => greedy)
      top_p:       [B] (1.0 => disabled)
      top_k:       [B] int32 (0 => disabled)
      key:         PRNG key

    Returns [B] int32 token ids.
    """
    b, vocab = logits.shape
    greedy_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]

    def masked_sample():
        # Rank of each logit within its row (0 = largest).
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)

        # top-k: keep ranks < k (k==0 disables).
        ranks = jnp.arange(vocab)[None, :]
        k = jnp.where(top_k > 0, top_k, vocab)
        topk_mask = ranks < k[:, None]

        # top-p: keep the smallest prefix with cumulative prob >=
        # top_p, always including the most likely token.
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(sorted_probs, axis=-1)
        topp_mask = (cumprobs - sorted_probs) < top_p[:, None]

        keep_sorted = topk_mask & topp_mask
        masked_sorted = jnp.where(keep_sorted, sorted_logits, NEG_INF)
        # Scatter the mask back to vocab order.
        masked = jnp.zeros_like(scaled).at[
            jnp.arange(b)[:, None], sort_idx
        ].set(masked_sorted)
        return jax.random.categorical(key, masked, axis=-1)

    def plain_sample():
        # No top-k/top-p anywhere in the batch: skip the vocab sort.
        return jax.random.categorical(key, scaled, axis=-1)

    def sample_path():
        needs_mask = jnp.any((top_k > 0) | (top_p < 1.0))
        return jax.lax.cond(
            needs_mask, masked_sample, plain_sample
        ).astype(jnp.int32)

    # Runtime fast path: an all-greedy batch (the common serving case
    # at temperature 0) never executes the sort/softmax at all.
    any_stochastic = jnp.any(temperature > 0)
    sampled = jax.lax.cond(
        any_stochastic, sample_path, lambda: greedy_tokens
    )
    return jnp.where(temperature > 0, sampled, greedy_tokens).astype(
        jnp.int32
    )
