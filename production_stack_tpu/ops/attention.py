"""Attention over the paged KV cache — XLA reference implementation.

One unified primitive serves prefill chunks and decode steps: queries at
absolute positions attend to everything already written to their
sequence's pages, with causal masking. Decode is the T=1 special case, so
there is exactly one numerics path to test. A Pallas kernel
(ops/paged_attention_pallas.py) implements the same contract for the
decode hot loop; this module is the ground truth it is tested against.

Replaces: vLLM's PagedAttention CUDA kernels (external to the reference
repo; provisioned via helm/templates/deployment-vllm-multi.yaml engine
image) — re-designed for TPU: gather whole pages (contiguous HBM reads),
mask in-register, let XLA tile the batched matmuls onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def gather_pages(cache_layer: jnp.ndarray,
                 page_table: jnp.ndarray) -> jnp.ndarray:
    """[kv, num_pages, d, page] gathered to [B, max_pages*page, kv, d].

    Cache layout (shared with the Pallas kernels): kv-head axis major
    so TP shards a leading axis, and each page stored *token-minor*
    ([head_dim, page_size]) so a page slice's last two dims are
    (d, 128)-tile-aligned for direct HBM->VMEM DMA and arrive
    pre-transposed for the MXU's ``q @ k^T`` contraction.
    """
    gathered = cache_layer[:, page_table]  # [kv, B, P, d, page]
    kv, b, p, d, page = gathered.shape
    return (gathered.transpose(1, 2, 4, 0, 3)  # [B, P, page, kv, d]
            .reshape(b, p * page, kv, d))


def write_to_pages(cache: jnp.ndarray, new_kv: jnp.ndarray,
                   page_table: jnp.ndarray, positions: jnp.ndarray,
                   valid: jnp.ndarray,
                   layer: "int | None" = None) -> jnp.ndarray:
    """Scatter new KV entries into their pages.

    Page 0 is the engine's trash page (the allocator never hands it out),
    so padded slots write there harmlessly instead of needing predication.

    With ``layer`` (a static int), ``cache`` is the full stacked
    [L, kv_heads, num_pages, head_dim, page_size] cache and the scatter
    lands at that layer IN PLACE. Model forwards must use this form
    inside their (statically unrolled) layer loop: threading per-layer
    cache slices through ``lax.scan`` xs/ys makes XLA copy the whole
    layer cache in and out every step (~20 ms/step measured on v5e for
    a 1B config vs ~1.3 ms for the chained in-place form).

    Args:
      cache:       [kv_heads, num_pages, head_dim, page_size], or the
                   stacked [L, ...] form when ``layer`` is given
      new_kv:      [B, T, kv_heads, head_dim]
      page_table:  [B, max_pages] int32 physical page ids
      positions:   [B, T] absolute token positions
      valid:       [B, T] bool; False entries are redirected to page 0
    """
    page_size = cache.shape[-1]
    b, t = positions.shape
    logical_page = positions // page_size  # [B, T]
    offset = positions % page_size  # [B, T]
    physical_page = jnp.take_along_axis(
        page_table, logical_page, axis=1
    )  # [B, T]
    physical_page = jnp.where(valid, physical_page, 0)
    flat_pages = physical_page.reshape(-1)
    flat_offsets = offset.reshape(-1)
    # Advanced indices on the page and token-slot dims broadcast to
    # the front: the updates shape is [B*T, kv, d].
    flat_kv = new_kv.reshape(b * t, *new_kv.shape[2:])
    if layer is None:
        return cache.at[:, flat_pages, :, flat_offsets].set(flat_kv)
    return cache.at[layer, :, flat_pages, :, flat_offsets].set(flat_kv)


def paged_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                    v_cache_layer: jnp.ndarray, page_table: jnp.ndarray,
                    q_positions: jnp.ndarray,
                    kv_lens: jnp.ndarray,
                    layer: "int | None" = None) -> jnp.ndarray:
    """Causal attention of q against a sequence's cached pages.

    Args:
      q:           [B, T, num_q_heads, head_dim]
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size],
                   or the stacked [L, ...] cache when ``layer`` (a
                   static int) is given — the static slice fuses into
                   the page gather instead of materializing
      page_table:  [B, max_pages]
      q_positions: [B, T] absolute positions of the queries
      kv_lens:     [B] number of valid cached tokens (>= max position + 1)

    Returns [B, T, num_q_heads, head_dim].
    """
    if layer is not None:
        k_cache_layer = k_cache_layer[layer]
        v_cache_layer = v_cache_layer[layer]
    b, t, num_q_heads, head_dim = q.shape
    num_kv_heads = k_cache_layer.shape[0]
    group = num_q_heads // num_kv_heads
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, dtype=jnp.float32))

    k = gather_pages(k_cache_layer, page_table)  # [B, S, kv, d]
    v = gather_pages(v_cache_layer, page_table)
    s = k.shape[1]

    qg = q.reshape(b, t, num_kv_heads, group, head_dim)
    # scores: [B, kv, group, T, S]
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale

    kv_positions = jnp.arange(s)[None, :]  # [1, S]
    causal = kv_positions[:, None, :] <= q_positions[:, :, None]  # [B,T,S]
    in_len = kv_positions < kv_lens[:, None]  # [B, S]
    mask = causal & in_len[:, None, :]  # [B, T, S]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs, v.astype(jnp.float32)
    )
    return out.reshape(b, t, num_q_heads, head_dim).astype(q.dtype)
