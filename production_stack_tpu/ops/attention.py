"""Attention over the paged KV cache — XLA reference implementation.

One unified primitive serves prefill chunks and decode steps: queries at
absolute positions attend to everything already written to their
sequence's pages, with causal masking. Decode is the T=1 special case, so
there is exactly one numerics path to test. A Pallas kernel
(ops/paged_attention_pallas.py) implements the same contract for the
decode hot loop; this module is the ground truth it is tested against.

Replaces: vLLM's PagedAttention CUDA kernels (external to the reference
repo; provisioned via helm/templates/deployment-vllm-multi.yaml engine
image) — re-designed for TPU: gather whole pages (contiguous HBM reads),
mask in-register, let XLA tile the batched matmuls onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from production_stack_tpu.ops.quant_kv import QuantKV, quantize_kv

NEG_INF = -1e30

# Every attention implementation with a paged-KV read path. The
# quantized-coverage lint (tests/test_kv_parity_coverage_lint.py)
# requires a bf16-vs-int8 parity test naming each function here, so a
# new kernel cannot silently skip int8 coverage.
ATTENTION_IMPLS = {
    "xla": ("production_stack_tpu.ops.attention", "paged_attention"),
    "pallas_decode": ("production_stack_tpu.ops.paged_attention_pallas",
                      "paged_decode_attention"),
    "pallas_prefill": ("production_stack_tpu.ops.prefill_attention_pallas",
                       "paged_prefill_attention"),
    "pallas_ragged": ("production_stack_tpu.ops.ragged_attention_pallas",
                      "paged_ragged_attention"),
}


def gather_pages(cache_layer: jnp.ndarray,
                 page_table: jnp.ndarray) -> jnp.ndarray:
    """[kv, num_pages, d, page] gathered to [kv, B, max_pages, d, page].

    Cache layout (shared with the Pallas kernels): kv-head axis major
    so TP shards a leading axis, and each page stored *token-minor*
    ([head_dim, page_size]) so a page slice's last two dims are
    (d, 128)-tile-aligned for direct HBM->VMEM DMA and arrive
    pre-transposed for the MXU's ``q @ k^T`` contraction.

    The gather output keeps the cache's native axis order: an explicit
    transpose here gets hoisted by XLA's algebraic simplifier onto the
    gather *operand* — materializing a transposed copy of the ENTIRE
    cache per layer (seen in compiled HLO as [L,kv,pages,d,p]
    transposes). Consumers contract it via einsum in native order
    instead.
    """
    return cache_layer[:, page_table]  # [kv, B, P, d, page]


def write_to_pages(cache: jnp.ndarray, new_kv: jnp.ndarray,
                   page_table: jnp.ndarray, positions: jnp.ndarray,
                   valid: jnp.ndarray,
                   layer: "int | None" = None) -> jnp.ndarray:
    """Scatter new KV entries into their pages.

    Page 0 is the engine's trash page (the allocator never hands it out),
    so padded slots write there harmlessly instead of needing predication.

    With ``layer`` (a static int), ``cache`` is the full stacked
    [L, kv_heads, num_pages, head_dim, page_size] cache and the scatter
    lands at that layer IN PLACE. Model forwards must use this form
    inside their (statically unrolled) layer loop: threading per-layer
    cache slices through ``lax.scan`` xs/ys makes XLA copy the whole
    layer cache in and out every step (~20 ms/step measured on v5e for
    a 1B config vs ~1.3 ms for the chained in-place form).

    Args:
      cache:       [kv_heads, num_pages, head_dim, page_size], or the
                   stacked [L, ...] form when ``layer`` is given
      new_kv:      [B, T, kv_heads, head_dim]
      page_table:  [B, max_pages] int32 physical page ids
      positions:   [B, T] absolute token positions
      valid:       [B, T] bool; False entries are redirected to page 0
    """
    if (cache.ndim == 5) != (layer is not None):
        raise ValueError(
            "layer index and cache rank must agree: pass a stacked "
            "[L, ...] cache WITH layer, or a per-layer [kv, ...] "
            f"cache WITHOUT (got ndim={cache.ndim}, layer={layer!r})")
    page_size = cache.shape[-1]
    b, t = positions.shape
    logical_page = positions // page_size  # [B, T]
    offset = positions % page_size  # [B, T]
    physical_page = jnp.take_along_axis(
        page_table, logical_page, axis=1
    )  # [B, T]
    physical_page = jnp.where(valid, physical_page, 0)
    flat_pages = physical_page.reshape(-1)
    flat_offsets = offset.reshape(-1)
    if isinstance(cache, QuantKV):
        # Quantize-on-write: one symmetric int8 scale per (token,
        # kv_head) row lands in the scale tensor's matching page slot,
        # so incremental writes never rescale a neighbour.
        q8, kv_scale = quantize_kv(new_kv)  # [B,T,kv,d] i8 / [B,T,kv]
        flat_q8 = q8.reshape(b * t, *q8.shape[2:])
        flat_scale = kv_scale.reshape(b * t, kv_scale.shape[2])
        if layer is None:
            data = cache.data.at[:, flat_pages, :, flat_offsets].set(
                flat_q8)
            # Adjacent advanced indices (page, slot) keep the result
            # in place — updates are [kv, B*T], hence the transpose.
            scale = cache.scale.at[:, flat_pages, flat_offsets].set(
                flat_scale.T)
        else:
            data = cache.data.at[
                layer, :, flat_pages, :, flat_offsets].set(flat_q8)
            # The static layer index makes the advanced indices
            # non-adjacent again: updates broadcast to the front as
            # [B*T, kv].
            scale = cache.scale.at[
                layer, :, flat_pages, flat_offsets].set(flat_scale)
        return QuantKV(data, scale)
    # Advanced indices on the page and token-slot dims broadcast to
    # the front: the updates shape is [B*T, kv, d].
    flat_kv = new_kv.reshape(b * t, *new_kv.shape[2:])
    if layer is None:
        return cache.at[:, flat_pages, :, flat_offsets].set(flat_kv)
    return cache.at[layer, :, flat_pages, :, flat_offsets].set(flat_kv)


def write_to_tail(tail: jnp.ndarray, new_kv: jnp.ndarray,
                  slot: jnp.ndarray, active: jnp.ndarray) -> jnp.ndarray:
    """One decode token into its burst-tail slot (deferred KV write).

    The round-5 decode ablation (benchmarks/results/round5_notes.md)
    measured the per-step paged scatters at ~5.1 of 11.1 ms — for
    ~1 MB of writes. Deferred mode appends each step's K/V to a small
    dense [B, S, kv, d] tail instead (a one-hot select over S<=32
    slots — no scatter), and flushes the whole tail to the pages with
    ONE write_to_pages call per layer at burst end.

    Args:
      tail:   [B, S, kv_heads, head_dim]
      new_kv: [B, 1, kv_heads, head_dim] — this step's K or V
      slot:   [B] int32 — tail slot per row (q_pos - frozen kv_len)
      active: [B] bool — rows decoding this step; a frozen row's hit
              mask is all-False, so its tail is untouched (its stale
              slots stay masked out of attention positionally and out
              of the flush by the emitted count)
    """
    s = tail.shape[1]
    hit = (jnp.arange(s)[None, :] == slot[:, None]) & active[:, None]
    return jnp.where(hit[..., None, None], new_kv, tail)


def paged_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                    v_cache_layer: jnp.ndarray, page_table: jnp.ndarray,
                    q_positions: jnp.ndarray,
                    kv_lens: jnp.ndarray,
                    layer: "int | None" = None,
                    k_tail: "jnp.ndarray | None" = None,
                    v_tail: "jnp.ndarray | None" = None) -> jnp.ndarray:
    """Causal attention of q against a sequence's cached pages.

    Args:
      q:           [B, T, num_q_heads, head_dim]
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size],
                   or the stacked [L, ...] cache when ``layer`` (a
                   static int) is given — the static slice fuses into
                   the page gather instead of materializing
      page_table:  [B, max_pages]
      q_positions: [B, T] absolute positions of the queries
      kv_lens:     [B] number of valid cached tokens (>= max position + 1)
      k_tail/v_tail: optional [B, S, kv_heads, head_dim] deferred-write
                   burst tails holding tokens NOT yet flushed to the
                   pages: tail slot s is absolute position
                   ``kv_lens + s`` (kv_lens frozen for the burst), and
                   masking is purely positional — unwritten slots sit
                   at positions > every query and never attend.

    Returns [B, T, num_q_heads, head_dim].
    """
    if (k_cache_layer.ndim == 5) != (layer is not None):
        raise ValueError(
            "layer index and cache rank must agree: pass a stacked "
            "[L, ...] cache WITH layer, or a per-layer [kv, ...] "
            f"cache WITHOUT (got ndim={k_cache_layer.ndim}, "
            f"layer={layer!r})")
    if layer is not None:
        k_cache_layer = k_cache_layer[layer]
        v_cache_layer = v_cache_layer[layer]
    b, t, num_q_heads, head_dim = q.shape
    num_kv_heads = k_cache_layer.shape[0]
    group = num_q_heads // num_kv_heads
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, dtype=jnp.float32))

    k = gather_pages(k_cache_layer, page_table)  # [kv, B, P, d, page]
    v = gather_pages(v_cache_layer, page_table)
    quantized = isinstance(k, QuantKV)
    if quantized:
        # int8 pages: keep the matmul operands int8 (dequant BEFORE
        # the gather would materialize the whole cache in f32, the
        # same hazard as the convert-hoist note below) and fold the
        # per-slot scales in afterwards — exact, because each scale
        # varies only over non-contracted score axes. Broadcast shape
        # [B, kv, 1(group), 1(T), P, page].
        k_scale_b = k.scale.transpose(1, 0, 2, 3)[:, :, None, None]
        v_scale_b = v.scale.transpose(1, 0, 2, 3)[:, :, None, None]
        k, v = k.data, v.data
    p_cnt, page = k.shape[2], k.shape[4]

    qg = q.reshape(b, t, num_kv_heads, group, head_dim)
    # scores: [B, kv, group, T, P, page], contracted in the cache's
    # NATIVE axis order. Two deliberate choices, both HBM-traffic
    # driven (this runs once per layer per step):
    # - operands stay in the cache dtype with an f32 accumulator (the
    #   MXU's native bf16xbf16->f32 form): upcasting k/v first makes
    #   XLA hoist the convert above the page gather and materialize
    #   the ENTIRE cache in f32,
    # - no reshape/transpose of the gathered pages: an explicit
    #   transpose gets hoisted onto the gather operand as a
    #   whole-cache transposed copy (see gather_pages).
    scores = jnp.einsum(
        "btkgd,kbpdc->bkgtpc", qg, k,
        preferred_element_type=jnp.float32,
    ) * scale
    if quantized:
        scores = scores * k_scale_b  # fold k dequant into the logits

    token_pos = (jnp.arange(p_cnt)[:, None] * page
                 + jnp.arange(page)[None, :])  # [P, page]
    causal = (token_pos[None, None]
              <= q_positions[:, :, None, None])  # [B, T, P, page]
    in_len = token_pos[None] < kv_lens[:, None, None]  # [B, P, page]
    mask = causal & in_len[:, None]  # [B, T, P, page]
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)

    shape = scores.shape
    flat = scores.reshape(*shape[:-2], p_cnt * page)

    if k_tail is not None:
        # Burst tail: S un-flushed tokens at positions kv_lens + s.
        s_len = k_tail.shape[1]
        t_scores = jnp.einsum(
            "btkgd,bskd->bkgts", qg, k_tail,
            preferred_element_type=jnp.float32,
        ) * scale  # [B, kv, group, T, S]
        tail_pos = (kv_lens[:, None]
                    + jnp.arange(s_len)[None, :])  # [B, S]
        t_mask = (tail_pos[:, None, :]
                  <= q_positions[:, :, None])  # [B, T, S]
        t_scores = jnp.where(t_mask[:, None, None], t_scores, NEG_INF)
        # One softmax over the joint pages+tail token axis.
        joint = jnp.concatenate([flat, t_scores], axis=-1)
        probs = jax.nn.softmax(joint, axis=-1)
        p_pages = probs[..., :p_cnt * page].reshape(shape)
        p_tail = probs[..., p_cnt * page:]
        if quantized:
            # v dequant folds into the probabilities (f32 — casting to
            # the cache dtype would truncate to int8); the burst tail
            # itself stays full precision.
            p_pages = p_pages * v_scale_b
        else:
            p_pages = p_pages.astype(v.dtype)
        out = jnp.einsum(
            "bkgtpc,kbpdc->btkgd", p_pages, v,
            preferred_element_type=jnp.float32,
        ) + jnp.einsum(
            "bkgts,bskd->btkgd", p_tail.astype(v_tail.dtype), v_tail,
            preferred_element_type=jnp.float32,
        )
        return out.reshape(b, t, num_q_heads, head_dim).astype(q.dtype)

    # Softmax over the joint (P, page) token axis.
    probs = jax.nn.softmax(flat, axis=-1).reshape(shape)  # f32
    if quantized:
        probs = probs * v_scale_b  # fold v dequant; keep f32
    else:
        probs = probs.astype(v.dtype)
    out = jnp.einsum(
        "bkgtpc,kbpdc->btkgd", probs, v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, num_q_heads, head_dim).astype(q.dtype)
